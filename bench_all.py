"""Full benchmark sweep over the BASELINE.md measurement configs.

Writes one JSON object per config to stdout (one per line) and a summary table
to BENCHMARKS.md. ``bench.py`` remains the single-line headline driver; this
is the RMMcompare-style wider harness.

Configs (BASELINE.md) — the default sweep runs 1-5; the extras run only when
named (``python bench_all.py lu chol attn``) because they are additions beyond
the BASELINE config list:
  1. 100×100 file-based multiply (genmat data), CPU-comparable
  2. 4000×4000 dense multiply, single chip
  3. 20000×20000 dense multiply (bf16: same multiply, bf16 MXU operands)
  4. tall-skinny ×512 Gramian, host-streamed (out-of-core)
  5. sparse 10⁶×10⁶ @ 1e-4 density × dense 10⁶×256 (ELL SpMM)
  lu / chol: 8192² distributed blocked factorizations
  attn: 32768×128 causal ring attention
  pr: PageRank on a 10⁷-node / 10⁸-edge random graph (edge-list operator)
  acc: north-star multiply row-block rel-err vs host f64 oracle + precision
       kwarg plumbing proof (default bf16 vs high f32)
  als: blocked ALS, 10^6 users x 10^5 items x rank 32 x 10^7 ratings
  bsr: structured-sparsity SpMM (5% of 128x128 blocks), chunked vs pallas
  svd: top-8 SVD of 10^6 x 512 via the dist-eigs Gramian+Lanczos path
  nn: MLP training steps/s, 262k x 784 synthetic MNIST-shaped, batch 8192
  lct: long-context LM training tokens/s, 32k-token causal stream
  lct_long: the longest-sequence training run one chip holds (256k+ tokens,
       remat + chunked LM head; MARLIN_BENCH_LCT_SEQ scales it)
  attn_long: pure causal flash attention at 256k+ tokens
       (MARLIN_BENCH_ATTN_SEQ scales it)
  decode: KV-cached autoregressive decode tokens/s (prefill vs per-token)
  serve: continuous-batching engine offered-load sweep — p50/p99 latency and
       tokens/s per offered rate (MARLIN_BENCH_SERVE_* env knobs scale it)
"""

import collections
import contextlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

RESULTS = []

# Provenance stamp for every measurement taken by THIS run (round-3 verdict
# #9: an unlabeled table invites quoting stale numbers as current). The date
# is always stamped (it can never silently go stale); the round label only
# when MARLIN_BENCH_ROUND is set (the recovery runner pins it) — a hard-coded
# round here would mislabel every future round's numbers.
ROUND = os.environ.get("MARLIN_BENCH_ROUND", "")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_ALL.json")


def record(name, value, unit, detail="", extra=None):
    # 2 decimals for human-scale values; 3 significant digits below that so
    # rel-err records (~1e-6) don't round to a meaningless 0.0
    rounded = round(value, 2) if abs(value) >= 0.01 else float(f"{value:.3g}")
    stamp = f"{ROUND} {time.strftime('%Y-%m-%d')}".strip()
    entry = {"config": name, "value": rounded, "unit": unit, "detail": detail,
             "measured": stamp}
    if extra:  # ride-along fields (e.g. roofline_frac) — tools/bench_compare
        entry.update(extra)  # shows them next to the gated value
    RESULTS.append(entry)
    print(json.dumps(entry), flush=True)


def _roofline_extra(flops, nbytes, seconds):
    """{"roofline_frac": ...} for one measured program, or None where peaks
    are unknown — BENCH rounds track utilization next to throughput
    (obs/perf.py; CPU peaks are nominal placeholders, TPU peaks are the
    generation table / config overrides)."""
    from marlin_tpu.obs import perf

    pf, bw = perf.peak_rates()
    frac = perf.roofline(flops, nbytes, seconds, pf, bw)["roofline_frac"]
    return {"roofline_frac": round(frac, 4)} if frac is not None else None


def sync(x):
    import jax

    jax.block_until_ready(x)
    return jax.device_get(x.ravel()[0] if hasattr(x, "ravel") else x)


def config1():
    import marlin_tpu as mt

    tools = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    subprocess.run(["make", "-s", "-C", tools], check=True)
    with tempfile.TemporaryDirectory() as d:
        for name, seed in (("a", 1), ("b", 2)):
            with open(os.path.join(d, f"{name}.txt"), "w") as f:
                subprocess.run([os.path.join(tools, "genmat"), "100", "100", str(seed)],
                               stdout=f, check=True)
        mesh = mt.create_mesh()
        a = mt.load_matrix_file(os.path.join(d, "a.txt"), mesh)
        b = mt.load_matrix_file(os.path.join(d, "b.txt"), mesh)
        mt.evaluate(a.multiply(b))
        t0 = time.perf_counter()
        mt.evaluate(a.multiply(b))
        dt = time.perf_counter() - t0
    record("1_file_100x100", dt * 1e3, "ms", "file-loaded multiply incl. sync")


def _dense_config(n, reps, name, precision="high"):
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    a = mt.DenseVecMatrix.random(0, n, n, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, n, n, mesh=mesh)
    float(jnp.sum(a.data) + jnp.sum(b.data))
    c = a.multiply(b, precision=precision)
    float(jnp.sum(c.data))
    t0 = time.perf_counter()
    for _ in range(reps):
        c = a.multiply(b, precision=precision)
    float(jnp.sum(c.data))
    dt = (time.perf_counter() - t0) / reps
    itemsize = jnp.dtype(a.data.dtype).itemsize
    record(name, 2 * n**3 / dt / 1e9, "GFLOP/s",
           f"{dt * 1e3:.1f} ms/multiply, precision={precision}",
           extra=_roofline_extra(2.0 * n**3, 3.0 * n * n * itemsize, dt))


def config4():
    from marlin_tpu.parallel import streamed_gramian
    from marlin_tpu.utils.profiling import StageTimes

    # BASELINE names 10^7 rows; GFLOP/s is row-count invariant for this
    # streamed kernel, and the relay tunnel's H2D bandwidth makes the full
    # 20 GB pass impractical in a bench slot — stream 4M rows (8 GB).
    rows = int(os.environ.get("MARLIN_BENCH_TALL_ROWS", 4_000_000))
    cols = 512
    chunk = int(os.environ.get("MARLIN_BENCH_CHUNK_ROWS", 1 << 19))
    # MARLIN_BENCH_PREFETCH=0 forces the synchronous path (the before/after
    # control for the async prefetch pipeline); default follows config (on)
    prefetch = (False if os.environ.get("MARLIN_BENCH_PREFETCH") == "0"
                else None)
    rng = np.random.default_rng(0)

    def chunks():
        done = 0
        while done < rows:
            size = min(chunk, rows - done)
            yield rng.random((size, cols), np.float32)
            done += size

    # warm-up compile on one chunk
    streamed_gramian(iter([np.zeros((1024, cols), np.float32)]))
    stats = StageTimes()
    t0 = time.perf_counter()
    g = streamed_gramian(chunks(), chunk_rows=chunk, prefetch=prefetch,
                         stats=stats)
    dt = time.perf_counter() - t0
    assert g.shape == (cols, cols)
    # label from the RESOLVED mode: prefetch=None follows config, which may
    # itself be off — the A/B record must say what actually ran
    from marlin_tpu.config import get_config as _get_cfg

    effective = _get_cfg().prefetch_enabled if prefetch is None else prefetch
    mode = "prefetch" if effective else "sync"
    record(f"4_tall_skinny_{rows}x512_gramian_e2e",
           2 * rows * cols**2 / dt / 1e9, "GFLOP/s",
           f"{dt:.1f} s end-to-end incl. host generation + H2D transfer "
           f"[{mode}; stages: {stats.summary()}]",
           extra=_roofline_extra(2.0 * rows * cols**2,
                                 4.0 * rows * cols, dt))

    # device-compute half of the split: the same per-chunk rank-update with
    # the operand already resident, sync-amortized over reps — what the
    # kernel does once data is on chip, i.e. the number that survives off
    # this container's relay tunnel (its H2D is ~23 MB/s; production hosts
    # feed PCIe/ICI).
    import jax
    import jax.numpy as jnp
    from marlin_tpu.config import get_config

    @jax.jit
    def rank_update(acc, x):
        return acc + jnp.dot(x.T, x, precision=get_config().matmul_precision)

    x = jnp.asarray(rng.random((chunk, cols), np.float32))
    acc = jnp.zeros((cols, cols), jnp.float32)
    sync(rank_update(acc, x))  # compile + warm
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        acc = rank_update(acc, x)
    sync(acc)
    dev_dt = (time.perf_counter() - t0) / reps
    record(f"4_tall_skinny_{rows}x512_gramian_device",
           2 * chunk * cols**2 / dev_dt / 1e9, "GFLOP/s",
           f"{dev_dt * 1e3:.1f} ms per {chunk}-row rank-update, data resident")

    _config4_file_legs()


def _config4_file_legs():
    """The data-plane A/B at the config-4 shape, fed from DISK: the same
    streamed Gramian with chunks produced by (a) the Python text parser
    (``MARLIN_BENCH_NATIVE_PLANE=0`` runs only this control leg) and (b) the
    native chunkstore sidecar (``=1`` only this; unset runs both, text
    first). The gap between the legs is what marlin_tpu/io/chunkstore.py
    exists to close. Each record's detail carries the producer-stage
    breakdown (produce = parse / mcs_read+convert, transfer = device_put,
    stall = un-overlapped producer latency the consumer actually waited out,
    compute, drain — utils/profiling.StageTimes). MARLIN_BENCH_FILE_ROWS
    sizes the file (default 65536 x 512 — ~300 MB of text, tractable for
    the Python-parser control; GFLOP/s is row-count invariant here)."""
    from marlin_tpu import native
    from marlin_tpu.io.chunkstore import transcode_text
    from marlin_tpu.io.text import load_matrix_file_out_of_core
    from marlin_tpu.parallel import streamed_gramian
    from marlin_tpu.utils.profiling import StageTimes

    rows = int(os.environ.get("MARLIN_BENCH_FILE_ROWS", 65536))
    cols = 512
    chunk = min(rows, 8192)
    plane = os.environ.get("MARLIN_BENCH_NATIVE_PLANE", "")
    legs = {"0": ("text",), "1": ("native",)}.get(plane, ("text", "native"))
    tools = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    subprocess.run(["make", "-s", "-C", tools], check=True)
    gflop = 2 * rows * cols**2 / 1e9
    speeds = {}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "tall.txt")
        with open(path, "w") as f:
            subprocess.run([os.path.join(tools, "genmat"), str(rows),
                            str(cols), "7"], stdout=f, check=True)
        log(f"config4 file legs: {os.path.getsize(path) / 1e6:.0f} MB text, "
            f"legs={legs}")
        # warm the chunk programs (full + tail shapes) so neither leg pays a
        # compile inside its timed pass
        streamed_gramian(iter([np.zeros((chunk, cols), np.float64),
                               np.zeros((rows % chunk or chunk, cols),
                                        np.float64)]))
        for leg in legs:
            if leg == "native":
                # the A/B is meaningless if the "native" leg silently fell
                # back to the text parser — refuse rather than mislabel
                if not native.chunkstore_available():
                    raise RuntimeError("native chunkstore library "
                                       f"unavailable: {native.build_error()}")
                t0 = time.perf_counter()
                transcode_text(path, chunk_rows=chunk)
                build_s = time.perf_counter() - t0
                ooc = load_matrix_file_out_of_core(path, chunk_rows=chunk)
                assert "chunkstore" in repr(ooc), "sidecar not auto-selected"
                note = f"sidecar built in {build_s:.1f} s (one-time); "
            else:
                ooc = load_matrix_file_out_of_core(path, chunk_rows=chunk,
                                                   chunkstore=False)
                note = "Python text parse every pass; "
            stats = StageTimes()
            t0 = time.perf_counter()
            g = ooc.gramian(stats=stats)
            dt = time.perf_counter() - t0
            assert g.shape == (cols, cols)
            speeds[leg] = gflop / dt
            if leg == "native" and "text" in speeds:
                note += (f"{speeds['native'] / speeds['text']:.1f}x the text "
                         "plane; ")
            record(f"4_file_{rows}x512_gramian_{leg}_plane", gflop / dt,
                   "GFLOP/s", f"{dt:.1f} s end-to-end from disk "
                   f"[{note}stages: {stats.summary()}]")


def config5():
    import marlin_tpu as mt
    from marlin_tpu.ops.sparse_ell import ell_from_coo, ell_spmm

    m = n = 1_000_000
    density, p = 1e-4, 256
    nnz = int(m * n * density)
    rng = np.random.default_rng(0)
    log(f"building ELL with {nnz:.0f} nnz...")
    rows = rng.integers(0, m, nnz, dtype=np.int64)
    cols = rng.integers(0, n, nnz, dtype=np.int64)
    vals = rng.random(nnz, dtype=np.float32)
    t0 = time.perf_counter()
    ell = ell_from_coo(rows, cols, vals, (m, n))
    log(f"ELL built in {time.perf_counter() - t0:.1f}s, K={ell.k_width}")
    b = rng.random((n, p), dtype=np.float32)
    import jax.numpy as jnp

    b_dev = jnp.asarray(b)
    out = ell_spmm(ell, b_dev)
    sync(out)
    t0 = time.perf_counter()
    out = ell_spmm(ell, b_dev)
    sync(out)
    dt = time.perf_counter() - t0
    record("5_spmm_1e6_1e-4_x256", 2 * nnz * p / dt / 1e9, "GFLOP/s",
           f"{dt * 1e3:.0f} ms, ELL K={ell.k_width}")


def config_lu(n=8192):
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    base = mt.BlockMatrix.random(0, n, n, mesh=mesh)
    a = base.add(mt.BlockMatrix.from_array(float(n) * np.eye(n, dtype=np.float32), mesh))
    float(jnp.sum(a.data))
    reps = 3  # amortize the relay sync round-trip
    # block pivot = the reference's strategy; the extra masked+panel leg
    # quantifies what LAPACK-style full-height panel pivoting costs on top
    legs = (("masked", "block"), ("shrinking", "block"),
            ("masked", "panel"))  # panel pivoting keeps the masked loop
    for sched, piv in legs:
        l, u, p = a.lu_decompose(mode="dist", schedule=sched, pivot=piv)
        float(jnp.sum(l.data) + jnp.sum(u.data))  # compile + materialize
        t0 = time.perf_counter()
        for _ in range(reps):
            l, u, p = a.lu_decompose(mode="dist", schedule=sched, pivot=piv)
        float(jnp.sum(l.data) + jnp.sum(u.data))
        dt = (time.perf_counter() - t0) / reps
        tag = sched if piv == "block" else f"{sched}_panelpivot"
        record(f"lu_dist_{n}_{tag}", (2 / 3) * n**3 / dt / 1e9, "GFLOP/s",
               f"{dt:.2f} s, pivot={piv}")


def config_cholesky(n=8192):
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    r = mt.BlockMatrix.random(0, n, n, mesh=mesh)
    a = r.multiply(r.transpose(), precision="high").add(
        mt.BlockMatrix.from_array(float(n) * np.eye(n, dtype=np.float32), mesh)
    )
    float(jnp.sum(a.data))
    reps = 3
    for sched in ("masked", "shrinking"):
        l = a.cholesky_decompose(mode="dist", schedule=sched)
        float(jnp.sum(l.data))
        t0 = time.perf_counter()
        for _ in range(reps):
            l = a.cholesky_decompose(mode="dist", schedule=sched)
        float(jnp.sum(l.data))
        dt = (time.perf_counter() - t0) / reps
        record(f"cholesky_dist_{n}_{sched}", (1 / 3) * n**3 / dt / 1e9,
               "GFLOP/s", f"{dt:.2f} s")


def config_attention(seq=32768, d=128, variants=None, reps=10):
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((seq, d)).astype(np.float32))
               for _ in range(3))
    flops = 2.0 * seq * seq * d  # causal: qk^T + pv, halved by the mask
    # reps amortize the relay's ~60 ms sync round-trip out of the figure
    for backend, prec in variants or (("xla", "high"), ("flash", "high"),
                                      ("flash", "default")):
        out = mt.ring_attention(q, k, v, mesh, causal=True, backend=backend,
                                precision=prec)
        float(jnp.sum(out))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = mt.ring_attention(q, k, v, mesh, causal=True,
                                    backend=backend, precision=prec)
        float(jnp.sum(out))
        dt = (time.perf_counter() - t0) / reps
        tag = backend if prec == "high" else f"{backend}_bf16"
        record(f"ring_attention_{seq}x{d}_{tag}", flops / dt / 1e9,
               "GFLOP/s", f"{dt * 1e3:.0f} ms causal")


def config_pagerank(n=10_000_000, e=100_000_000, iterations=10):
    from marlin_tpu.ml import build_transition_operator, pagerank

    rng = np.random.default_rng(0)
    edges = np.empty((e, 2), np.int64)
    edges[:, 0] = rng.integers(0, n, e)
    edges[:, 1] = rng.integers(0, n, e)
    op = build_transition_operator(edges, n=n)
    del edges
    r = pagerank(op, iterations=1)  # compile + H2D transfer
    t0 = time.perf_counter()
    r = pagerank(op, iterations=iterations)
    dt = time.perf_counter() - t0
    assert abs(float(r.sum()) - 1.0) < 1e-3
    record(f"pagerank_{n}n_{e}e", dt / iterations * 1e3, "ms/iter",
           f"{dt:.2f} s for {iterations} iters, edges resident on chip")


def config_bsr(grid=256, bs=128, p=256, block_density=0.05):
    """Structured-sparsity SpMM: (grid·bs)² matrix holding ``block_density``
    of its bs×bs blocks, times a dense (n, p) panel — chunked-einsum vs the
    scatter-free Pallas kernel."""
    import jax.numpy as jnp

    from marlin_tpu.ops.sparse_bsr import BsrMatrix, bsr_spmm, bsr_spmm_pallas

    rng = np.random.default_rng(0)
    n = grid * bs
    nnzb = max(1, int(grid * grid * block_density))
    ids = np.sort(rng.choice(grid * grid, nnzb, replace=False))
    blocks = rng.standard_normal((nnzb, bs, bs)).astype(np.float32)
    bsr = BsrMatrix(jnp.asarray(blocks),
                    jnp.asarray(ids // grid, jnp.int32),
                    jnp.asarray(ids % grid, jnp.int32), (n, n), bs)
    b = jnp.asarray(rng.standard_normal((n, p)).astype(np.float32))
    flops = 2.0 * nnzb * bs * bs * p
    # the Pallas leg runs the Mosaic kernel on TPU; in interpret mode (CPU)
    # it is minutes per call at this scale — a debugging path, not a
    # measurement — so it defaults off unless a real TPU backend is up
    import jax as _jax

    run_pallas = os.environ.get(
        "MARLIN_BENCH_BSR_PALLAS",
        "1" if _jax.default_backend() == "tpu" else "0") != "0"
    legs = [("chunked", lambda: bsr_spmm(bsr, b))]
    if run_pallas:
        legs.append(("pallas", lambda: bsr_spmm_pallas(bsr, b)))
    else:
        log("bsr pallas leg skipped (interpret mode; "
            "MARLIN_BENCH_BSR_PALLAS=1 forces)")
    for name, fn in legs:
        out = fn()
        float(jnp.sum(out))
        t0 = time.perf_counter()
        for _ in range(5):
            out = fn()
        float(jnp.sum(out))
        dt = (time.perf_counter() - t0) / 5
        record(f"bsr_{n}x{n}_bd{block_density}_{name}", flops / dt / 1e9,
               "GFLOP/s", f"{dt * 1e3:.1f} ms, nnzb={nnzb}, bs={bs}, p={p}")

    # the generated-family record: the autotune ranking over chunked-chunk
    # variants + the Pallas kernel picks the dispatch winner (what
    # backend="auto" will run); the record shows the winner's rate and the
    # full measured ordering. Off-TPU the interpret-mode kernel is excluded
    # for the same reason as above (explicit candidate lists don't pin the
    # dispatch cache — the record is a measurement, not a winner override).
    from marlin_tpu.ops import tile_family
    from marlin_tpu.parallel import autotune

    cands = None
    if not run_pallas:
        cands = [c for c in tile_family.bsr_candidates(
            bs, bsr.nnzb, p, 4) if c != "pallas"]
    ranking = autotune.tune_bsr(bsr, b, candidates=cands, reps=2)
    win, sec = ranking[0]
    order = ", ".join(f"{nm} {s * 1e3:.1f}ms" for nm, s in ranking)
    record(f"bsr_{n}x{n}_bd{block_density}_family", flops / sec / 1e9,
           "GFLOP/s", f"winner {win} of [{order}]; nnzb={nnzb}, bs={bs}, "
           f"p={p} (backend='auto' dispatches this)")


def config_nn(m=262_144, d=784, hidden=1024, classes=10, batch=8192,
              iters=50):
    """MLP training throughput — the reference's flagship iterative workload
    (examples/NeuralNetwork.scala; MNIST-shaped synthetic data at 4× MNIST's
    row count so sampling stride matters). One jitted SPMD step per iteration,
    weights resident on device; the recorded figure is steps/s and the
    model-FLOP rate (3 matmul passes per layer per step: fwd + two grad)."""
    import jax
    import jax.numpy as jnp

    import marlin_tpu as mt
    from marlin_tpu.ml import NeuralNetwork

    mesh = mt.create_mesh()
    data = mt.DenseVecMatrix.random(0, m, d, mesh=mesh)
    labels = np.arange(m) % classes
    nn = NeuralNetwork(input_dim=d, hidden_dim=hidden, output_dim=classes)
    # warm-up (compile) outside the timed region
    params, _ = nn.train(data, labels, iterations=2, batch_size=batch)
    t0 = time.perf_counter()
    params, losses = nn.train(data, labels, iterations=iters,
                              batch_size=batch, params=params)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    layer_flops = 2 * batch * (d * hidden + hidden * classes)
    steps_per_s = iters / dt
    record(f"nn_{m}x{d}_h{hidden}_b{batch}", steps_per_s, "steps/s",
           f"{3 * layer_flops * steps_per_s / 1e9:.0f} GFLOP/s model, "
           f"loss {losses[-1]:.4f}")


def config_lct(seq=32768, d_model=256, heads=2, layers=2, steps=3,
               remat=False, loss_chunk=None, name=None, attn="ring",
               compute_dtype=None, mlp_chunk=None, offload_residuals=False):
    """Long-context LM training throughput: one 32k-token causal stream,
    flash ring attention (dh=128 -> MXU tiles), Adam, full backward through
    the sequence-parallel attention (recompute VJP). No reference analog —
    this is the long-context mandate's training headline."""
    import numpy as np

    import marlin_tpu as mt
    from marlin_tpu.models import TransformerLM

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    vocab = 512
    tokens = rng.integers(0, vocab, seq).astype(np.int32)
    lm = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                       layers=layers, attn=attn, remat=remat,
                       loss_chunk=loss_chunk, compute_dtype=compute_dtype,
                       mlp_chunk=mlp_chunk,
                       offload_residuals=offload_residuals)
    params, _ = lm.train(tokens, steps=1, mesh=mesh)  # compile
    t0 = time.perf_counter()
    params, losses = lm.train(tokens, steps=steps, mesh=mesh, params=params)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    knobs = "+remat" if remat else ""
    knobs += f"+loss_chunk{loss_chunk}" if loss_chunk else ""
    knobs += f"+{compute_dtype}" if compute_dtype else ""
    record(name or f"lct_{seq}tok_d{d_model}_h{heads}_l{layers}",
           seq * steps / dt / 1e3, "ktok/s",
           f"{steps} steps in {dt:.1f} s, loss {losses[-1]:.3f}, "
           f"fwd+bwd through flash ring attention{knobs}")


def config_moe(seq=32768, d_model=256, heads=2, layers=2, n_experts=8,
               steps=3):
    """Mixture-of-experts LM training throughput at the lct shape: same
    32k-token stream and flash ring attention, the FFN replaced by 8 experts
    with GShard top-2 capacity routing (grouped — routing memory linear in
    seq) and the Switch aux in the loss. The comparison row for
    lct_32768tok: what expert routing costs at equal d_model (the MoE win is
    CAPACITY — 8x FFN params at ~2x FFN FLOPs — not step time). No
    reference analog (docs/parallelism.md "Expert parallelism")."""
    import numpy as np

    import marlin_tpu as mt
    from marlin_tpu.models import TransformerLM

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    vocab = 512
    tokens = rng.integers(0, vocab, seq).astype(np.int32)
    lm = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                       layers=layers, remat=True, loss_chunk=2048,
                       n_experts=n_experts)
    params, _ = lm.train(tokens, steps=1, mesh=mesh)  # compile
    t0 = time.perf_counter()
    params, losses = lm.train(tokens, steps=steps, mesh=mesh, params=params)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    record(f"moe_{seq}tok_e{n_experts}_top2_d{d_model}_l{layers}",
           seq * steps / dt / 1e3, "ktok/s",
           f"{steps} steps in {dt:.1f} s, loss {losses[-1]:.3f}, "
           f"{n_experts} experts/layer, grouped GShard routing + aux")


def config_attn_long():
    """Pure-attention long-context point: one causal flash forward at 256k+
    tokens (MARLIN_BENCH_ATTN_SEQ scales; O(S²) compute so reps stay low)."""
    seq = int(os.environ.get("MARLIN_BENCH_ATTN_SEQ", 262144))
    # reps amortize a ~60 ms relay sync; once a single forward is seconds
    # (O(S²)) that amortization buys nothing — drop to 1 rep past 256k
    config_attention(seq=seq, variants=(("flash", "high"),
                                        ("flash", "default")),
                     reps=3 if seq <= 262144 else 1)


def config_lct_long():
    """The marquee long-context run: the longest causal stream one 16 GB v5e
    trains end-to-end (ring flash attention + per-block remat + chunked LM
    head). HBM budget at the defaults (seq S=256k, d=256, L=2, f32):
    residual checkpoints ~L*S*d*4 = 512 MB, block recompute peak ~S*d_ff*4
    = 1 GB, head chunk ~MBs, params+Adam ~MBs — see docs/parallelism.md.
    MARLIN_BENCH_LCT_SEQ scales it up (524288, 1048576) to find the cliff."""
    seq = int(os.environ.get("MARLIN_BENCH_LCT_SEQ", 262144))
    # flash pinned (auto would pick it on TPU anyway): the Pallas forward +
    # two-pass Pallas backward is the only memory-feasible path up here.
    # MARLIN_BENCH_LCT_DTYPE=bfloat16 selects the mixed-precision path —
    # REQUIRED at 1M tokens (f32 needs 22 GiB; bf16 fits — AOT_MEMORY.json)
    cd = os.environ.get("MARLIN_BENCH_LCT_DTYPE") or None
    mc = int(os.environ.get("MARLIN_BENCH_LCT_MLP_CHUNK", 0)) or None
    remat, lc, off = True, 16384, False
    if os.environ.get("MARLIN_BENCH_LCT_PLAN") == "1":
        # let the planner pick the knobs from the compiler's own memory
        # accounting (models/planner.py) instead of the hand-set defaults —
        # costs one AOT compile per probed rung (~1 min each at 1M tokens),
        # which is why it is opt-in for the relay-uptime-limited batch
        from marlin_tpu.models import TransformerLM, plan_context

        base = TransformerLM(vocab=512, d_model=256, heads=2, layers=2,
                             attn="ring_flash")
        plan = plan_context(seq, base)
        print(f"[lct_long] planner: {plan.describe()}", flush=True)
        m = plan.model
        remat, lc, mc, cd, off = m.remat, m.loss_chunk, m.mlp_chunk, \
            m.compute_dtype, m.offload_residuals
    suffix = f"_{cd}" if cd else ""
    config_lct(seq=seq, steps=2, remat=remat, loss_chunk=lc,
               name=f"lct_long_{seq}tok_d256_h2_l2{suffix}",
               attn="ring_flash", compute_dtype=cd, mlp_chunk=mc,
               offload_residuals=off)


def config_decode(d_model=512, heads=8, layers=4, vocab=4096,
                  prompt_len=512, steps_a=64, steps_b=320):
    """KV-cached autoregressive decode: prefill vs per-token split, plus the
    traced-temperature no-recompile guarantee (round-3 verdict #7). Two step
    counts isolate the per-token cost (total = prefill + steps x per_token);
    a temperature sweep afterward must not grow the jit cache."""
    import jax
    import numpy as np

    import marlin_tpu as mt  # noqa: F401  (mesh/env init side effects)
    from marlin_tpu.models import TransformerLM
    from marlin_tpu.models.transformer import lm_generate

    lm = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                       layers=layers, seed=0)
    params = lm.init_params()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, prompt_len).astype(np.int32)
    key = jax.random.key(0)
    max_len = prompt_len + steps_b

    def run(steps, temperature=0.7):
        out = lm_generate(params, prompt, key, heads=heads, max_len=max_len,
                          steps=steps, temperature=temperature)
        jax.block_until_ready(out)
        return out

    run(steps_a), run(steps_b)  # compile both step counts
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        run(steps_a)
    ta = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        run(steps_b)
    tb = (time.perf_counter() - t0) / reps
    per_tok = (tb - ta) / (steps_b - steps_a)
    prefill_s = max(ta - steps_a * per_tok, 1e-9)

    # private jitted-function API: a JAX upgrade may drop it — degrade the
    # no-recompile check to a skip rather than a hard AttributeError
    cache_size = getattr(lm_generate, "_cache_size", None)
    n_compiled = cache_size() if cache_size else None
    for t in (0.0, 0.3, 1.3):
        run(steps_a, temperature=t)
    if cache_size:
        assert cache_size() == n_compiled, \
            "temperature sweep recompiled lm_generate"

    record(f"decode_d{d_model}_h{heads}_l{layers}_v{vocab}", 1.0 / per_tok,
           "tok/s",
           f"decode {per_tok * 1e3:.2f} ms/tok; prefill {prompt_len} tok in "
           f"{prefill_s * 1e3:.0f} ms ({prompt_len / prefill_s / 1e3:.1f} "
           f"ktok/s); no recompile across temperatures")

    # batch-decode throughput: the serving shape — per-step matmuls become
    # (B, d) @ (d, d) MXU work, so tok/s should scale far better than
    # linearly in cost. Short prompts (dense prefill, no flash dependency).
    from marlin_tpu.models.transformer import lm_generate_batch

    for bsz in (8, 64):
        bp = rng.integers(0, vocab, (bsz, prompt_len)).astype(np.int32)
        lens = np.full(bsz, prompt_len, np.int32)

        def run_b():
            out = lm_generate_batch(params, bp, lens, key, heads=heads,
                                    max_len=prompt_len + steps_a,
                                    steps=steps_a, temperature=0.7)
            jax.block_until_ready(out)

        run_b()  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            run_b()
        tb_ = (time.perf_counter() - t0) / 3
        record(f"decode_batch{bsz}", bsz * steps_a / tb_, "tok/s",
               f"{bsz} sequences decoded together, {steps_a} steps each; "
               f"{tb_ * 1e3 / steps_a:.2f} ms per batched step")

    # prompt-length sweep (round-4 verdict #3): past _PREFILL_FLASH_MIN the
    # prefill runs the flash kernel, so long-document prompts neither OOM
    # (linear score memory) nor fall off a throughput cliff. steps is tiny so
    # the measurement is prefill-dominated; per_tok from above removes the
    # decode tail. MARLIN_BENCH_DECODE_SWEEP=0 skips the sweep — the recovery
    # runner sets it when the Mosaic flash smoke failed, keeping a flash
    # compile failure out of the otherwise flash-free decode config.
    if os.environ.get("MARLIN_BENCH_DECODE_SWEEP", "1") == "0":
        return
    sweep_steps = 8
    for plen in (4096, 16384, 65536):
        pr = rng.integers(0, vocab, plen).astype(np.int32)

        def run_p(temperature=0.7):
            out = lm_generate(params, pr, key, heads=heads,
                              max_len=plen + sweep_steps, steps=sweep_steps,
                              temperature=temperature)
            jax.block_until_ready(out)

        run_p()  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            run_p()
        tp = (time.perf_counter() - t0) / 3
        pf = max(tp - sweep_steps * per_tok, 1e-9)
        record(f"decode_prefill_p{plen}", plen / pf / 1e3, "ktok/s",
               f"flash prefill ({plen} >= 2048 threshold): {pf * 1e3:.0f} ms "
               f"for the prompt; linear score memory (AOT-asserted)")


def config_serve(d_model=128, heads=8, layers=4, vocab=256):
    """Offered-load sweep through the serving engine (marlin_tpu/serving/):
    submitters inject Poisson-ish open-loop traffic at each offered rate;
    reported per rate are achieved tokens/s and p50/p99 end-to-end + TTFT
    latency (submit -> Result / first token). Env control,
    MARLIN_BENCH_PREFETCH-style:
    MARLIN_BENCH_SERVE_RATES (req/s list, default "4,16,64"),
    MARLIN_BENCH_SERVE_N (requests per rate, default 64),
    MARLIN_BENCH_SERVE_BATCH (slot width, default 8),
    MARLIN_BENCH_SERVE_STEPS (decode-steps range "lo,hi", default "4,32" —
    ragged output lengths, the traffic continuous batching exists for;
    rows retire at their requested steps),
    MARLIN_BENCH_SERVE_WARMUP=0 skips the per-bucket pre-compile (the
    first-request-pays-the-compile A/B),
    MARLIN_BENCH_SERVE_PAGED=0 is the dense-slab control for the paged
    KV-pool A/B (records get a `_slab` suffix; docs/performance.md records
    the pair),
    MARLIN_BENCH_SERVE_PREFIX_LEN=N (0 = off, the default) prepends a
    shared N-token system prompt to every request — the prefix-cache
    workload (records get a `_prefix` suffix; the acceptance bar is
    prefix-cache hits > 0 and TTFT p99 down vs the `_prefix_slab` control,
    ISSUE 8); the per-rate detail carries the hit counts,
    MARLIN_BENCH_SERVE_ROUTER=N (0 = off, the default) serves each rate
    through a Router over N supervised engine replicas instead of one bare
    engine — the resilience-layer A/B (records get a `_router` suffix;
    the acceptance bar is routed tok/s within 5% of the single-engine
    baseline at the top rate; router records carry the fleet
    `router_prefix_hit_rate` as a gated ride-along).

    MARLIN_BENCH_REPS=N (default 1) repeats every rate N times and records
    the median rep — serve numbers sample a live multi-threaded engine, so
    one rep is one draw of host scheduling noise. The sweep also emits a
    `serve_control*` record (a fixed pure-numpy matmul loop): it moves only
    when the HOST moved, and tools/bench_compare.py downgrades serve
    regressions that slid with it to warnings. The model
    (d_model=128, heads=8, layers=4) is sized so decode COMPUTE is
    non-trivial relative to dispatch — the serving regime; at toy sizes the
    sweep measures Python/dispatch overhead, which flatters whichever
    backend does the least host-side bookkeeping.

    Observability ride-along (docs/observability.md): a /metrics endpoint
    (MARLIN_BENCH_OBS_PORT, default ephemeral) is scraped DURING the first
    rate's live serve, every serve record lands in a JSONL
    (MARLIN_BENCH_SERVE_EVENTS, default under $TMPDIR) with request trace
    ids, and a `serve_obs` record reports scrape families + trace join —
    the proof the layer sees traffic without steering it."""
    import urllib.request

    import jax  # noqa: F401  (backend init before threads)

    import marlin_tpu as mt  # noqa: F401
    from marlin_tpu import obs
    from marlin_tpu.models import TransformerLM
    from marlin_tpu.obs import collectors
    from marlin_tpu.serving import Request, Router, ServeEngine, percentile
    from marlin_tpu.utils.tracing import EventLog, set_default_event_log

    rates = [float(r) for r in os.environ.get(
        "MARLIN_BENCH_SERVE_RATES", "4,16,64").split(",")]
    n_req = int(os.environ.get("MARLIN_BENCH_SERVE_N", 64))
    max_batch = int(os.environ.get("MARLIN_BENCH_SERVE_BATCH", 8))
    warmup = os.environ.get("MARLIN_BENCH_SERVE_WARMUP", "1") != "0"
    paged = os.environ.get("MARLIN_BENCH_SERVE_PAGED", "1") != "0"
    # decode-kernel A/B control: "" = the config default ('auto'),
    # "gather"/"pallas" force a backend and tag every record key with _k…
    # so both legs coexist in BENCH_ALL.json
    decode_kernel = os.environ.get("MARLIN_BENCH_DECODE_KERNEL", "")
    prefix_len = int(os.environ.get("MARLIN_BENCH_SERVE_PREFIX_LEN", "0"))
    if prefix_len > 240:
        # prompts must leave the per-request tail (8..) room inside the
        # largest (256, ...) bucket — clamp rather than die on the first
        # submit with a numpy low>=high error
        log(f"MARLIN_BENCH_SERVE_PREFIX_LEN={prefix_len} clamped to 240 "
            f"(tails need room inside the 256-token bucket)")
        prefix_len = 240
    router_n = int(os.environ.get("MARLIN_BENCH_SERVE_ROUTER", "0"))
    suffix = (("_prefix" if prefix_len else "")
              + ("" if paged else "_slab")
              + ("_router" if router_n else "")
              + (f"_k{decode_kernel}" if decode_kernel else ""))
    steps_lo, steps_hi = (int(v) for v in os.environ.get(
        "MARLIN_BENCH_SERVE_STEPS", "4,32").split(","))
    buckets = ((64, 32), (256, 32))
    lm = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                      layers=layers, seed=0)
    params = lm.init_params()
    rng = np.random.default_rng(0)
    # the shared system prompt for the prefix-cache workload: fixed tokens,
    # page-aligned-friendly length, identical across requests and sweeps
    prefix = (np.arange(prefix_len) * 7 % vocab).astype(np.int32)

    events_path = os.environ.get("MARLIN_BENCH_SERVE_EVENTS") or os.path.join(
        tempfile.gettempdir(), f"marlin_serve_events{suffix}.jsonl")
    for rot in ("", ".1", ".2"):  # fresh stream per sweep
        if os.path.exists(events_path + rot):
            os.remove(events_path + rot)
    elog = EventLog(events_path)
    prev_log = set_default_event_log(elog)
    srv = obs.MetricsServer(port=int(os.environ.get("MARLIN_BENCH_OBS_PORT",
                                                    "0")))
    obs_port = srv.start()  # installs compile + device-memory collectors
    scrape = ""
    mem_during: dict = {}

    def make_engine():
        return ServeEngine(params, heads, buckets=buckets,
                           max_batch=max_batch, max_wait_ms=5.0,
                           queue_depth=4 * n_req, paged=paged,
                           decode_kernel=decode_kernel or None)

    def run_rate(rate):
        nonlocal scrape
        if router_n:
            # the resilience A/B: supervised replicas behind the router,
            # same total offered load (admission capacity scales with N —
            # per-replica queues still bound overload)
            eng = Router(make_engine, replicas=router_n, warmup=warmup)
        else:
            eng = make_engine()
        try:
            if warmup and not router_n:
                eng.warmup()
            gaps = rng.exponential(1.0 / rate, n_req)
            handles, t_start = [], time.perf_counter()
            for i in range(n_req):
                if i:  # inter-arrival gaps only BETWEEN submits: a trailing
                    # sleep after the last one would deflate tok/s at low
                    # rates (no request is outstanding during it)
                    time.sleep(gaps[i - 1])
                plen = int(rng.integers(8, min(192, 256 - prefix_len)))
                prompt = rng.integers(0, vocab, plen).astype(np.int32)
                if prefix_len:
                    # the shared-prefix shape: one system prompt + a short
                    # per-request tail (the prefix cache should prefill the
                    # system prompt once per pool lifetime)
                    prompt = np.concatenate([prefix, prompt])
                handles.append(eng.submit(Request(
                    prompt=prompt,
                    steps=int(rng.integers(steps_lo, steps_hi + 1)))))
            scraper = None
            if not scrape:
                # scrape DURING the live serve (requests still in flight at
                # the first offered rate): the endpoint must show traffic
                # while it happens, not post-hoc aggregates. Off-thread so a
                # slow scrape never inflates the measured span — the tok/s
                # this sweep records is the passivity evidence.
                def _scrape_live():
                    nonlocal scrape, mem_during
                    collectors.log_device_memory(elog)  # mem timeline
                    try:
                        # the HBM ledger's mid-serve reconcile: taken while
                        # the KV slab and programs are still resident, so
                        # the serve_mem record attributes live bytes, not
                        # the post-close remainder
                        from marlin_tpu.obs import memledger
                        mem_during = memledger.reconcile()
                    except Exception:
                        pass
                    try:
                        scrape = urllib.request.urlopen(
                            f"http://127.0.0.1:{obs_port}/metrics",
                            timeout=10).read().decode()
                    except Exception:
                        pass  # next rate retries; the record shows 0/7
                scraper = threading.Thread(target=_scrape_live, daemon=True)
                scraper.start()
            eng.drain()
            span = time.perf_counter() - t_start
        finally:
            eng.close()
        if scraper is not None:
            scraper.join(timeout=15.0)
        results = [h.result(timeout=0) for h in handles]
        ok = [r for r in results if r.ok]
        lat = [r.metrics["total_s"] for r in ok]
        ttft = [r.metrics["ttft_s"] for r in ok
                if r.metrics.get("ttft_s") is not None]
        snap = eng.snapshot() if router_n else eng.metrics.snapshot()
        toks = sum(r.tokens.size - len(h.request.prompt)
                   for h, r in zip(handles, results) if r.ok)
        # a fully-shed load point (admission rejecting everything, chaos
        # faults) is a degraded data point, not a sweep abort
        ms = lambda xs, q: (  # noqa: E731
            f"{percentile(xs, q) * 1e3:.0f}" if xs else "n/a")
        sched = (f"paged, {snap['steps']} decode steps"
                 if paged else f"dense slab, {snap['steps']} decode steps")
        if paged:
            hits, misses = snap.get("prefix_hits", 0), \
                snap.get("prefix_misses", 0)
            sched += (f", prefix-cache {hits} hit / {misses} miss, "
                      f"cache-resident pages {snap.get('pages_used', 0)}"
                      f"/{snap.get('pages_total', 0)}")
        extra = None
        if router_n:
            # the router observability satellite (ISSUE 12): the merged
            # snapshot spans rotated-out replicas too, so the hit rate is
            # the fleet's — the prefix-affinity acceptance bar reads it
            hr = snap.get("prefix_hit_rate")
            sched = (f"{router_n}-replica supervised router "
                     f"({snap['retries']} retries, "
                     f"{snap.get('migrated_in', 0)} adopted, "
                     f"prefix-hit-rate "
                     f"{hr if hr is not None else 'n/a'}), " + sched)
            extra = {"router_prefix_hit_rate": hr}
        occ = snap.get("occupancy_mean", "n/a")
        detail = (f"{len(ok)}/{n_req} ok at {rate:g} req/s offered; p50 "
                  f"{ms(lat, 50)} ms / p99 {ms(lat, 99)} ms latency; ttft "
                  f"p50 {ms(ttft, 50)} ms / p99 {ms(ttft, 99)} ms; "
                  f"occupancy {occ}, {sched}, "
                  f"warmup={'on' if warmup else 'off'}")
        return toks / span, detail, extra

    # host-drift control (ISSUE 12): a fixed pure-numpy workload no serving
    # change can touch — when IT moves between BASE and NEW, the host was
    # noisy and bench_compare downgrades same-direction serve regressions
    # to warnings instead of failing the gate on machine weather
    def run_control():
        rng_c = np.random.default_rng(12345)
        a = rng_c.standard_normal((256, 256))
        reps_c = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.25:
            a = a @ a.T
            a *= 1e-3 / max(1e-9, float(abs(a).max()))
            reps_c += 1
        span = time.perf_counter() - t0
        return 2 * 256**3 * reps_c / span / 1e9

    # MARLIN_BENCH_REPS (ISSUE 12): median-of-N per offered rate — the
    # serve legs measure a live multi-threaded engine on a shared host, so
    # a single rep is one sample of the machine's mood; the median rep's
    # (value, detail) pair is recorded whole to keep the numbers coherent
    bench_reps = max(1, int(os.environ.get("MARLIN_BENCH_REPS", "1")))

    try:
        for rate in rates:
            runs = sorted((run_rate(rate) for _ in range(bench_reps)),
                          key=lambda t: t[0])
            val, detail, extra = runs[len(runs) // 2]
            if bench_reps > 1:
                detail += f"; median of {bench_reps} reps"
            # the slab/prefix/router controls keep their own record keys so
            # the A/B tuple coexists in BENCH_ALL.json (merge keyed by
            # config)
            record(f"serve_load{rate:g}" + suffix, val, "tok/s", detail,
                   extra=extra)
        ctrl = sorted(run_control() for _ in range(bench_reps))
        record("serve_control" + suffix,
               ctrl[len(ctrl) // 2], "GFLOP/s",
               "untouched-control sentinel: fixed 256x256 numpy matmul "
               "loop, no marlin code on the path — drift here is host "
               "noise, and the gate warns instead of failing when serve "
               "records move WITH it",
               extra={"control": True})
        # ---- decode-program roofline: the serve sweep's utilization record
        # (ISSUE 6 acceptance: BENCH rounds track utilization, not just
        # tok/s). The cost model came from warmup's capture, the timings
        # from the engines' live decode steps across all rates.
        from marlin_tpu.obs import perf as obs_perf

        # the slab control runs lm_decode_rows; the paged default decodes
        # through the block-table gather program
        decode_prog = "lm_decode_paged" if paged else "lm_decode_rows"
        decode_rows = [r for r in obs_perf.get_program_costs().rows()
                       if r["program"] == decode_prog and r["calls"]
                       and r["roofline_frac"] is not None]
        if decode_rows:
            r = max(decode_rows, key=lambda r: r["calls"])
            # frac can be non-None while achieved/peak_flops are (bandwidth
            # roofline, bytes-only cost model) — format each defensively
            ach = (f"{r['achieved_flops_per_s'] / 1e9:.2f} GFLOP/s"
                   if r["achieved_flops_per_s"] else "n/a")
            peak = (f"peak {r['peak_flops'] / 1e12:.1f} TFLOP/s"
                    if r["peak_flops"] else "the bandwidth roofline")
            record("serve_decode_roofline" + suffix,
                   r["roofline_frac"], "frac",
                   f"{decode_prog}[{r['key']}]: {ach} achieved over "
                   f"{r['calls']} dispatches vs {peak} "
                   f"(marlin_program_roofline_frac live on /metrics)",
                   extra={"roofline_frac": round(r["roofline_frac"], 4)})
    finally:
        # a mid-sweep failure must not leak the default log / endpoint into
        # the rest of the bench process (main() catches and keeps sweeping)
        srv.close()
        set_default_event_log(prev_log)
        elog.close()

    # ---- observability acceptance record: scrape families + trace join
    want = ("marlin_serve_submitted_total", "marlin_serve_queue_depth",
            "marlin_serve_slot_occupancy", "marlin_serve_kv_inflight_bytes",
            "marlin_compile_total", "marlin_prefetch_chunks_total",
            "marlin_device_memory_bytes_in_use",
            "marlin_program_roofline_frac",
            # the HBM-ledger attribution families (obs/memledger.py) ride
            # the same scrape: TYPE lines render even before any backend
            # sample lands, so the check holds on CPU too
            "marlin_mem_registered_bytes", "marlin_mem_live_bytes",
            "marlin_mem_unattributed_bytes")
    if paged:
        # the paging families ride only when the paged pool served
        want += ("marlin_serve_kv_pages_total", "marlin_serve_kv_pages_used",
                 "marlin_serve_prefix_cache_total")
    if router_n:
        # the resilience families ride only when the router/supervisors ran
        want += ("marlin_serve_retries_total", "marlin_serve_restarts_total",
                 "marlin_serve_replica_state")
    got = [n for n in want if f"# TYPE {n} " in scrape]
    # same "trace-joined" definition as python -m marlin_tpu.obs.report
    from marlin_tpu.obs.report import trace_join
    joined, total = trace_join(elog.read(include_rotated=True))
    trace_note = (f"{joined}/{total} requests trace-joined"
                  if total else "no serve events recorded")
    record("serve_obs" + suffix, float(len(got)),
           "families",
           f"live /metrics scrape during serve carried {len(got)}/{len(want)}"
           f" series ({', '.join(got)}); {trace_note}; events at "
           f"{events_path} (analyze: python -m marlin_tpu.obs.report)")

    # ---- memory-attribution acceptance record (HBM ledger,
    # docs/observability.md "Memory attribution"): the mid-serve reconcile
    # taken by the scrape thread is the evidence — per-component
    # attribution while the slab was resident, the unattributed fraction
    # ("n/a" without backend memory_stats, i.e. CPU), and the
    # calibrated-vs-raw admission headroom read from AOT_MEMORY.json's
    # serve_buckets table. Value = marlin_mem_* families on the live
    # scrape, so the record gates (unit is not informational).
    from marlin_tpu.obs import memledger

    mem_want = ("marlin_mem_registered_bytes", "marlin_mem_live_bytes",
                "marlin_mem_unattributed_bytes")
    mem_got = [n for n in mem_want if f"# TYPE {n} " in scrape]
    rec = mem_during or memledger.reconcile()
    frac = rec.get("unattributed_frac")
    comp = rec.get("components") or {}
    comp_note = (", ".join(f"{k} {v / 1e6:.1f}MB"
                           for k, v in sorted(comp.items()))
                 or "no live ledger entries at scrape time")
    ratios = [r["calibration"] for r in memledger.ratio_table()
              if r.get("calibration")]
    headroom = f"{max(ratios):.2f}" if ratios else "n/a"
    record("serve_mem" + suffix, float(len(mem_got)), "families",
           f"{len(mem_got)}/{len(mem_want)} marlin_mem_* families on the "
           f"live scrape; unattributed frac "
           f"{frac if frac is not None else 'n/a'}; components: "
           f"{comp_note}; calib-headroom {headroom}")


def config_serve_als(d_model=64, heads=4, layers=2, vocab=256):
    """BucketProgram serving legs (serving/programs/, ISSUE 18): (a) ALS
    recommendation scoring alone through the engine spine — achieved QPS
    and p50/p99 submit→Result latency against device-resident factors
    (`serve_als`) — and (b) the mixed-traffic leg: the same open-loop LM
    stream run bare, then again with an equal ALS stream interleaved on the
    SAME engine; `serve_mixed_lm` records the mixed run's LM tokens/s with
    the LM-only control and the mixed/solo ratio in the detail (acceptance:
    within 5% — co-resident one-shot programs must not tax LM decode).

    MARLIN_BENCH_SERVE_ALS_N (ALS requests, default 256),
    MARLIN_BENCH_SERVE_ALS_SHAPE ("users,items,rank", default
    "512,256,16"), MARLIN_BENCH_SERVE_MIX_N (LM requests per mixed leg,
    default 32) size the legs; MARLIN_BENCH_REPS medians the mixed pair."""
    import jax  # noqa: F401  (backend init before threads)

    from marlin_tpu.models import TransformerLM
    from marlin_tpu.serving import (ALSScoreProgram, Request, ServeEngine,
                                    percentile)

    n_als = int(os.environ.get("MARLIN_BENCH_SERVE_ALS_N", 256))
    users, items, rank = (int(v) for v in os.environ.get(
        "MARLIN_BENCH_SERVE_ALS_SHAPE", "512,256,16").split(","))
    n_lm = int(os.environ.get("MARLIN_BENCH_SERVE_MIX_N", 32))
    reps = max(1, int(os.environ.get("MARLIN_BENCH_REPS", "1")))
    rng = np.random.default_rng(0)
    uf = rng.standard_normal((users, rank)).astype(np.float32)
    pf = rng.standard_normal((items, rank)).astype(np.float32)
    lm = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                      layers=layers, seed=0)
    params = lm.init_params()
    buckets = ((64, 32),)

    def make_engine():
        eng = ServeEngine(params, heads, buckets=buckets, max_batch=8,
                          max_wait_ms=1.0, queue_depth=4 * (n_als + n_lm),
                          programs=[ALSScoreProgram((uf, pf))])
        eng.warmup()
        return eng

    def als_requests(n):
        return [Request(program="als",
                        payload={"user": int(rng.integers(0, users)),
                                 "k": 8})
                for _ in range(n)]

    def lm_requests(n):
        return [Request(prompt=rng.integers(0, vocab, int(
                    rng.integers(8, 48))).astype(np.int32),
                        steps=int(rng.integers(4, 16)))
                for _ in range(n)]

    # ---- leg (a): ALS alone — QPS + latency percentiles
    eng = make_engine()
    try:
        t0 = time.perf_counter()
        handles = [eng.submit(r) for r in als_requests(n_als)]
        eng.drain()
        span = time.perf_counter() - t0
        results = [h.result(timeout=0) for h in handles]
    finally:
        eng.close()
    ok = [r for r in results if r.ok]
    lat = sorted(r.metrics["total_s"] for r in ok)
    ms = lambda q: (f"{percentile(lat, q) * 1e3:.1f}"  # noqa: E731
                    if lat else "n/a")
    record("serve_als", len(ok) / span, "req/s",
           f"{len(ok)}/{n_als} ok; top-8 of {items} items, rank {rank}, "
           f"{users} users resident; p50 {ms(50)} ms / p99 {ms(99)} ms "
           f"submit-to-result")

    # ---- leg (b): the mixed-traffic bar — LM tok/s solo vs with an equal
    # ALS stream co-resident on the same engine
    def run_lm(mixed):
        eng = make_engine()
        try:
            reqs = lm_requests(n_lm)
            extra = als_requests(n_lm) if mixed else []
            t0 = time.perf_counter()
            handles = [eng.submit(r) for r in reqs]
            ehandles = [eng.submit(r) for r in extra]
            eng.drain()
            span = time.perf_counter() - t0
            results = [h.result(timeout=0) for h in handles]
            eok = sum(h.result(timeout=0).ok for h in ehandles)
        finally:
            eng.close()
        toks = sum(r.tokens.size - len(q.prompt)
                   for q, r in zip(reqs, results) if r.ok)
        return toks / span, sum(r.ok for r in results), eok

    solo = sorted(run_lm(False)[0] for _ in range(reps))[reps // 2]
    mixed_runs = sorted((run_lm(True) for _ in range(reps)),
                        key=lambda t: t[0])
    mixed_toks, lm_ok, als_ok = mixed_runs[reps // 2]
    ratio = mixed_toks / solo if solo else 0.0
    record("serve_mixed_lm", mixed_toks, "tok/s",
           f"LM decode under mixed LM+ALS load: {lm_ok}/{n_lm} LM ok with "
           f"{als_ok}/{n_lm} ALS ok co-resident; LM-only control "
           f"{solo:.1f} tok/s, mixed/solo ratio {ratio:.3f} "
           f"(bar: >= 0.95)" + (f"; median of {reps} reps"
                                if reps > 1 else ""),
           extra={"mixed_solo_ratio": round(ratio, 4)})


def config_serve_slo(d_model=64, heads=4, layers=2, vocab=256):
    """SLO-engine acceptance leg (docs/observability.md "Serving SLOs"):
    the same open-loop serve run twice — leg A with `serve_slo` objectives
    configured (generous targets, so the engine evaluates but never
    breaches) and leg B plain — and records (a) the `marlin_slo_*`
    families carried by a live /metrics scrape plus the `/debug/slo`
    payload DURING leg A's serve, and (b) passivity: an
    evaluating-but-quiet SLO engine must cost <= 2% tok/s vs the plain
    engine (the A/B lands as `serve_slo_passivity`; tools/Makefile's
    obs-gate reads both through bench_compare --only serve_).

    MARLIN_BENCH_SERVE_SLO_N (requests per leg, default 48) and
    MARLIN_BENCH_SERVE_SLO_RATE (req/s, default 32) size the legs."""
    import urllib.request

    import jax  # noqa: F401  (backend init before threads)

    import marlin_tpu as mt
    from marlin_tpu import obs
    from marlin_tpu.models import TransformerLM
    from marlin_tpu.serving import Request, ServeEngine

    n_req = int(os.environ.get("MARLIN_BENCH_SERVE_SLO_N", 48))
    rate = float(os.environ.get("MARLIN_BENCH_SERVE_SLO_RATE", 32))
    buckets = ((64, 32),)
    params = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                           layers=layers, seed=0).init_params()
    rng = np.random.default_rng(0)
    # generous targets: the leg proves evaluation cost + exposition, not
    # breach handling (tests/test_slo.py owns the breach state machine)
    slo_cfg = (
        {"name": "ttft", "metric": "p95:marlin_serve_ttft_seconds",
         "target": 60.0, "window_s": 600.0},
        {"name": "avail",
         "metric": "ratio:marlin_serve_requests_total{status=ok}"
                   "/marlin_serve_requests_total",
         "target": 0.5, "window_s": 600.0},
    )

    srv = obs.MetricsServer(port=int(os.environ.get("MARLIN_BENCH_OBS_PORT",
                                                    "0")))
    obs_port = srv.start()
    scrape, slo_json = "", ""

    def run_leg(with_slo):
        nonlocal scrape, slo_json
        ctx = (mt.config_context(serve_slo=slo_cfg,
                                 serve_slo_eval_interval_s=0.25,
                                 serve_ts_bucket_s=1.0)
               if with_slo else contextlib.nullcontext())
        with ctx:
            eng = ServeEngine(params, heads, buckets=buckets, max_batch=8,
                              max_wait_ms=5.0, queue_depth=4 * n_req)
        try:
            eng.warmup()
            gaps = rng.exponential(1.0 / rate, n_req)
            handles, t0 = [], time.perf_counter()
            for i in range(n_req):
                if i:
                    time.sleep(gaps[i - 1])
                plen = int(rng.integers(8, 48))
                handles.append(eng.submit(Request(
                    prompt=rng.integers(0, vocab, plen).astype(np.int32),
                    steps=int(rng.integers(4, 17)))))
            scraper = None
            if with_slo:
                def _scrape_live():  # off-thread: never inflates the span
                    nonlocal scrape, slo_json
                    try:
                        scrape = urllib.request.urlopen(
                            f"http://127.0.0.1:{obs_port}/metrics",
                            timeout=10).read().decode()
                        slo_json = urllib.request.urlopen(
                            f"http://127.0.0.1:{obs_port}/debug/slo",
                            timeout=10).read().decode()
                    except Exception:
                        pass  # the record shows 0/5 families
                scraper = threading.Thread(target=_scrape_live, daemon=True)
                scraper.start()
            eng.drain()
            span = time.perf_counter() - t0
        finally:
            eng.close()
        if scraper is not None:
            scraper.join(timeout=15.0)
        results = [h.result(timeout=0) for h in handles]
        toks = sum(r.tokens.size - len(h.request.prompt)
                   for h, r in zip(handles, results) if r.ok)
        return toks / span, sum(r.ok for r in results)

    try:
        # throwaway warm leg: the first engine of the process pays
        # first-render/threadpool costs that would land entirely on
        # whichever A/B leg runs first and masquerade as SLO overhead
        run_leg(False)
        # SLO leg next so the scrape catches it live; plain leg last
        tok_slo, ok_slo = run_leg(True)
        tok_plain, ok_plain = run_leg(False)
    finally:
        srv.close()

    want = ("marlin_slo_compliance", "marlin_slo_budget_remaining",
            "marlin_slo_burn_rate", "marlin_slo_breached",
            "marlin_slo_shed_total")
    got = [n for n in want if f"# TYPE {n} " in scrape]
    payload = {}
    try:
        payload = json.loads(slo_json)
    except Exception:
        pass
    scopes = payload.get("scopes") or []
    slo_names = sorted({o.get("slo") for s in scopes
                        for o in s.get("objectives", ())})
    record("serve_slo", float(len(got)), "families",
           f"live /metrics scrape during an SLO-evaluating serve carried "
           f"{len(got)}/{len(want)} marlin_slo_* series ({', '.join(got)}); "
           f"/debug/slo returned {len(scopes)} scope(s) with objectives "
           f"{slo_names}; {ok_slo}/{n_req} ok")
    delta = (tok_plain - tok_slo) / tok_plain if tok_plain > 0 else 0.0
    record("serve_slo_passivity", tok_slo, "tok/s",
           f"SLO leg {tok_slo:.1f} tok/s vs plain {tok_plain:.1f} tok/s "
           f"({delta:+.1%} cost; acceptance bar <= 2%); {ok_plain}/{n_req} "
           f"ok plain leg", extra={"plain_tok_s": round(tok_plain, 2),
                                   "delta_frac": round(delta, 4)})


def config_fleet(d_model=64, heads=4, layers=2, vocab=256):
    """Elastic-fleet acceptance leg (docs/serving.md "Elastic fleet"): a
    diurnal open-loop trace — quiet, burst, quiet — served twice through a
    Router. The elastic leg starts at ``serve_fleet_min_replicas`` and lets
    a FleetController scale on fleet-merged SLO burn; the static control
    leg serves the identical trace on a peak-sized fixed fleet. Records:

    - ``serve_fleet`` (elastic): value = fraction of the static fleet's
      replica-hours saved; detail carries dropped-request count, tail
      (p95) TTFT vs the SLO target, scale-event count, and
      ``replica-hours-saved F`` — the higher-is-better detail gate
      tools/bench_compare.py enforces under ``make -C tools fleet-gate``.
    - ``serve_fleet_static`` (control): the peak-sized fixed fleet's ok
      fraction + replica-hours, the denominator of the saving.

    MARLIN_BENCH_FLEET=0 skips the elastic leg (static control only).
    MARLIN_BENCH_FLEET_PHASES ("rate:count,…", default "4:12,40:160,2:32")
    shapes the trace, MARLIN_BENCH_FLEET_MAX (default 3) sizes the static
    fleet and the elastic ceiling, MARLIN_BENCH_FLEET_TTFT_SLO (seconds,
    default 0.3) sets the p95 TTFT objective the burn is computed from."""
    import jax  # noqa: F401  (backend init before threads)

    import marlin_tpu as mt
    from marlin_tpu.models import TransformerLM
    from marlin_tpu.serving import (FleetController, Request, Router,
                                    ServeEngine, percentile)

    elastic = os.environ.get("MARLIN_BENCH_FLEET", "1") != "0"
    phases = [(float(r), int(c)) for r, c in
              (p.split(":") for p in os.environ.get(
                  "MARLIN_BENCH_FLEET_PHASES", "4:12,40:160,2:32")
               .split(","))]
    peak = int(os.environ.get("MARLIN_BENCH_FLEET_MAX", "3"))
    ttft_slo = float(os.environ.get("MARLIN_BENCH_FLEET_TTFT_SLO", "0.75"))
    n_req = sum(c for _, c in phases)
    buckets = ((64, 32),)
    params = TransformerLM(vocab=vocab, d_model=d_model, heads=heads,
                           layers=layers, seed=0).init_params()
    # the burn source: a tight p95 TTFT objective over a short window, so
    # the burst phase's queueing shows up as burn >> 1 within seconds and
    # the quiet phases decay back to slack
    slo_cfg = ({"name": "ttft", "metric": "p95:marlin_serve_ttft_seconds",
                "target": ttft_slo, "window_s": 60.0},)

    def make_engine():
        # the factory runs from controller action threads too — carry the
        # SLO config with it so scaled-out replicas evaluate burn as well
        # shedding off: the fleet experiment wants burn answered with
        # topology (scale events), not with admission-level degradation
        with mt.config_context(serve_slo=slo_cfg,
                               serve_slo_eval_interval_s=0.25,
                               serve_slo_fast_window_s=4.0,
                               serve_slo_shed=False,
                               serve_ts_bucket_s=1.0):
            # max_batch=2: batch SLOTS are the capacity unit, so a
            # scale-out adds real headroom even where replicas share
            # host compute (CPU CI) — the burst queues on slots, not FLOPs
            return ServeEngine(params, heads, buckets=buckets, max_batch=2,
                               max_wait_ms=5.0, queue_depth=4 * n_req)

    def run_trace(replicas, with_controller):
        rng = np.random.default_rng(7)  # identical trace both legs
        router = Router(make_engine, replicas=replicas, warmup=True)
        ctl = None
        # integrate replica-seconds off-thread at 50 ms so BOTH legs pay
        # the same accounting (the controller's own counter only advances
        # on its ticks, and the static leg has no controller at all)
        stop, acc = threading.Event(), {"rs": 0.0}

        def _integrate():
            last = time.perf_counter()
            while not stop.is_set():
                stop.wait(0.05)
                now = time.perf_counter()
                acc["rs"] += (now - last) * router.replica_count()
                last = now

        sampler = threading.Thread(target=_integrate, daemon=True)
        t0 = time.perf_counter()
        sampler.start()
        events = []
        try:
            if with_controller:
                ctl = FleetController(router, max_replicas=peak,
                                      eval_interval_s=0.25, out_burn=1.0,
                                      in_burn=0.25, hysteresis=1,
                                      cooldown_s=1.0, flap_window_s=6.0,
                                      action_timeout_s=120.0)
                ctl.start(poll_s=0.1)
            handles, submit_ts = [], []
            for rate, count in phases:
                gaps = rng.exponential(1.0 / rate, count)
                for i in range(count):
                    time.sleep(gaps[i])
                    plen = int(rng.integers(8, 48))
                    submit_ts.append(time.monotonic())
                    handles.append(router.submit(Request(
                        prompt=rng.integers(0, vocab, plen)
                        .astype(np.int32),
                        steps=int(rng.integers(24, 33)))))
            router.drain()
            span = time.perf_counter() - t0
            if ctl is not None:
                events = [r for r in ctl.payload()["history"]
                          if r["outcome"] == "ok"]
        finally:
            if ctl is not None:
                ctl.close()
            stop.set()
            sampler.join(timeout=5.0)
            router.close()
        results = [h.result(timeout=0) for h in handles]
        ok = [r for r in results if r.ok]
        ttft = [r.metrics["ttft_s"] for r in ok
                if r.metrics.get("ttft_s") is not None]
        # the converged tail: requests submitted after the last scale-out
        # landed (the fleet is at size for them) — the reaction transient
        # ahead of it is the price of elasticity, reported separately
        outs = [e["finished"] for e in events if e["action"] == "scale_out"]
        steady = [r.metrics["ttft_s"]
                  for t, r in zip(submit_ts, results)
                  if r.ok and r.metrics.get("ttft_s") is not None
                  and (not outs or t >= max(outs))] or ttft
        return {"ok": len(ok), "dropped": len(results) - len(ok),
                "span": span, "replica_seconds": acc["rs"],
                "ttft_p95_ms": (percentile(ttft, 95) * 1e3 if ttft
                                else 0.0),
                "ttft_steady_p95_ms": (percentile(steady, 95) * 1e3
                                       if steady else 0.0),
                "events": events}

    static = run_trace(peak, False)
    record("serve_fleet_static", static["ok"] / max(1, n_req), "frac",
           f"peak-sized static fleet ({peak} replicas): "
           f"{static['ok']}/{n_req} ok, {static['dropped']} dropped; "
           f"ttft p95 {static['ttft_p95_ms']:.0f} ms vs SLO "
           f"{ttft_slo * 1e3:.0f} ms; "
           f"{static['replica_seconds']:.1f} replica-seconds over "
           f"{static['span']:.1f} s — the replica-hours denominator for "
           f"serve_fleet",
           extra={"replica_seconds": round(static["replica_seconds"], 2)})
    if not elastic:
        log("MARLIN_BENCH_FLEET=0: static control leg only")
        return
    el = run_trace(1, True)
    saved = ((static["replica_seconds"] - el["replica_seconds"])
             / static["replica_seconds"]) if static["replica_seconds"] \
        else 0.0
    kinds = collections.Counter(r["action"] for r in el["events"])
    within = el["ttft_steady_p95_ms"] <= ttft_slo * 1e3
    record("serve_fleet", saved, "frac saved",
           f"elastic fleet 1..{peak} replicas: {el['ok']}/{n_req} ok, "
           f"{el['dropped']} dropped; converged ttft p95 "
           f"{el['ttft_steady_p95_ms']:.0f} ms vs SLO "
           f"{ttft_slo * 1e3:.0f} ms "
           f"({'within' if within else 'OVER'}; full-trace "
           f"{el['ttft_p95_ms']:.0f} ms incl. reaction transient); "
           f"{len(el['events'])} scale events ({dict(kinds)}); "
           f"{el['replica_seconds']:.1f} replica-seconds vs static "
           f"{static['replica_seconds']:.1f} "
           f"(replica-hours-saved {max(0.0, saved):.3f})",
           extra={"dropped": el["dropped"],
                  "scale_events": len(el["events"]),
                  "ttft_p95_ms": round(el["ttft_p95_ms"], 1),
                  "ttft_steady_p95_ms": round(el["ttft_steady_p95_ms"], 1),
                  "replica_seconds": round(el["replica_seconds"], 2)})


def config_svd(m=1_000_000, n=512, k=8):
    """Top-k SVD of a tall-skinny matrix via the distributed Gramian +
    matrix-free Lanczos path (the reference's dist-eigs ARPACK mode,
    DenseVecMatrix.scala:1531-1652) — on-chip evidence for the eigensolver."""
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    a = mt.DenseVecMatrix.random(0, m, n, mesh=mesh)
    float(jnp.sum(a.data))
    svd = a.compute_svd(k, mode="dist-eigs", compute_u=False)  # compile
    t0 = time.perf_counter()
    svd = a.compute_svd(k, mode="dist-eigs", compute_u=False)
    s = np.asarray(svd.s)  # SVDResult.s is host-side — fetch ends the timing
    dt = time.perf_counter() - t0
    assert s.shape[0] == k and np.all(np.diff(s) <= 0), "singular values not sorted"
    record(f"svd_{m}x{n}_top{k}", dt, "s", f"dist-eigs Gramian+Lanczos, "
           f"sigma_max {s[0]:.1f}")


def config_als(users=1_000_000, items=100_000, rank=32, nnz=10_000_000,
               iters=3):
    """Blocked ALS at MovieLens-10M-ish scale on one chip: wall clock per
    sweep plus the RMSE trajectory (reference workload: examples/ALS.scala →
    ALSHelp.ALSRun)."""
    import marlin_tpu as mt

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    ui = rng.integers(0, users, nnz).astype(np.int32)
    ii = rng.integers(0, items, nnz).astype(np.int32)
    u_t = rng.standard_normal((users, 8)).astype(np.float32) / 8.0
    v_t = rng.standard_normal((items, 8)).astype(np.float32)
    vals = np.einsum("nk,nk->n", u_t[ui], v_t[ii]) + \
        0.1 * rng.standard_normal(nnz).astype(np.float32)
    coo = mt.CoordinateMatrix(ui, ii, vals, shape=(users, items), mesh=mesh)
    model = coo.als(rank=rank, iterations=1, lam=0.05)  # compile + H2D
    mt.evaluate(model.user_features, model.product_features)
    t0 = time.perf_counter()
    model = coo.als(rank=rank, iterations=iters, lam=0.05)
    # data-dependent fetch inside the timed region: async dispatch otherwise
    # means the clock reads dispatch latency, not compute (profiling.evaluate)
    mt.evaluate(model.user_features, model.product_features)
    dt = time.perf_counter() - t0
    rmse = model.rmse(coo)
    record(f"als_{users}x{items}_r{rank}_{nnz}nnz", dt / iters, "s/sweep",
           f"{iters} sweeps in {dt:.1f} s, rmse {rmse:.3f}")


def config_accuracy(n=20000, rows=128):
    """On-TPU numerics evidence (VERDICT r1 #9): rel-err of one row block of
    the north-star multiply against a *host* f64 oracle (independent hardware,
    independent arithmetic; D2H bounded to 3 row blocks), plus the
    default-vs-high precision delta proving the ``precision`` kwarg reaches
    the MXU (bf16 passes vs f32 — indistinguishable on the CPU mesh, where
    tests/test_strategy_equivalence.py documents the blind spot)."""
    import jax
    import jax.numpy as jnp

    import marlin_tpu as mt

    mesh = mt.create_mesh()
    a = mt.DenseVecMatrix.random(0, n, n, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, n, n, mesh=mesh)
    # "default" must be requested explicitly: the library config default is
    # "highest" (config.matmul_precision), so a bare multiply runs the full-
    # f32 path — comparing that against "high" proves nothing about bf16
    c_hi = a.multiply(b, precision="high")
    c_def = a.multiply(b, precision="default")
    hi_rows = np.asarray(jax.device_get(c_hi.data[:rows]), np.float64)
    def_rows = np.asarray(jax.device_get(c_def.data[:rows]), np.float64)
    dev_a_rows = np.asarray(jax.device_get(a.data[:rows]))

    # regenerate the operands on the host CPU backend — threefry is
    # counter-based and backend-deterministic, so this is the same data
    # without a 3.2 GB D2H; verify that claim bitwise on the fetched rows
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        a_cpu = np.asarray(mt.random.random_array(0, (n, n)))
        b_cpu = np.asarray(mt.random.random_array(1, (n, n)))
    assert np.array_equal(a_cpu[:rows], dev_a_rows), \
        "host regeneration diverged from device operand — oracle invalid"
    oracle = a_cpu[:rows].astype(np.float64) @ b_cpu.astype(np.float64)
    scale = np.abs(oracle).max()
    err_hi = float(np.abs(hi_rows - oracle).max() / scale)
    err_def = float(np.abs(def_rows - oracle).max() / scale)
    ratio = err_def / max(err_hi, 1e-30)
    plumbed = "kwarg reaches the MXU" if ratio > 3 else (
        "WARNING: default≈high — expected only off-TPU, where both paths "
        "compute f32")
    record(f"acc_{n}_rowblock_f64_oracle", err_hi, "rel err",
           f"precision=high {err_hi:.2e} vs host f64; "
           f"default(bf16)={err_def:.2e}, ratio {ratio:.0f}x — {plumbed}")


def main():
    which = sys.argv[1:] or ["1", "2", "3", "4", "5"]
    steps = {
        "1": config1,
        # 100 reps so the relay's fixed ~66 ms sync round-trip (measured:
        # per-multiply device time is rep-count invariant at ~2.2 ms)
        # amortizes out of the per-multiply figure
        "2": lambda: _dense_config(4000, 100, "2_dense_4000"),
        "3": lambda: _dense_config(20000, 5, "3_dense_20000"),
        # the bf16-storage speed story (accuracy story lives in `acc`):
        # same 20000^2 multiply with bf16 MXU operands
        "bf16": lambda: _dense_config(20000, 10, "3_dense_20000_bf16",
                                      precision="default"),
        "4": config4,
        # the file-fed data-plane A/B alone (it also runs at the tail of
        # config 4): re-measure the text-vs-chunkstore legs without the
        # 8 GB synthetic-generation legs in front
        "4file": _config4_file_legs,
        "5": config5,
        "lu": config_lu,
        "chol": config_cholesky,
        "attn": config_attention,
        "pr": config_pagerank,
        "acc": config_accuracy,
        "als": config_als,
        "bsr": config_bsr,
        "svd": config_svd,
        "nn": config_nn,
        "lct": config_lct,
        "lct_long": config_lct_long,
        "attn_long": config_attn_long,
        "decode": config_decode,
        "moe": config_moe,
        "serve": config_serve,
        "serve_als": config_serve_als,
        "serve_slo": config_serve_slo,
        "fleet": config_fleet,
    }
    for k in which:
        log(f"=== config {k}")
        try:
            steps[k]()
        except Exception as e:  # keep the sweep going
            log(f"config {k} FAILED: {type(e).__name__}: {e}")
            record(f"{k}_FAILED", 0.0, "error", str(e)[:200])

    # merge with prior runs so partial sweeps don't clobber the table
    merged = {}
    if os.path.exists(RESULTS_PATH):
        try:
            merged = {r["config"]: r for r in json.load(open(RESULTS_PATH))}
        except Exception:
            merged = {}
    for r in RESULTS:
        merged[r["config"]] = r
    ordered = [merged[k] for k in sorted(merged)]
    with open(RESULTS_PATH, "w") as f:
        json.dump(ordered, f, indent=1)
    with open("BENCHMARKS.md", "w") as f:
        f.write("# Benchmarks (single TPU v5e chip via relay)\n\n")
        f.write("Configs from BASELINE.md; run `python bench_all.py`. Note: this\n")
        f.write("environment reaches the chip through a loopback relay whose sync\n")
        f.write("round-trip (~60 ms) and H2D bandwidth (~25 MB/s) bound the small\n")
        f.write("and streaming configs; compute-bound configs are unaffected.\n\n")
        f.write("| Config | Value | Unit | Measured | Detail |\n"
                "|---|---|---|---|---|\n")
        for r in ordered:
            # entries from before the provenance stamp are round-2-or-earlier
            # by definition (the stamp shipped in round 4; the relay was down
            # for all of round 3)
            when = r.get("measured", "≤r2 (pre-provenance; stale)")
            f.write(f"| {r['config']} | {r['value']} | {r['unit']} | {when} "
                    f"| {r['detail']} |\n")


if __name__ == "__main__":
    main()
