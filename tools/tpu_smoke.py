"""Fast on-chip smoke for the Pallas attention kernels (fwd + two-pass bwd).

Run right after relay recovery, before the heavy bench batch: the backward
kernels (ops/flash_attention.py:flash_attention_panel_bwd) are validated in
interpret mode by the test suite, but their first real Mosaic compile happens
on the chip — this catches a Mosaic rejection in seconds instead of failing
the 256k lct_long config twenty minutes into the batch.

Exits 0 on pass; prints the failure and exits 1 otherwise (the recovery
runner logs but does not abort on it — the dense benches don't depend on
these kernels).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    import marlin_tpu as mt
    from marlin_tpu.parallel.ring_attention import (attention_reference,
                                                    ring_attention)

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    seq, d = 1024, 128
    q, k, v = (jnp.asarray(rng.standard_normal((seq, d)).astype(np.float32))
               for _ in range(3))

    out = ring_attention(q, k, v, mesh, causal=True, backend="flash")
    ref = attention_reference(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    print(f"flash fwd rel err: {err:.2e}")
    if not err < 1e-3:
        print("FWD MISMATCH", file=sys.stderr)
        return 1

    gq, gk, gv = jax.jit(jax.grad(
        lambda qq, kk, vv: jnp.sum(ring_attention(
            qq, kk, vv, mesh, causal=True, backend="flash")),
        argnums=(0, 1, 2)))(q, k, v)
    _, vjp = jax.vjp(lambda qq, kk, vv: attention_reference(
        qq, kk, vv, causal=True), q, k, v)
    oq, ok, ov = vjp(jnp.ones((seq, d), jnp.float32))
    for name, got, want in (("dq", gq, oq), ("dk", gk, ok), ("dv", gv, ov)):
        e = float(jnp.max(jnp.abs(got - want)) /
                  jnp.maximum(jnp.max(jnp.abs(want)), 1e-30))
        print(f"flash bwd {name} rel err: {e:.2e}")
        if not e < 1e-3:
            print(f"BWD {name} MISMATCH", file=sys.stderr)
            return 1

    # bf16 leg: the dtype the 1M lct_long config runs (mixed precision);
    # oracle stays the small-seq f32 reference with a loose bf16 tolerance
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    outb = ring_attention(qb, kb, vb, mesh, causal=True, backend="flash")
    eb = float(jnp.max(jnp.abs(outb.astype(jnp.float32) - ref)) /
               jnp.max(jnp.abs(ref)))
    print(f"flash fwd bf16 rel err: {eb:.2e}")
    if not eb < 3e-2:
        print("BF16 FWD MISMATCH", file=sys.stderr)
        return 1

    if jax.default_backend() != "tpu":
        # CPU debug run: the big-panel and BSR legs are interpret-mode hours
        # off-chip (and covered by the suite + AOT tests there); the point of
        # this tool is the on-chip Mosaic compile
        print("tpu_smoke ok (small legs only — non-TPU backend)")
        return 0

    # big-panel leg: >=64k panels take the 512-token flash blocks (the
    # 1024-block kernel exceeds Mosaic's scoped-VMEM budget there — caught
    # by the AOT channel; this is the on-chip confirmation at exactly the
    # regime lct_long runs). The dense oracle would need an (S, S) score
    # matrix, so the xla tiled backend is the oracle instead.
    seq_big = 65536
    qL, kL, vL = (jnp.asarray(rng.standard_normal((seq_big, d)).astype(np.float32))
                  for _ in range(3))
    fL = ring_attention(qL, kL, vL, mesh, causal=True, backend="flash")
    xL = ring_attention(qL, kL, vL, mesh, causal=True, backend="xla")
    eL = float(jnp.max(jnp.abs(fL - xL)) / jnp.max(jnp.abs(xL)))
    print(f"flash fwd 64k (512-blocks) vs xla rel err: {eL:.2e}")
    if not eL < 1e-3:
        print("BIG-PANEL FWD MISMATCH", file=sys.stderr)
        return 1
    gbig = jax.jit(jax.grad(
        lambda qq: jnp.sum(ring_attention(
            qq, kL, vL, mesh, causal=True, backend="flash"))))(qL)
    if not bool(jnp.isfinite(gbig).all()):
        print("BIG-PANEL BWD NON-FINITE", file=sys.stderr)
        return 1
    print("flash bwd 64k: compiled, finite")

    # BSR manual-DMA kernel (ops/sparse_bsr.py): its first real Mosaic
    # compile also happens on-chip; oracle is the chunked formulation
    from marlin_tpu.ops.sparse_bsr import bsr_from_coo

    M = N = K = 2048
    bs, nb = 128, 24
    flat = rng.choice((M // bs) * (K // bs), nb, replace=False)
    ri, ci = np.divmod(flat, K // bs)
    coo_r = np.concatenate([(r * bs + np.arange(bs)).repeat(bs) for r in ri])
    coo_c = np.concatenate([np.tile(c * bs + np.arange(bs), bs) for c in ci])
    coo_v = rng.random(nb * bs * bs).astype(np.float32)
    bsr = bsr_from_coo(coo_r, coo_c, coo_v, (M, K), block_size=bs)
    b_dense = jnp.asarray(rng.standard_normal((K, N)).astype(np.float32))
    # pin true-f32 matmuls for BOTH paths: this is a correctness gate, and
    # the two formulations' bf16-default roundings differ by summation order
    # (the production default stays whatever the caller's precision is)
    with jax.default_matmul_precision("highest"):
        yp = bsr.multiply(b_dense, backend="pallas")
        yc = bsr.multiply(b_dense, backend="chunked")
    ebsr = float(jnp.max(jnp.abs(yp - yc)) /
                 jnp.maximum(jnp.max(jnp.abs(yc)), 1e-30))
    print(f"bsr pallas vs chunked rel err: {ebsr:.2e}")
    if not ebsr < 1e-4:
        print("BSR MISMATCH", file=sys.stderr)
        return 1

    print("tpu_smoke ok: flash fwd+bwd (1k f32, 1k bf16, 64k 512-block) and "
          "BSR manual-DMA kernel compile and match on chip")
    return 0


if __name__ == "__main__":
    sys.exit(main())
