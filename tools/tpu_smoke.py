"""Fast on-chip smoke for the Pallas attention kernels (fwd + two-pass bwd).

Run right after relay recovery, before the heavy bench batch: the backward
kernels (ops/flash_attention.py:flash_attention_panel_bwd) are validated in
interpret mode by the test suite, but their first real Mosaic compile happens
on the chip — this catches a Mosaic rejection in seconds instead of failing
the 256k lct_long config twenty minutes into the batch.

Exits 0 on pass; prints the failure and exits 1 otherwise (the recovery
runner logs but does not abort on it — the dense benches don't depend on
these kernels).
"""

import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    import marlin_tpu as mt
    from marlin_tpu.parallel.ring_attention import (attention_reference,
                                                    ring_attention)

    mesh = mt.create_mesh()
    rng = np.random.default_rng(0)
    seq, d = 1024, 128
    q, k, v = (jnp.asarray(rng.standard_normal((seq, d)).astype(np.float32))
               for _ in range(3))

    out = ring_attention(q, k, v, mesh, causal=True, backend="flash")
    ref = attention_reference(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    print(f"flash fwd rel err: {err:.2e}")
    if not err < 1e-3:
        print("FWD MISMATCH", file=sys.stderr)
        return 1

    gq, gk, gv = jax.jit(jax.grad(
        lambda qq, kk, vv: jnp.sum(ring_attention(
            qq, kk, vv, mesh, causal=True, backend="flash")),
        argnums=(0, 1, 2)))(q, k, v)
    _, vjp = jax.vjp(lambda qq, kk, vv: attention_reference(
        qq, kk, vv, causal=True), q, k, v)
    oq, ok, ov = vjp(jnp.ones((seq, d), jnp.float32))
    for name, got, want in (("dq", gq, oq), ("dk", gk, ok), ("dv", gv, ov)):
        e = float(jnp.max(jnp.abs(got - want)) /
                  jnp.maximum(jnp.max(jnp.abs(want)), 1e-30))
        print(f"flash bwd {name} rel err: {e:.2e}")
        if not e < 1e-3:
            print(f"BWD {name} MISMATCH", file=sys.stderr)
            return 1
    print("tpu_smoke ok: flash fwd + two-pass bwd compile and match on chip")
    return 0


if __name__ == "__main__":
    sys.exit(main())
