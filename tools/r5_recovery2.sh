#!/bin/bash
# Round-5 recovery watcher, generation 2.
#
# The relay recovered at 03:43, the staged batch banked the critical numbers
# (headline 57.5 TF/s, dense bf16, LU/Chol schedules, BSR shoot-out, lct 32k,
# decode, NN, streaming split, and execution-validation of the context
# envelope through 1M tokens), then the relay PROCESS died ~04:40 mid-way
# through the 2M-token probe step. This watcher waits for the next relay
# resurrection and runs ONLY the still-unmeasured legs, most-critical-first.
# The 2M probe configs are deliberately EXCLUDED: they are the prime suspect
# for the relay death, and the remaining timing legs + the driver's
# round-end bench.py matter more than one more envelope point.
#
# Discipline unchanged: one TPU client at a time, no kills, no timed phase
# under CPU contention, no batch on a CPU-fallback backend.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/r5_recovery2.log
exec >>"$LOG" 2>&1

exec 9>/tmp/r5_recovery2.lock
flock -n 9 || { echo "another r5_recovery2 instance holds the lock; exiting"; exit 0; }

ts() { date -u +%H:%M:%S; }

tpu_clients() {
  pgrep -af "import jax|bench\.py|bench_all\.py|tpu_smoke|hbm_probe" \
    2>/dev/null | grep -v "claude -p" | grep -v "r5_recovery2" | grep -q .
}
cpu_load() {
  pgrep -af "pytest" 2>/dev/null | grep -v "claude -p" | grep -q .
}

# split gates (round-3 verdict): only true TPU clients block the PROBE —
# cpu_load (pytest) must not starve it through a short recovery window; the
# timed batch below additionally defers on cpu_load.
while true; do
  while tpu_clients; do
    echo "$(ts) waiting for in-flight TPU client to exit"
    sleep 60
  done
  echo "$(ts) probing"
  out=$(python -c "import jax; d = jax.devices(); print('NDEV', len(d), d[0].platform)" 2>&1 | grep -E "NDEV|Error" | tail -1)
  echo "$(ts) probe: $out"
  case "$out" in
    NDEV*cpu*) echo "$(ts) cpu fallback — not recovery" ;;
    NDEV*) break ;;
  esac
  sleep 180
done

export MARLIN_BENCH_ROUND=r5
echo "$(ts) RECOVERED (gen 2) — relay is alive"
while cpu_load; do
  echo "$(ts) deferring timed batch: heavy CPU load (pytest) running"
  sleep 60
done

echo "$(ts) [1] pallas smoke"
if python tools/tpu_smoke.py; then SMOKE_OK=1; else SMOKE_OK=0; fi

if [ "$SMOKE_OK" = 1 ]; then
  echo "$(ts) [2] long-context: lct_long + attn_long at 256k"
  python bench_all.py lct_long attn_long

  echo "$(ts) [3] decode prompt sweep (flash prefill legs)"
  python bench_all.py decode
else
  # no decode salvage run here: the non-flash decode legs (single/batch8/
  # batch64) were already measured and banked earlier this session; only
  # the flash-prefill prompt sweep is missing, and it needs the smoke
  echo "$(ts) smoke failed — skipping flash legs"
fi

echo "$(ts) [4] refresh of remaining round-2 configs"
python bench_all.py attn acc 1 2 5 als pr svd

if [ "$SMOKE_OK" = 1 ]; then
  echo "$(ts) [5] escalation: 512k"
  MARLIN_BENCH_LCT_SEQ=524288 MARLIN_BENCH_ATTN_SEQ=524288 \
    python bench_all.py lct_long attn_long

  echo "$(ts) [6] escalation: 1M (bf16 lct; attn f32 fits)"
  MARLIN_BENCH_LCT_SEQ=1048576 MARLIN_BENCH_ATTN_SEQ=1048576 \
    MARLIN_BENCH_LCT_DTYPE=bfloat16 python bench_all.py lct_long attn_long
fi

echo "$(ts) gen-2 batch done"
