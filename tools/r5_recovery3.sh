#!/bin/bash
# Round-5 recovery watcher, generation 3.
#
# Gen-2 history: relay recovered 06:28 (container restart), the refresh
# batch banked attn/acc/1/2/5, then the relay's upstream connection died
# mid-ALS (~06:43) — the bench client is asleep forever with NO open socket
# (verified via /proc/<pid>/fd: its transport is gone, it can never wake or
# resume; killing TPU clients is what wedged rounds 1-2, so it is abandoned,
# not killed). The pallas smoke had FAILED before that batch: the restarted
# runtime's default matmul precision ran the then-unpinned flash kernel dots
# single-pass bf16 (3.03e-3 vs oracle). The kernel dots are now pinned
# bf16_3x (ops/flash_attention._DOT_PREC), so on the next resurrection this
# watcher re-gates on the smoke and runs ONLY the still-unmeasured flash
# legs, most-critical-first. Known-dead client PIDs are excluded from the
# in-flight gate (they never exit).
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/r5_recovery3.log
exec >>"$LOG" 2>&1

exec 9>/tmp/r5_recovery3.lock
flock -n 9 || { echo "another r5_recovery3 instance holds the lock; exiting"; exit 0; }

DEAD=/tmp/r5_dead_clients
touch "$DEAD"

ts() { date -u +%H:%M:%S; }

tpu_clients() {
  pgrep -af "import jax|bench\.py|bench_all\.py|tpu_smoke|hbm_probe" \
    2>/dev/null | grep -v "claude -p" | grep -v "r5_recovery3" \
    | cut -d' ' -f1 | grep -v -x -F -f "$DEAD" | grep -q .
}

while true; do
  while tpu_clients; do
    echo "$(ts) waiting for in-flight (non-dead) TPU client to exit"
    sleep 60
  done
  echo "$(ts) probing"
  out=$(python -c "import jax; d = jax.devices(); print('NDEV', len(d), d[0].platform)" 2>&1 | grep -E "NDEV|Error" | tail -1)
  echo "$(ts) probe: $out"
  case "$out" in
    NDEV*cpu*) echo "$(ts) cpu fallback — not recovery" ;;
    NDEV*) break ;;
  esac
  sleep 180
done

export MARLIN_BENCH_ROUND=r5
echo "$(ts) RECOVERED (gen 3) — relay is alive"

echo "$(ts) [1] pallas smoke (pinned-precision kernels)"
if ! python tools/tpu_smoke.py; then
  echo "$(ts) smoke failing with the pinned kernels — needs diagnosis, not a batch"
  exit 1
fi

echo "$(ts) [2] long-context: lct_long + attn_long at 256k"
python bench_all.py lct_long attn_long

echo "$(ts) [3] decode prompt sweep (flash prefill legs)"
python bench_all.py decode

echo "$(ts) [4] attn re-run (pinned-kernel provenance)"
python bench_all.py attn

echo "$(ts) [5] escalation: 512k"
MARLIN_BENCH_LCT_SEQ=524288 MARLIN_BENCH_ATTN_SEQ=524288 \
  python bench_all.py lct_long attn_long

echo "$(ts) [6] escalation: 1M (bf16 lct; attn f32 fits)"
MARLIN_BENCH_LCT_SEQ=1048576 MARLIN_BENCH_ATTN_SEQ=1048576 \
  MARLIN_BENCH_LCT_DTYPE=bfloat16 python bench_all.py lct_long attn_long

echo "$(ts) [7] salvage of the legs the gen-2 hang ate: als pr svd"
python bench_all.py als pr svd

echo "$(ts) [8] new-family leg: MoE training throughput at the lct shape"
python bench_all.py moe

echo "$(ts) gen-3 batch done"
