#!/bin/bash
# TPU-relay recovery runner (round 5).
#
# The relay wedged at round end in rounds 1 AND 2, so the driver-captured
# bench was 0.0 four times. This script converts relay uptime into
# measurements the moment it appears: probe patiently (never killing a
# client — a SIGKILL mid-claim wedges the lease for hours), and on the first
# successful device enumeration run the measurement batch,
# most-critical-first, so a re-wedge mid-batch costs the least important
# numbers.
#
# Discipline (see ROADMAP.md environment caveats):
#   - one TPU client at a time (waits for any in-flight probe first)
#   - no timeouts/kills anywhere near a process that touched the backend
#   - no concurrent heavy CPU work while a TPU process runs
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/r5_recovery_runner.log
exec >>"$LOG" 2>&1

ts() { date -u +%H:%M:%S; }

# Two distinct gates (round-3 verdict: a single broad gate let any pytest run
# starve the probe for its whole duration, so a recovery window could be
# missed entirely):
#   tpu_clients  — processes that may hold / be claiming the relay lease.
#                  These BLOCK everything: overlapping clients wedge the lease.
#   cpu_load     — heavy CPU work (pytest). This does NOT block the probe —
#                  the probe is never killed, so starvation merely delays it —
#                  but it DOES defer the heavy measurement batch, because
#                  running benches under CPU contention yields garbage numbers
#                  and a starved *timed* phase is the documented wedge shape.
# Both matchers exclude the build driver, whose command line embeds a prompt
# containing these very file names.
tpu_clients() {
  # hbm_probe IS a claiming client (it inits the backend); orphaned probes
  # from killed runner loops are too — only the build driver is excluded
  # (its cmdline embeds these very file names inside its prompt).
  pgrep -af "import jax|bench\.py|bench_all\.py|tpu_smoke|hbm_probe" \
    2>/dev/null | grep -v "claude -p" | grep -q .
}
cpu_load() {
  pgrep -af "pytest" 2>/dev/null | grep -v "claude -p" | grep -q .
}

while true; do
  while tpu_clients; do
    echo "$(ts) waiting for in-flight TPU client to exit"
    sleep 60
  done
  echo "$(ts) probing"
  out=$(python -c "import jax; d = jax.devices(); print('NDEV', len(d), d[0].platform)" 2>&1 | grep -E "NDEV|Error" | tail -1)
  echo "$(ts) probe: $out"
  # require a non-CPU platform: a CPU-fallback init must NOT start the batch
  case "$out" in
    NDEV*cpu*) echo "$(ts) cpu fallback — not recovery" ;;
    NDEV*) break ;;
  esac
  sleep 180
done

export MARLIN_BENCH_ROUND=r5  # provenance label for every bench_all entry
echo "$(ts) RECOVERED — relay is alive"
while cpu_load; do
  echo "$(ts) deferring measurement batch: heavy CPU load (pytest) running"
  sleep 60
done
echo "$(ts) measurement batch starts"

echo "$(ts) [1/6] bench.py headline"
# the runner's own patient probe just succeeded; skip bench.py's
# subprocess probe (its timeout SIGKILL is itself a wedge risk)
MARLIN_BENCH_SKIP_PROBE=1 python bench.py >BENCH_PROBE_r5.json
echo "$(ts) headline: $(cat BENCH_PROBE_r5.json)"

echo "$(ts) [1b/6] pallas kernel smoke (first Mosaic compile of the bwd)"
if python tools/tpu_smoke.py; then
  SMOKE_OK=1
else
  SMOKE_OK=0
  echo "$(ts) SMOKE FAILED — skipping flash-dependent long-context configs"
fi

echo "$(ts) [2/6] bench_all: previously-run shapes (fresh numbers) + decode"
# decode's prompt sweep crosses the flash-prefill threshold — flash-gated
if [ "$SMOKE_OK" = 1 ]; then
  python bench_all.py 3 bf16 lu chol lct nn decode
else
  MARLIN_BENCH_DECODE_SWEEP=0 python bench_all.py 3 bf16 lu chol lct nn decode
fi

echo "$(ts) [3/6] bench_all: new configs (riskier, after the safe ones)"
if [ "$SMOKE_OK" = 1 ]; then
  python bench_all.py lct_long attn_long bsr 4
else
  python bench_all.py bsr 4
fi

echo "$(ts) [3b/6] HBM high-water on-chip vs AOT prediction (verdict r4 #2)"
python tools/hbm_probe.py || echo "$(ts) hbm_probe failed (non-fatal)"

if [ "$SMOKE_OK" = 1 ]; then
  echo "$(ts) [4/6] long-context escalation: 512k"
  MARLIN_BENCH_LCT_SEQ=524288 MARLIN_BENCH_ATTN_SEQ=524288 \
    python bench_all.py lct_long attn_long

  echo "$(ts) [5/6] long-context escalation: 1M (bf16 — f32 exceeds HBM at 1M"
  echo "            per AOT_MEMORY.json; attn fwd fits at f32 either way)"
  MARLIN_BENCH_LCT_SEQ=1048576 MARLIN_BENCH_ATTN_SEQ=1048576 \
    MARLIN_BENCH_LCT_DTYPE=bfloat16 python bench_all.py lct_long attn_long
else
  echo "$(ts) [4-5/6] skipped (smoke failed)"
fi

echo "$(ts) [6] refresh of remaining round-2 configs (lowest priority)"
python bench_all.py 1 2 attn acc als pr svd 5

echo "$(ts) batch done"
