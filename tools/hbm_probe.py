#!/usr/bin/env python
"""On-chip HBM high-water vs the AOT compiler's prediction (verdict r4 #2).

AOT_MEMORY.json's `peak_bytes` is the TPU compiler's accounting against a raw
16 GiB budget; a real v5e reserves a slice of HBM for the runtime/framework,
so a "fits" with thin margin could still OOM on chip. This probe, run inside
the recovery batch (single TPU client, no timeouts — see tools/on_recovery.sh
and the relay discipline in ROADMAP.md):

1. reads the device's OWN budget: `memory_stats()["bytes_limit"]` is the
   usable HBM after runtime reservation — the number the docs' envelope
   table should be keyed to;
2. runs one lm_train_step per long-context config (ascending size, so each
   cumulative `peak_bytes_in_use` high-water is attributable to the config
   that just ran) and records measured peak vs AOT predicted peak;
3. writes HBM_ONCHIP.json: usable HBM, reserved bytes, and the
   predicted-vs-measured table for docs/parallelism.md.

An on-chip OOM is a *result* (the claim was wrong), not a tool crash: it is
recorded per-config and the probe continues with the smaller configs' data.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GIB = 1024 ** 3

# (label, seq, compute_dtype, mlp_chunk) — ascending predicted HBM per
# AOT_MEMORY.json (256k f32 3.79 GiB, 512k f32 7.56, 1M bf16 8.08,
# 1M f32 14.08, 2M bf16+mlp_chunk 14.18, 2M bf16 15.12) so each cumulative
# high-water is attributable to the config that just ran; plain 2M bf16 runs
# LAST because its 15.12 GiB prediction is the thinnest margin of any claim
# and the most likely to OOM against the runtime-reserved budget.
CONFIGS = [
    ("lct_long_262144", 262144, None, None),
    ("lct_long_524288", 524288, None, None),
    ("lct_long_bf16_1048576", 1048576, "bfloat16", None),
    ("lct_long_1048576", 1048576, None, None),
    # the round-5 packed-flash-state headline: 2M bf16 on one chip.
    # mlp_chunk=16384 matches the knob value docs/parallelism.md tells
    # users to set at 2M (the 14.18 GiB prediction is derived for it)
    ("lct_long_bf16_mlpchunk_2097152", 2097152, "bfloat16", 16384),
    ("lct_long_bf16_2097152", 2097152, "bfloat16", None),
]


def main():
    import jax

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("hbm_probe: CPU backend — nothing to measure", flush=True)
        return 1
    stats = dev.memory_stats() or {}
    limit = int(stats.get("bytes_limit", 0))
    out = {
        "device": str(dev.device_kind),
        "bytes_limit": limit,
        "usable_hbm_gib": round(limit / GIB, 3) if limit else None,
        "reserved_gib": round((16 * GIB - limit) / GIB, 3) if limit else None,
        "configs": {},
    }
    print(f"hbm_probe: usable HBM {out['usable_hbm_gib']} GiB "
          f"(runtime reserves {out['reserved_gib']} GiB of 16)", flush=True)

    try:
        with open(os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "AOT_MEMORY.json")) as f:
            aot = json.load(f)
    except (FileNotFoundError, ValueError):
        aot = {}

    import jax.numpy as jnp
    import numpy as np
    import optax

    import marlin_tpu as mt  # noqa: F401
    from marlin_tpu.models.transformer import TransformerLM, lm_train_step

    for label, seq, cd, mlp_chunk in CONFIGS:
        sec = "lct_long_bf16" if cd else "lct_long"
        # AOT_MEMORY.json has no mlp_chunk section; docs/parallelism.md
        # carries that prediction — leave pred unset rather than mislabel
        pred = (None if mlp_chunk else
                (aot.get(sec, {}).get(str(seq)) or {}).get("peak_bytes"))
        lm = TransformerLM(vocab=512, d_model=256, heads=2, layers=2,
                          attn="ring_flash", remat=True, loss_chunk=16384,
                          compute_dtype=cd)
        rec = {"seq": seq, "compute_dtype": cd, "mlp_chunk": mlp_chunk,
               "aot_peak_bytes": pred}
        try:
            pre_peak = int((dev.memory_stats() or {})
                           .get("peak_bytes_in_use", 0))
            params = lm.init_params()
            opt_state = optax.adam(lm.learning_rate).init(params)
            tokens = jnp.asarray(
                np.random.default_rng(0).integers(0, 512, seq), jnp.int32)
            params, opt_state, loss = lm_train_step(
                params, opt_state, tokens, jax.sharding.Mesh(
                    np.array(jax.devices()[:1]), ("rows",)),
                lm.heads, lm.attn, lm.remat, lm.precision, lm.learning_rate,
                lm.loss_chunk, lm.compute_dtype, mlp_chunk)
            rec["loss"] = float(loss)  # forces completion (sync point)
            del params, opt_state, tokens, loss
            peak = int((dev.memory_stats() or {}).get("peak_bytes_in_use", 0))
            if peak == 0 and pre_peak == 0:
                # the axon relay device exposes no memory_stats() at all —
                # there is no telemetry to read; the result here is that the
                # step EXECUTED at this size (fits proven by completion)
                rec["memory_stats_unavailable"] = True
                rec["note"] = ("device exposes no memory_stats(); 'fits' is "
                               "validated by the step running to completion, "
                               "no high-water number exists")
            else:
                rec["measured_peak_bytes"] = peak
                rec["measured_peak_gib"] = round(peak / GIB, 3)
                # peak_bytes_in_use is a device-LIFETIME high-water: if this
                # config did not set a new one, its true peak is only bounded
                # above by a predecessor's — an upper bound, not a measurement
                if peak <= pre_peak:
                    rec["clipped_by_predecessor"] = True
                    rec["note"] = ("true peak <= a predecessor's high-water; "
                                   "value is an upper bound only")
            if pred and peak > pre_peak:
                rec["measured_vs_aot"] = round(peak / pred, 3)
            if limit:
                rec["headroom_gib"] = round((limit - peak) / GIB, 3)
            measured = (f"measured {rec['measured_peak_gib']} GiB"
                        f"{' (clipped)' if peak <= pre_peak else ''}"
                        if "measured_peak_gib" in rec else
                        "ran to completion (no memory telemetry)")
            print(f"hbm_probe: {label}: {measured} (AOT predicted "
                  f"{round(pred / GIB, 3) if pred else '?'} GiB)", flush=True)
        except Exception as e:  # OOM on chip IS the finding — record it
            rec["error"] = str(e).split("\n")[0][:300]
            print(f"hbm_probe: {label}: FAILED — {rec['error']}", flush=True)
        out["configs"][label] = rec

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "HBM_ONCHIP.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"hbm_probe: wrote {path}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
