#!/bin/bash
# Round-5 follow-up: the flash-kernel legs the gen-2 batch skipped.
#
# The gen-2 smoke FAILED after the container restart: the new runtime's
# default matmul precision ran the (unpinned) Pallas kernel dots single-pass
# bf16 — rel err 3.03e-03 vs the pinned-precision oracle. The kernel dots are
# now pinned (ops/flash_attention.py:_HIGHEST), so this runner re-gates on
# the smoke and then runs exactly the legs gen-2 skipped: lct_long/attn_long
# at 256k, the decode prompt sweep, the 512k/1M escalations, plus a re-run
# of `attn` (its earlier r5 row was measured with the unpinned kernel).
#
# Discipline unchanged: one TPU client at a time, no kills.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/r5_flash_legs.log
exec >>"$LOG" 2>&1

exec 9>/tmp/r5_flash_legs.lock
flock -n 9 || { echo "another r5_flash_legs instance holds the lock; exiting"; exit 0; }

ts() { date -u +%H:%M:%S; }

tpu_clients() {
  pgrep -af "import jax|bench\.py|bench_all\.py|tpu_smoke|hbm_probe" \
    2>/dev/null | grep -v "claude -p" | grep -v "r5_flash_legs" | grep -q .
}

while tpu_clients; do
  echo "$(ts) waiting for in-flight TPU client to exit"
  sleep 60
done

export MARLIN_BENCH_ROUND=r5

echo "$(ts) [1] pallas smoke (pinned-precision kernels)"
if ! python tools/tpu_smoke.py; then
  echo "$(ts) smoke STILL failing — stopping so the mismatch can be diagnosed"
  exit 1
fi

echo "$(ts) [2] long-context: lct_long + attn_long at 256k"
python bench_all.py lct_long attn_long

echo "$(ts) [3] decode prompt sweep (flash prefill legs)"
python bench_all.py decode

echo "$(ts) [4] attn re-run (pinned kernel provenance)"
python bench_all.py attn

echo "$(ts) [5] escalation: 512k"
MARLIN_BENCH_LCT_SEQ=524288 MARLIN_BENCH_ATTN_SEQ=524288 \
  python bench_all.py lct_long attn_long

echo "$(ts) [6] escalation: 1M (bf16 lct; attn f32 fits)"
MARLIN_BENCH_LCT_SEQ=1048576 MARLIN_BENCH_ATTN_SEQ=1048576 \
  MARLIN_BENCH_LCT_DTYPE=bfloat16 python bench_all.py lct_long attn_long

echo "$(ts) flash-legs batch done"
