#!/usr/bin/env python
"""Compiler-verified HBM accounting for the long-context configs.

AOT-compiles the SAME jitted programs bench_all's `lct_long` / `attn_long`
configs execute — `lm_train_step` (ring flash attention + remat + chunked LM
head) and the ring flash forward — against a compile-only v5e topology
(utils/aot.py: libtpu, no chip, no relay), and records the TPU compiler's own
memory analysis per sequence length into AOT_MEMORY.json.

This is the evidence channel for the docs/parallelism.md HBM budget table:
the "compiler-verified" peak replaces hand arithmetic wherever the two
disagree. Run on-chip benches remain the throughput source of truth; this
tool proves *feasibility* (fits in 16 GB) and kernel *compilability* ahead
of relay uptime.

Usage: python tools/aot_report.py [seq ...]   (defaults: 262144 524288 1048576)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # never touch the relay

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import marlin_tpu as mt  # noqa: E402
from marlin_tpu.models.transformer import TransformerLM  # noqa: E402
from marlin_tpu.parallel.ring_attention import ring_attention  # noqa: E402
from marlin_tpu.utils.aot import topology_mesh  # noqa: E402

GIB = 1024 ** 3
V5E_HBM = 16 * GIB


def _usable_budget() -> int:
    """Measured usable HBM (HBM_ONCHIP.json) else raw minus the documented
    reserve — the same policy plan_context applies (round-4 verdict #2: a
    'fits' against the 16 GiB sticker can still OOM on chip)."""
    from marlin_tpu.models.planner import usable_hbm_bytes

    return usable_hbm_bytes(V5E_HBM)


def _mem(compiled):
    ma = compiled.memory_analysis()
    out = {
        "argument_bytes": ma.argument_size_in_bytes,
        "output_bytes": ma.output_size_in_bytes,
        "temp_bytes": ma.temp_size_in_bytes,
        "peak_bytes": ma.peak_memory_in_bytes,
        "peak_gib": round(ma.peak_memory_in_bytes / GIB, 3),
        "fits_16gib": ma.peak_memory_in_bytes < V5E_HBM,
        "fits_usable_hbm": ma.peak_memory_in_bytes < _usable_budget(),
    }
    host = getattr(ma, "host_temp_size_in_bytes", 0)
    if host:  # offloaded residuals live here, not in device HBM
        out["host_temp_bytes"] = host
    return out


def lct_train_step(seq: int, mesh, compute_dtype=None,
                   offload: bool = False, mlp_chunk=None,
                   n_experts=None, moe_group=8192) -> dict:
    """AOT-compile one lct_long training step (same knobs as config_lct_long:
    d256/h2/l2/v512, remat, loss_chunk=16k, ring_flash; optionally the bf16
    activation path, host-offloaded residuals, the chunked FFN, or the MoE
    FFN — ``n_experts`` swaps in grouped GShard top-2 routing + Switch aux,
    the row proving expert routing keeps long-context memory linear in
    seq)."""
    from marlin_tpu.utils.aot import trace_lm_train_step

    lm = TransformerLM(vocab=512, d_model=256, heads=2, layers=2,
                      attn="ring_flash", remat=True, loss_chunk=16384,
                      compute_dtype=compute_dtype, mlp_chunk=mlp_chunk,
                      offload_residuals=offload, n_experts=n_experts,
                      moe_group=moe_group)
    t0 = time.time()
    with mt.config_context(pallas_interpret=False):
        compiled = trace_lm_train_step(lm, seq, mesh).lower().compile()
    out = _mem(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    return out


def moe_train_step(seq: int, mesh) -> dict:
    """The MoE row of the report (docs/parallelism.md "Expert
    parallelism"): the shared lct recipe with 8 experts."""
    return lct_train_step(seq, mesh, n_experts=8)


def serve_bucket_report() -> dict:
    """Predicted vs planner-estimated serving memory, one table: for each
    bucket of the bench-serve model (bench_all config_serve: d128/h8/L4/
    v256, row-level), the TPU compiler's own ``memory_analysis()`` peak
    (``aot_compile_buckets`` — the real prefill + decode-step programs on a
    compile-only v5e topology) next to the planner's slab arithmetic
    (``bucket_kv_bytes * max_batch`` — what the admission gate charges) and
    the usable-HBM budget both are sized against. Where the two columns
    disagree, the compiler wins (round-4 verdict #2); the planner's number
    is what admission will *enforce*, so a planner underestimate here is an
    OOM waiting for traffic. Each row also records the measured
    ``peak_planner_ratio`` — planner honesty in one number; past 2x,
    ``aot_compile_buckets`` itself warns (serving.planner_ratio_warning).

    The ``calibration`` column is that ratio clamped to the admission
    multiplier range ([1, 32]): with ``serve_admission_calibration`` on,
    the engine multiplies its per-bucket admission charge by exactly this
    number (obs/memledger.admission_ratio reads it back through
    models/planner.bucket_calibration, keyed on ``program_key`` /
    ``program_key_slab`` so only the program it was measured for can
    inherit it), which is what brings the calibrated estimate within the
    acceptance band of the compiler-measured peak instead of 4-5x under."""
    from marlin_tpu import get_config
    from marlin_tpu.serving import aot_compile_buckets, bucket_kv_bytes
    from marlin_tpu.serving.batcher import bucket_program_key
    from marlin_tpu.serving.kvpool import paged_program_key

    heads, max_batch = 8, 8
    buckets = ((64, 32), (256, 32))
    lm = TransformerLM(vocab=256, d_model=128, heads=heads, layers=4, seed=0)
    params = lm.init_params()
    t0 = time.time()
    compiled = aot_compile_buckets(params, heads, buckets, max_batch)
    budget = _usable_budget()
    page_len = get_config().serve_page_len
    out = {"model": "d128/h8/L4/v256 (bench_all config_serve)",
           "max_batch": max_batch, "usable_hbm_budget_bytes": budget,
           "compile_s": round(time.time() - t0, 1), "buckets": {}}
    # steady-state residency sums over buckets (the engine never frees a
    # slab); program peak is per dispatched bucket
    slab_total = 0
    print(f"  {'bucket':>10} {'compiler peak':>14} {'planner slab':>13} "
          f"{'peak/plan':>10} {'calib':>6} {'of budget':>10}")
    for b in buckets:
        slab = bucket_kv_bytes(params, heads, b, batch=max_batch)
        slab_total += slab
        peak = compiled[b]
        ratio = round(peak / slab, 3) if slab else None
        calib = min(max(ratio, 1.0), 32.0) if ratio else None
        out["buckets"][f"{b[0]}x{b[1]}"] = {
            "compiler_peak_bytes": int(peak),
            "planner_slab_bytes": int(slab),
            "peak_planner_ratio": ratio,
            "calibration": calib,
            "calibrated_bytes": int(slab * calib) if calib else None,
            "program_key": paged_program_key(params, b, max_batch,
                                             page_len),
            "program_key_slab": bucket_program_key(params, b, max_batch),
            "peak_frac_of_budget": round(peak / budget, 4),
        }
        print(f"  {b[0]:>7}x{b[1]:<2} {peak:>14} {slab:>13} "
              f"{peak / slab if slab else 0:>10.2f} {calib or 0:>6.2f} "
              f"{peak / budget:>9.2%}")
    out["planner_slab_total_bytes"] = int(slab_total)
    out["fits_usable_hbm"] = slab_total + max(compiled.values()) < budget
    return out


def attn_forward(seq: int, mesh) -> dict:
    """AOT-compile the attn_long flash forward (d=128 head)."""
    rep = NamedSharding(mesh, P())
    a = jax.ShapeDtypeStruct((seq, 128), jnp.float32, sharding=rep)
    t0 = time.time()
    with mt.config_context(pallas_interpret=False):
        compiled = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True,
                                           backend="flash"),
        ).trace(a, a, a).lower().compile()
    out = _mem(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    return out


# bf16-only escalations past the f32 cliff: these run ONLY on the bf16 sweep
# (their f32 compiles are known-doomed hour-long OOMs) and are part of the
# default run so a plain `python tools/aot_report.py` regenerates every
# number the docs cite.
BF16_EXTRA_SEQS = [1572864, 2097152]

_REPORT_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "AOT_MEMORY.json")


def main(seqs):
    mesh = topology_mesh(("rows",), (1,))  # the single-chip bench shape
    # merge-update: a partial rerun (subset of seqs) must refresh its rows
    # without dropping the rest of the committed evidence
    try:
        with open(_REPORT_PATH) as f:
            report = json.load(f)
    except (FileNotFoundError, ValueError):
        report = {}
    report["topology"] = "v5e (compile-only, libtpu " + _libtpu_version() + ")"
    report["usable_hbm_budget_bytes"] = _usable_budget()
    report["usable_hbm_note"] = (
        "fits_usable_hbm is keyed to measured bytes_limit (HBM_ONCHIP.json) "
        "when the on-chip probe has run, else 16 GiB minus a 0.75 GiB "
        "runtime reserve (models/planner.usable_hbm_bytes)")
    report["program"] = (
        "lm_train_step d256/h2/l2/v512 remat+loss_chunk16k "
        "ring_flash (= bench_all config_lct_long) and the "
        "ring-flash causal forward at d=128 (= config_attn_long)")
    for sec in ("lct_long", "lct_long_bf16", "attn_long", "lct_long_4chip"):
        report.setdefault(sec, {})
    for seq in seqs:
        print(f"[aot] lct_long seq={seq} ...", flush=True)
        report["lct_long"][str(seq)] = r = _try(lct_train_step, seq, mesh)
        print(f"  {_fmt(r)}", flush=True)
    for seq in list(seqs) + BF16_EXTRA_SEQS:
        print(f"[aot] lct_long_bf16 seq={seq} ...", flush=True)
        report["lct_long_bf16"][str(seq)] = r = _try(
            lambda s, m: lct_train_step(s, m, compute_dtype="bfloat16"),
            seq, mesh)
        print(f"  {_fmt(r)}", flush=True)
    # host-offloaded residuals + chunked FFN on top of bf16: the knobs that
    # push past the single-chip cliff (r4 verdict #5) — 1M as a sanity delta
    # vs plain bf16, then the 2M+ escalations
    report.setdefault("lct_long_bf16_offload", {})
    for seq in [1048576, 2097152, 3145728]:
        print(f"[aot] lct_long_bf16_offload seq={seq} ...", flush=True)
        report["lct_long_bf16_offload"][str(seq)] = r = _try(
            lambda s, m: lct_train_step(s, m, compute_dtype="bfloat16",
                                        offload=True, mlp_chunk=16384),
            seq, mesh)
        print(f"  {_fmt(r)}", flush=True)
    for seq in seqs:
        print(f"[aot] attn_long seq={seq} ...", flush=True)
        report["attn_long"][str(seq)] = r = _try(attn_forward, seq, mesh)
        print(f"  {_fmt(r)}", flush=True)
    # MoE at the first (256k-class) rung: expert routing must not bend the
    # linear-in-seq memory story
    report.setdefault("moe_long_e8", {})
    for seq in seqs[:1]:
        print(f"[aot] moe_long_e8 seq={seq} ...", flush=True)
        report["moe_long_e8"][str(seq)] = r = _try(moe_train_step, seq, mesh)
        print(f"  {_fmt(r)}", flush=True)
    # multi-chip: the budget table's "p chips train p× the context at the
    # same per-chip residency" claim, compiler-verified on a real 4-chip v5e
    # topology (ring over ICI). memory_analysis is per device.
    mesh4 = topology_mesh(("rows",), (4,), topology_name="v5e:2x2")
    for seq, cd in ((4 * seqs[-1], "bfloat16"), (seqs[-1], None)):
        label = f"{seq}{'_bf16' if cd else ''}"
        print(f"[aot] lct_long_4chip {label} ...", flush=True)
        report["lct_long_4chip"][label] = r = _try(
            lambda s, m: lct_train_step(s, m, compute_dtype=cd), seq, mesh4)
        print(f"  {_fmt(r)} (per chip)", flush=True)

    # serving buckets: compiler-predicted peak vs the planner's admission
    # arithmetic, next to the same usable-HBM budget (one table)
    print("[aot] serve_buckets (bench-serve model) ...", flush=True)
    try:
        report["serve_buckets"] = serve_bucket_report()
    except Exception as e:
        report["serve_buckets"] = {
            "error": str(e).split("\n")[0][:200]}
        print(f"  serve_buckets failed: {report['serve_buckets']['error']}",
              flush=True)

    with open(_REPORT_PATH, "w") as f:
        json.dump(report, f, indent=2)
    print("wrote AOT_MEMORY.json")


def _try(fn, seq, mesh) -> dict:
    """An over-HBM configuration is a *result* (the compiler locating the
    cliff), not a tool crash: record the compiler's own accounting."""
    from marlin_tpu.utils.aot import parse_hbm_oom

    try:
        return fn(seq, mesh)
    except Exception as e:
        needed = parse_hbm_oom(e)
        return {
            "fits_16gib": False,
            "error": (f"compiler: needs {needed / GIB:.2f}G HBM"
                      if needed else str(e).split("\n")[0][:200]),
        }


def _fmt(r: dict) -> str:
    if "error" in r:
        return f"OVER HBM — {r['error']}"
    return (f"peak {r['peak_gib']} GiB, temps {r['temp_bytes'] / GIB:.3f} GiB, "
            f"compile {r['compile_s']}s")


def _libtpu_version() -> str:
    try:
        import libtpu
        return getattr(libtpu, "__version__", "?")
    except ImportError:
        return "?"


if __name__ == "__main__":
    seqs = [int(a) for a in sys.argv[1:]] or [262144, 524288, 1048576]
    main(seqs)
