"""lock-discipline: cross-thread attribute writes must hold the owning lock.

The serving stack is a handful of classes whose methods run on several
threads at once: the caller's thread (``submit``/``drain``/``close``/the
public API), the worker loop (``threading.Thread(target=self._run...)``),
the supervisor's monitor thread (which calls back into
``ServeEngine._recover``), the migration mailbox (serviced on the worker,
posted from peers), and the obs HTTP server (health/flight handlers).
PR 8's review pass caught a superseded worker mutating the replacement's
KV pool — exactly the class of bug this check makes structural.

Model: per target class, build the intra-class call graph, seed it with
the *thread entry points* (public methods = the caller domain, each
``Thread(target=self.X)`` = a worker domain, plus the repo-aware hints
below for callback/handler entries), and propagate. A field written
outside ``__init__`` from methods spanning **two or more domains** must
have every such write either lexically inside ``with self.<*lock*>:`` or
carry the ``# analyze: single-writer`` annotation (which documents the
single-writer claim class-wide for that field).

Target classes: the known concurrent surface (ServeEngine, Router,
Supervisor, PagedKVPool, ChunkPrefetcher) plus any class that spawns a
thread on one of its own methods — fixture classes and future subsystems
are picked up without editing this list.
"""

from __future__ import annotations

import ast

from ..core import Finding, Repo, dotted

NAME = "lock-discipline"
SCOPE = "files"

#: always-analyzed classes (the concurrent serving surface)
KNOWN_CLASSES = {"ServeEngine", "Router", "Supervisor", "PagedKVPool",
                 "ChunkPrefetcher"}

#: repo-aware extra entry points: methods invoked from a thread the call
#: graph cannot see (callbacks, HTTP handlers, mailbox services)
ENTRY_HINTS: dict[str, dict[str, str]] = {
    # Supervisor._monitor calls engine._recover from the monitor thread
    "ServeEngine": {"_recover": "supervisor",
                    # registered as an obs health provider; runs on the
                    # HTTP server thread
                    "_health_info": "http"},
    # Router state is read by the health endpoint too
    "Router": {"_health_info": "http"},
}

#: methods treated as construction (happens-before the object escapes)
CONSTRUCTION = {"__init__", "__post_init__"}


def _lockish(expr: ast.AST) -> bool:
    """True for a with-item that names a lock: ``self._lock``,
    ``self._restart_lock``, a bare ``lock`` variable, ``self._cv`` ..."""
    d = dotted(expr)
    if d is None and isinstance(expr, ast.Call):
        d = dotted(expr.func)
    if d is None:
        return False
    leaf = d.split(".")[-1].lower()
    return "lock" in leaf or leaf in {"_mu", "_cv", "_cond", "cond"}


class _MethodScan(ast.NodeVisitor):
    """Per-method facts: self-calls, self-field writes (+lock context),
    thread targets."""

    def __init__(self):
        self.calls: set[str] = set()
        #: (field, line, under_lock)
        self.writes: list[tuple[str, int, bool]] = []
        self.thread_targets: set[str] = set()
        self._lock_depth = 0

    def visit_With(self, node: ast.With):
        locked = any(_lockish(item.context_expr) for item in node.items)
        self._lock_depth += 1 if locked else 0
        self.generic_visit(node)
        self._lock_depth -= 1 if locked else 0

    def _record_target(self, tgt: ast.AST, line: int):
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            self.writes.append((tgt.attr, line, self._lock_depth > 0))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_target(el, line)

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._record_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        d = dotted(node.func)
        if d is not None and d.startswith("self."):
            parts = d.split(".")
            if len(parts) == 2:
                self.calls.add(parts[1])
        if d is not None and d.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    t = dotted(kw.value)
                    if t is not None and t.startswith("self."):
                        self.thread_targets.add(t.split(".")[1])
        self.generic_visit(node)

    # nested defs run on the same thread as their caller; scan them too
    # (closures registered elsewhere are covered by ENTRY_HINTS)


def _spawns_thread(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is not None and d.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    t = dotted(kw.value) or ""
                    if kw.arg == "target" and t.startswith("self."):
                        return True
    return False


def _analyze_class(sf, cls: ast.ClassDef) -> list[Finding]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    scans: dict[str, _MethodScan] = {}
    for name, fn in methods.items():
        sc = _MethodScan()
        for stmt in fn.body:
            sc.visit(stmt)
        scans[name] = sc

    # entry -> domain label
    entries: dict[str, str] = {}
    for name in methods:
        if not name.startswith("_") or name in {"__enter__", "__exit__"}:
            entries[name] = "caller"
    for name, sc in scans.items():
        for tgt in sc.thread_targets:
            if tgt in methods:
                entries[tgt] = f"worker:{tgt}"
    for m, dom in ENTRY_HINTS.get(cls.name, {}).items():
        if m in methods:
            entries[m] = dom
    for m in CONSTRUCTION:
        entries.pop(m, None)

    # propagate domains over the self-call graph
    domains: dict[str, set[str]] = {n: set() for n in methods}
    for entry, dom in entries.items():
        stack, seen = [entry], set()
        while stack:
            m = stack.pop()
            if m in seen or m not in methods:
                continue
            seen.add(m)
            domains[m].add(dom)
            stack.extend(scans[m].calls)

    # fields declared single-writer anywhere in the class body
    single_writer: set[str] = set()
    for name, sc in scans.items():
        for field, line, _ in sc.writes:
            if sf.annotated(line, "single-writer"):
                single_writer.add(field)

    # collect write sites per field (construction excluded)
    by_field: dict[str, list[tuple[str, int, bool]]] = {}
    for name, sc in scans.items():
        if name in CONSTRUCTION:
            continue
        for field, line, locked in sc.writes:
            by_field.setdefault(field, []).append((name, line, locked))

    findings = []
    for field, sites in sorted(by_field.items()):
        doms = set()
        for meth, _, _ in sites:
            doms |= domains.get(meth, set())
        if len(doms) < 2 or field in single_writer:
            continue
        for meth, line, locked in sites:
            if locked or sf.ignored(line, NAME):
                continue
            findings.append(Finding(
                check=NAME, path=sf.rel, line=line,
                message=(f"{cls.name}.{field} is written from thread "
                         f"domains {{{', '.join(sorted(doms))}}} but this "
                         f"write in {meth}() holds no lock"),
                hint=("wrap the write in `with self._lock:` (the owning "
                      "lock), or annotate the field's write with "
                      "`# analyze: single-writer` and say why it is "
                      "single-writer by design"),
                key=f"{NAME}:{sf.rel}:{cls.name}.{field}@{meth}"))
    return findings


def run(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name in KNOWN_CLASSES or _spawns_thread(node):
                findings.extend(_analyze_class(sf, node))
    return findings
