"""doc-sync: the registries and the docs that claim to mirror them.

Five sub-areas, each cross-referencing a source-of-truth registry against
the documentation (and secondary consumers) that enumerate it. Drift here
is invisible to every runtime test — the code works, the docs lie:

- **faults** — ``utils/faults.py`` ``KNOWN_POINTS`` vs the fault-point
  table in ``docs/robustness.md`` (both directions).
- **config** — ``MarlinConfig`` dataclass fields vs the knob table in
  ``docs/configuration.md``: undocumented fields, documented ghosts,
  *default-value drift* (the table's Default column is parsed, GiB/MiB and
  2^n notations included, and compared to the dataclass default), knobs no
  code ever reads (dead knob; a DEPRECATED comment on the field exempts
  it), and attribute reads off ``get_config()`` that name no field.
- **metrics** — every family registered in the package
  (``reg.counter/gauge/histogram("marlin_*", ...)``) vs the metric table in
  ``docs/observability.md`` (both directions), plus the bench scrape
  acceptance list (``bench_all.py``'s ``want`` tuple) ⊆ registered.
- **memory** — ``obs/memledger.py`` ``KNOWN_COMPONENTS`` (the HBM
  ledger's attribution vocabulary) vs the component table inside
  ``docs/observability.md``'s "Memory attribution" section (both
  directions): an undocumented component is a ledger slice no operator
  can interpret, a ghost row promises attribution nothing records.
- **events** — EventLog ``kind=`` literals and serving ``ev=``
  discriminators actually emitted vs the post-mortem vocabulary
  ``obs/report.py`` declares (``KNOWN_KINDS`` / ``KNOWN_SERVE_EVS``): a
  record kind the analyzer has never heard of is a black-box stream.

Each sub-area silently skips when its source files are absent, so the
check runs unchanged over the seeded fixture trees.
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, Repo, dotted, str_const
from .testcov import known_points

NAME = "doc-sync"
SCOPE = "repo"

CONFIG_REL = "marlin_tpu/config.py"
REPORT_REL = "marlin_tpu/obs/report.py"
MEMLEDGER_REL = "marlin_tpu/obs/memledger.py"
BENCH_REL = "bench_all.py"
DOC_ROBUST = "docs/robustness.md"
DOC_CONFIG = "docs/configuration.md"
DOC_OBS = "docs/observability.md"

_ROW_RE = re.compile(r"^\|\s*`")


def _doc_rows(text: str) -> dict[str, tuple[int, list[str]]]:
    """Backticked key(s) in the first column -> (lineno, remaining cells)
    for every markdown table row. A cell documenting several keys at once
    (``| `ckpt.write` / `ckpt.manifest` | ...``) yields every key."""
    rows: dict[str, tuple[int, list[str]]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if not _ROW_RE.match(line):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        for m in re.finditer(r"`([^`]+)`", cells[0]):
            rows.setdefault(m.group(1), (i, cells[1:]))
    return rows


# ------------------------------------------------------------------ faults

def _check_faults(repo: Repo, findings: list[Finding]) -> None:
    points, lineno = known_points(repo)
    doc = repo.text(DOC_ROBUST)
    if not points or doc is None:
        return
    rows = {k: v for k, v in _doc_rows(doc).items()
            if re.fullmatch(r"[a-z_]+\.[a-z_]+", k)}
    from .testcov import FAULTS_REL
    for pt in points:
        if pt not in rows:
            findings.append(Finding(
                check=NAME, path=FAULTS_REL, line=lineno,
                message=(f"fault point {pt!r} is in KNOWN_POINTS but has "
                         f"no row in {DOC_ROBUST}'s fault-point table"),
                hint=f"add a `{pt}` row (fires-from + blast radius)",
                key=f"{NAME}:faults:{pt}@undocumented"))
    for key, (line, _) in sorted(rows.items()):
        if key not in points:
            findings.append(Finding(
                check=NAME, path=DOC_ROBUST, line=line,
                message=(f"{DOC_ROBUST} documents fault point {key!r} "
                         f"which KNOWN_POINTS does not register"),
                hint="drop the row or register the point in utils/faults.py",
                key=f"{NAME}:faults:{key}@ghost"))


# ------------------------------------------------------------------ config

_UNIT_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([KMG])iB$")
_SUPERSCRIPTS = str.maketrans("⁰¹²³⁴⁵⁶⁷⁸⁹", "0123456789")


def _eval_const(node: ast.AST):
    """Constant value of a default expression; handles the repo's shift /
    power idioms (``1 << 30``, ``256 << 20``). None when not constant."""
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError, MemoryError):
        pass
    if isinstance(node, ast.BinOp):
        left, right = _eval_const(node.left), _eval_const(node.right)
        if isinstance(left, (int, float)) and isinstance(right, (int, float)):
            if isinstance(node.op, ast.LShift):
                return int(left) << int(right)
            if isinstance(node.op, ast.Pow):
                return left ** right
            if isinstance(node.op, ast.Mult):
                return left * right
    return None


def _parse_doc_default(s: str):
    """The Default cell: numbers, GiB/MiB units, 2^n superscripts, quoted
    strings, tuples, None. Falls back to the raw string."""
    s = s.strip().strip("`")
    m = _UNIT_RE.match(s)
    if m:
        return float(m.group(1)) * (
            1 << {"K": 10, "M": 20, "G": 30}[m.group(2)])
    if any(c in "⁰¹²³⁴⁵⁶⁷⁸⁹" for c in s):
        base = s.rstrip("⁰¹²³⁴⁵⁶⁷⁸⁹")
        exp = s[len(base):].translate(_SUPERSCRIPTS)
        if base.isdigit() and exp.isdigit():
            return int(base) ** int(exp)
    try:
        return ast.literal_eval(s)
    except (ValueError, TypeError, SyntaxError, MemoryError):
        return s


def _norm_str(v) -> str:
    return re.sub(r"[\s'\"]", "", str(v)).lower()


def _defaults_match(code_val, code_src: str, doc_val) -> bool:
    if code_val is not None and not isinstance(code_val, str):
        if isinstance(code_val, (int, float)) and isinstance(
                doc_val, (int, float)) and not isinstance(
                code_val, bool) and not isinstance(doc_val, bool):
            return float(code_val) == float(doc_val)
        if isinstance(doc_val, str):
            return _norm_str(code_val) == _norm_str(doc_val)
        return code_val == doc_val
    if code_val is None and isinstance(doc_val, str) \
            and doc_val.strip() in {"None", "none"}:
        # unevaluable code default documented as None: can't compare
        return True
    a, b = _norm_str(code_val if code_val is not None else code_src), \
        _norm_str(doc_val)
    # "jnp.float32" documents as "float32"
    return a == b or a.endswith("." + b) or b.endswith("." + a)


def _config_fields(repo: Repo):
    """field -> (lineno, default AST|None, deprecated?) from the first
    dataclass in config.py, plus the SourceFile."""
    sf = repo.file(CONFIG_REL)
    if sf is None or sf.tree is None:
        return {}, None
    cls = next((n for n in ast.walk(sf.tree)
                if isinstance(n, ast.ClassDef) and "Config" in n.name), None)
    if cls is None:
        return {}, sf
    fields = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            dep = False
            i = node.lineno - 1
            while i >= 1 and sf.lines[i - 1].strip().startswith("#"):
                if "DEPRECATED" in sf.lines[i - 1]:
                    dep = True
                i -= 1
            fields[node.target.id] = (node.lineno, node.value, dep)
    return fields, sf


def _check_config(repo: Repo, findings: list[Finding]) -> None:
    fields, sf = _config_fields(repo)
    if not fields or sf is None:
        return
    doc = repo.text(DOC_CONFIG)
    rows = _doc_rows(doc) if doc is not None else None

    if rows is not None:
        for name, (line, default, _) in sorted(fields.items()):
            if name not in rows:
                findings.append(Finding(
                    check=NAME, path=CONFIG_REL, line=line,
                    message=(f"config knob {name!r} has no row in "
                             f"{DOC_CONFIG}'s knob table"),
                    hint="document the knob (default + effect)",
                    key=f"{NAME}:config:{name}@undocumented"))
                continue
            doc_line, cells = rows[name]
            if default is None or not cells:
                continue
            doc_val = _parse_doc_default(cells[0])
            code_val = _eval_const(default)
            code_src = ast.unparse(default)
            if not _defaults_match(code_val, code_src, doc_val):
                findings.append(Finding(
                    check=NAME, path=DOC_CONFIG, line=doc_line,
                    message=(f"documented default for {name!r} "
                             f"({cells[0]!r}) != code default "
                             f"({code_src})"),
                    hint=f"sync the Default cell with {CONFIG_REL}",
                    key=f"{NAME}:config:{name}@default-drift"))
        for key, (line, _) in sorted(rows.items()):
            if re.fullmatch(r"[a-z][a-z0-9_]*", key) and key not in fields:
                findings.append(Finding(
                    check=NAME, path=DOC_CONFIG, line=line,
                    message=(f"{DOC_CONFIG} documents knob {key!r} which "
                             f"MarlinConfig does not define"),
                    hint="drop the row or add the field",
                    key=f"{NAME}:config:{key}@ghost"))

    # dead knob: a field no attribute read in the package ever names
    reads: set[str] = set()
    for src in repo.py_files():
        if src.rel == CONFIG_REL or src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and isinstance(node.ctx,
                                                              ast.Load):
                reads.add(node.attr)
    for name, (line, _, deprecated) in sorted(fields.items()):
        if name in reads or deprecated:
            continue
        if sf.ignored(line, NAME):
            continue
        findings.append(Finding(
            check=NAME, path=CONFIG_REL, line=line,
            message=(f"config knob {name!r} is never read anywhere in the "
                     f"package — setting it changes nothing"),
            hint=("wire the knob up, or mark its comment DEPRECATED "
                  "(keeping parse-compat) and say what replaced it"),
            key=f"{NAME}:config:{name}@dead-knob"))

    # reads off get_config() that name no field
    for src in repo.py_files():
        if src.tree is None:
            continue
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cfg_names: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and (dotted(node.value.func) or "").split(".")[-1] \
                        == "get_config":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            cfg_names.add(tgt.id)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)):
                    continue
                base = node.value
                is_cfg = (isinstance(base, ast.Call)
                          and (dotted(base.func) or "").split(".")[-1]
                          == "get_config") \
                    or (isinstance(base, ast.Name) and base.id in cfg_names)
                if is_cfg and node.attr not in fields \
                        and not node.attr.startswith("__") \
                        and not src.ignored(node.lineno, NAME):
                    findings.append(Finding(
                        check=NAME, path=src.rel, line=node.lineno,
                        message=(f"read of config attribute {node.attr!r} "
                                 f"which MarlinConfig does not define"),
                        hint="typo'd knob? set_config would reject it, but "
                             "a read raises only when reached",
                        key=(f"{NAME}:config:{node.attr}@unknown-read:"
                             f"{src.rel}:{fn.name}")))


# ----------------------------------------------------------------- metrics

def _registered_metrics(repo: Repo) -> dict[str, tuple[str, int]]:
    """name -> (rel, lineno) for every reg.counter/gauge/histogram family."""
    out: dict[str, tuple[str, int]] = {}
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in {"counter", "gauge", "histogram"}
                    and node.args):
                continue
            name = str_const(node.args[0])
            if name and name.startswith("marlin_") and name not in out:
                out[name] = (sf.rel, node.lineno)
    return out


def _bench_want(repo: Repo) -> list[tuple[str, int]]:
    sf = repo.file(BENCH_REL)
    if sf is None or sf.tree is None:
        return []
    out: list[tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        value = None
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "want"
                for t in node.targets):
            value = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name) and node.target.id == "want":
            value = node.value
        if isinstance(value, (ast.Tuple, ast.List)):
            for el in value.elts:
                s = str_const(el)
                if s and s.startswith("marlin_"):
                    out.append((s, el.lineno))
    return out


def _check_metrics(repo: Repo, findings: list[Finding]) -> None:
    registered = _registered_metrics(repo)
    if not registered:
        return
    doc = repo.text(DOC_OBS)
    if doc is not None:
        rows = {k: v for k, v in _doc_rows(doc).items()
                if k.startswith("marlin_")}
        for name, (rel, line) in sorted(registered.items()):
            if name not in rows:
                findings.append(Finding(
                    check=NAME, path=rel, line=line,
                    message=(f"metric family {name!r} is registered but "
                             f"has no row in {DOC_OBS}'s metric table"),
                    hint="add the row (type, labels, source)",
                    key=f"{NAME}:metrics:{name}@undocumented"))
        for name, (line, _) in sorted(rows.items()):
            if name not in registered:
                findings.append(Finding(
                    check=NAME, path=DOC_OBS, line=line,
                    message=(f"{DOC_OBS} documents metric {name!r} which "
                             f"nothing registers"),
                    hint="drop the row or restore the family",
                    key=f"{NAME}:metrics:{name}@ghost"))
    for name, line in _bench_want(repo):
        if name not in registered:
            findings.append(Finding(
                check=NAME, path=BENCH_REL, line=line,
                message=(f"bench scrape want-list expects {name!r} which "
                         f"nothing registers — the serve_obs acceptance "
                         f"record can never reach full marks"),
                hint="fix the want-list entry or register the family",
                key=f"{NAME}:metrics:{name}@bench-want"))


# ----------------------------------------------------------------- memory

_MEM_SECTION = "Memory attribution"


def _md_section(text: str, title: str) -> tuple[str | None, int]:
    """(section body, 0-based line offset) of the first markdown section
    whose heading contains ``title`` (case-insensitive), running to the
    next heading of the same or higher level; (None, 0) when absent."""
    lines = text.splitlines()
    start = level = None
    for i, ln in enumerate(lines):
        m = re.match(r"^(#+)\s+(.*)", ln)
        if not m:
            continue
        if start is None:
            if title.lower() in m.group(2).lower():
                start, level = i, len(m.group(1))
        elif len(m.group(1)) <= level:
            return "\n".join(lines[start:i]), start
    if start is not None:
        return "\n".join(lines[start:]), start
    return None, 0


def _known_components(repo: Repo) -> tuple[set | None, int]:
    """(KNOWN_COMPONENTS, lineno) parsed from obs/memledger.py."""
    sf = repo.file(MEMLEDGER_REL)
    if sf is None or sf.tree is None:
        return None, 0
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) \
                    and tgt.id == "KNOWN_COMPONENTS" \
                    and isinstance(node.value,
                                   (ast.Tuple, ast.List, ast.Set)):
                return ({el.value for el in node.value.elts
                         if isinstance(el, ast.Constant)
                         and isinstance(el.value, str)}, node.lineno)
    return None, 0


def _check_memory(repo: Repo, findings: list[Finding]) -> None:
    comps, lineno = _known_components(repo)
    if comps is None:
        return
    doc = repo.text(DOC_OBS)
    if doc is None:
        return
    sec, off = _md_section(doc, _MEM_SECTION)
    if sec is None:
        findings.append(Finding(
            check=NAME, path=DOC_OBS, line=1,
            message=(f"{DOC_OBS} has no {_MEM_SECTION!r} section but "
                     f"{MEMLEDGER_REL} defines KNOWN_COMPONENTS — the "
                     f"ledger's attribution vocabulary is undocumented"),
            hint="add the section with one row per ledger component",
            key=f"{NAME}:memory:section@missing"))
        return
    # component rows only: single lowercase slugs in the section's tables
    # (metric rows — marlin_mem_* — live in the metric table and are
    # cross-checked by the metrics sub-area)
    rows = {k: (line + off, cells)
            for k, (line, cells) in _doc_rows(sec).items()
            if re.fullmatch(r"[a-z][a-z0-9_]*", k)
            and not k.startswith("marlin_")}
    for comp in sorted(comps):
        if comp not in rows:
            findings.append(Finding(
                check=NAME, path=MEMLEDGER_REL, line=lineno,
                message=(f"ledger component {comp!r} is in "
                         f"KNOWN_COMPONENTS but has no row in {DOC_OBS}'s "
                         f"memory-attribution table"),
                hint=f"add a `{comp}` row (what registers it, lifetime)",
                key=f"{NAME}:memory:{comp}@undocumented"))
    for key, (line, _) in sorted(rows.items()):
        if key not in comps:
            findings.append(Finding(
                check=NAME, path=DOC_OBS, line=line,
                message=(f"{DOC_OBS} documents ledger component {key!r} "
                         f"which KNOWN_COMPONENTS does not define"),
                hint=(f"drop the row or add the component to "
                      f"KNOWN_COMPONENTS in {MEMLEDGER_REL}"),
                key=f"{NAME}:memory:{key}@ghost"))


# ------------------------------------------------------------------ events

def _known_sets(repo: Repo) -> tuple[set | None, set | None, int]:
    """(KNOWN_KINDS, KNOWN_SERVE_EVS, lineno) parsed from obs/report.py."""
    sf = repo.file(REPORT_REL)
    if sf is None or sf.tree is None:
        return None, None, 0
    kinds = evs = None
    line = 1
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            val = node.value
            if isinstance(val, ast.Call) and val.args:
                val = val.args[0]
            if not isinstance(val, (ast.Set, ast.Tuple, ast.List)):
                continue
            items = {el.value for el in val.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, str)}
            if tgt.id == "KNOWN_KINDS":
                kinds, line = items, node.lineno
            elif tgt.id == "KNOWN_SERVE_EVS":
                evs = items
    return kinds, evs, line


def _emitted_events(repo: Repo):
    """(kind -> first (rel, line), serve ev -> first (rel, line)) collected
    from the emission sites (AST literals only — docstrings don't count)."""
    kinds: dict[str, tuple[str, int]] = {}
    evs: dict[str, tuple[str, int]] = {}

    def note(d, name, sf, line):
        if name and name not in d:
            d[name] = (sf.rel, line)

    for sf in repo.py_files():
        if sf.tree is None or sf.rel == REPORT_REL:
            continue
        in_serving = "/serving/" in f"/{sf.rel}"
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                fn = dotted(node.func) or ""
                leaf = fn.split(".")[-1]
                recv = fn.rsplit(".", 1)[0].lower() if "." in fn else ""
                first = str_const(node.args[0]) if node.args else None
                if leaf in {"event", "timed"} and "log" in recv:
                    note(kinds, first, sf, node.lineno)
                    if first == "serve":
                        for kw in node.keywords:
                            if kw.arg == "ev":
                                note(evs, str_const(kw.value), sf,
                                     node.lineno)
                elif leaf in {"_log", "_log_event"}:
                    note(kinds, first, sf, node.lineno)
                elif leaf == "emit":
                    for kw in node.keywords:
                        if kw.arg == "kind":
                            note(kinds, str_const(kw.value), sf,
                                 node.lineno)
                elif leaf == "_emit" and in_serving:
                    for kw in node.keywords:
                        if kw.arg == "ev":
                            note(evs, str_const(kw.value), sf, node.lineno)
            elif isinstance(node, ast.Dict):
                keys = {str_const(k): v for k, v in zip(node.keys,
                                                        node.values)
                        if k is not None}
                if "kind" in keys and "t" in keys:
                    note(kinds, str_const(keys["kind"]), sf, node.lineno)
                if "ev" in keys and in_serving:
                    note(evs, str_const(keys["ev"]), sf, node.lineno)
    return kinds, evs


def _check_events(repo: Repo, findings: list[Finding]) -> None:
    known_kinds, known_evs, decl_line = _known_sets(repo)
    if known_kinds is None and known_evs is None:
        return
    kinds, evs = _emitted_events(repo)
    if known_kinds is not None:
        for kind, (rel, line) in sorted(kinds.items()):
            if kind not in known_kinds:
                findings.append(Finding(
                    check=NAME, path=rel, line=line,
                    message=(f"EventLog kind {kind!r} is emitted but "
                             f"missing from KNOWN_KINDS in {REPORT_REL} — "
                             f"obs.report has never heard of it"),
                    hint="add the kind to KNOWN_KINDS (and a report "
                         "section if generic per-kind latency isn't "
                         "enough)",
                    key=f"{NAME}:events:kind:{kind}@unknown"))
    if known_evs is not None:
        for ev, (rel, line) in sorted(evs.items()):
            if ev not in known_evs:
                findings.append(Finding(
                    check=NAME, path=rel, line=line,
                    message=(f"serve ev {ev!r} is emitted but missing "
                             f"from KNOWN_SERVE_EVS in {REPORT_REL}"),
                    hint="add it to KNOWN_SERVE_EVS (and teach the "
                         "serving section if it matters)",
                    key=f"{NAME}:events:ev:{ev}@unknown"))
        for ev in sorted(known_evs - set(evs)):
            findings.append(Finding(
                check=NAME, path=REPORT_REL, line=decl_line,
                message=(f"KNOWN_SERVE_EVS declares ev {ev!r} which no "
                         f"serving code emits"),
                hint="prune the stale entry or restore the emitter",
                key=f"{NAME}:events:ev:{ev}@stale"))


def run(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    _check_faults(repo, findings)
    _check_config(repo, findings)
    _check_metrics(repo, findings)
    _check_memory(repo, findings)
    _check_events(repo, findings)
    return findings
