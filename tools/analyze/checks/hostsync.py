"""host-sync: device->host synchronization inside the serving hot path.

A ``.item()``, ``float(x[i])``, ``np.asarray(device_value)`` or
``block_until_ready()`` inside the engine step / decode / prefill loop
stalls the Python thread on the accelerator stream — the exact dispatch
bubble the async-dispatch design (and the PR 11 fused decode kernel) exists
to avoid. One stray sync per decode step caps tokens/sec at the host
round-trip rate no matter how fast the kernel is.

Hot functions are selected by name (``_run*``, ``*step*``, ``*decode*``,
``*prefill*``, ``*worker*``, ``*loop*``, ``*hot*``) or opted in with a
``# analyze: hot-loop`` annotation on the ``def`` line. Inside them the
check flags:

- ``<expr>.item()`` / ``<expr>.block_until_ready()`` / ``jax.device_get``
- ``np.asarray(x)`` / ``np.array(x)`` where ``x`` is not a literal
  (literals build host arrays; names may be device values)
- ``float(x)`` / ``int(x)`` of a subscript or call result (scalar pull)

Intentional syncs (batching a transfer at a flush boundary, pulling the
sampled token because the host must see it) carry
``# analyze: ignore[host-sync]`` with the reason in prose.
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, Repo, dotted

NAME = "host-sync"
SCOPE = "files"

_HOT_NAME_RE = re.compile(
    r"(^_run|step|decode|prefill|worker|loop|hot)", re.IGNORECASE)

_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_ASARRAY_LEAVES = {"asarray", "array"}


def _is_hot(sf, fn) -> bool:
    if sf.annotated(fn.lineno, "hot-loop"):
        return True
    return bool(_HOT_NAME_RE.search(fn.name))


def _literal(node: ast.AST) -> bool:
    """Literal-ish expressions that can only build host data."""
    return isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                             ast.Set, ast.ListComp, ast.GeneratorExp))


def _host_names(fn) -> set[str]:
    """Names the function rebinds from np.asarray/np.array — after that,
    subscripting them is host-side indexing, not a device sync (the
    asarray itself is the sync, and it gets its own finding)."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = dotted(node.value.func) or ""
            if d.split(".")[0] in {"np", "numpy", "onp"} \
                    and d.split(".")[-1] in _ASARRAY_LEAVES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _sync_kind(node: ast.Call, host_names: set[str] = frozenset()
               ) -> str | None:
    d = dotted(node.func) or ""
    leaf = d.split(".")[-1]
    if leaf in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
        return f".{leaf}()"
    if d in _SYNC_CALLS:
        return f"{d}()"
    root = d.split(".")[0]
    if root in {"np", "numpy", "onp"} and leaf in _ASARRAY_LEAVES:
        if node.args and not _literal(node.args[0]):
            return f"{d}()"
        return None
    if d in {"float", "int"} and node.args and isinstance(
            node.args[0], (ast.Subscript, ast.Call)):
        arg = node.args[0]
        # host metadata, not device data: int(x.shape[i]), int(len(...)),
        # int(getattr(c, "nbytes", 0)), int(time.time()), int(os.environ[k])
        if isinstance(arg, ast.Subscript):
            base = dotted(arg.value) or ""
            if base.split(".")[-1] == "shape" or "environ" in base \
                    or base.split(".")[0] in host_names:
                return None
        if isinstance(arg, ast.Call):
            leaf = (dotted(arg.func) or "").split(".")[-1]
            if leaf in {"getattr", "len", "time", "perf_counter",
                        "monotonic", "get", "getenv"}:
                return None
        return f"{d}() of a device value"
    return None


class _HotScan(ast.NodeVisitor):
    def __init__(self, sf, fn, findings):
        self.sf, self.fn, self.findings = sf, fn, findings
        self.host_names = _host_names(fn)

    def visit_FunctionDef(self, node):
        # nested defs execute on the same hot path when called from it;
        # keep scanning them — unless the nested def is itself hot, in
        # which case it gets its own scan (avoid double-reporting)
        if not _is_hot(self.sf, node):
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        kind = _sync_kind(node, self.host_names)
        if kind is not None and not self.sf.ignored(node.lineno, NAME):
            self.findings.append(Finding(
                check=NAME, path=self.sf.rel, line=node.lineno,
                message=(f"{kind} inside hot function {self.fn.name}() "
                         f"forces a device->host sync on the step path"),
                hint=("keep the value on device (jnp ops / donated "
                      "updates), batch the transfer at a flush boundary, "
                      "or annotate `# analyze: ignore[host-sync]` with why "
                      "this sync is intentional"),
                key=(f"{NAME}:{self.sf.rel}:{self.fn.name}@{kind}"
                     f"#{node.lineno}")))
        self.generic_visit(node)


def run(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        # only the runtime packages have a hot path; benches and tests
        # measure whatever they like
        if sf.rel.startswith(("tests/", "bench", "tools/")):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_hot(sf, node):
                sc = _HotScan(sf, node, findings)
                for stmt in node.body:
                    sc.visit(stmt)
    return findings
