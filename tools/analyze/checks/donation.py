"""donation: arguments donated to a jitted program must not be read after
the call.

``donate_argnums`` hands the argument's buffer to XLA — after the call the
Python reference is a deleted array, and touching it raises (or, worse,
on some paths silently aliases freed memory). The repo's donated programs
are the serving KV-slab updaters (``_lm_prefill_slot_jit`` etc.,
models/transformer.py); the safe idiom is ``pool.pages =
_lm_decode_paged_jit(params, pool.pages, ...)`` — the donated reference is
overwritten by the very statement that consumes it.

Two passes, repo-wide:

1. Collect donated callables: module-scope ``@functools.partial(jax.jit,
   donate_argnums=...)`` / ``@jax.jit(...)`` decorations and ``name =
   jax.jit(fn, donate_argnums=...)`` assignments, keyed by *name* so
   imported call sites in other modules resolve.
2. At every call of a donated name, each donated positional argument that
   is a plain name/attribute chain is traced forward through the enclosing
   function: a load of the same chain after the call line — before the
   chain is reassigned — is a read-after-donation finding.

The forward trace is line-ordered (control flow is not modeled), which is
exactly the PR-8 idiom's shape; genuinely-safe reads on disjoint branches
can carry ``# analyze: ignore[donation]`` with the reason.
"""

from __future__ import annotations

import ast

from ..core import Finding, Repo, dotted

NAME = "donation"
SCOPE = "files"


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """donate_argnums of a jax.jit(...) call expression, else None."""
    d = dotted(call.func) or ""
    if d.split(".")[-1] == "partial":
        # functools.partial(jax.jit, donate_argnums=...)
        if not (call.args and (dotted(call.args[0]) or "").endswith("jit")):
            return None
    elif not d.endswith("jit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                out = []
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value,
                                                                   int):
                        out.append(el.value)
                return tuple(out)
            if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, int):
                return (kw.value.value,)
    return None


def collect_donated(repo: Repo) -> dict[str, tuple[int, ...]]:
    donated: dict[str, tuple[int, ...]] = {}
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donated_positions(dec)
                        if pos:
                            donated[node.name] = pos
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Call):
                pos = _donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            donated[tgt.id] = pos
    return donated


class _FnIndex(ast.NodeVisitor):
    """Loads and stores of dotted chains within one function, by line."""

    def __init__(self):
        self.loads: list[tuple[str, int]] = []
        self.stores: list[tuple[str, int]] = []
        self.calls: list[ast.Call] = []

    def _visit_chain(self, node, ctx):
        d = dotted(node)
        if d is not None:
            (self.stores if isinstance(ctx, (ast.Store, ast.Del))
             else self.loads).append((d, node.lineno))
            return True
        return False

    def visit_Call(self, node):
        self.calls.append(node)
        self.generic_visit(node)

    def visit_Name(self, node):
        self._visit_chain(node, node.ctx)

    def visit_Attribute(self, node):
        if not self._visit_chain(node, node.ctx):
            self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested scopes analyzed separately

    visit_AsyncFunctionDef = visit_FunctionDef


def _check_function(sf, fn, donated, findings):
    idx = _FnIndex()
    for stmt in fn.body:
        idx.visit(stmt)
    for node in idx.calls:
        d = dotted(node.func)
        callee = (d or "").split(".")[-1]
        if callee not in donated:
            continue
        end = getattr(node, "end_lineno", node.lineno)
        for pos in donated[callee]:
            if pos >= len(node.args):
                continue
            chain = dotted(node.args[pos])
            if chain is None or chain == "self":
                continue
            # first reassignment at/after the call (the consuming statement
            # itself counts: `x = f(x)` re-binds x)
            re_lines = [ln for c, ln in idx.stores
                        if c == chain and ln >= node.lineno]
            rebound = min(re_lines) if re_lines else None
            for c, ln in idx.loads:
                if c != chain or ln <= end:
                    continue
                if rebound is not None and ln >= rebound:
                    continue
                if sf.ignored(ln, NAME):
                    continue
                findings.append(Finding(
                    check=NAME, path=sf.rel, line=ln,
                    message=(f"`{chain}` is read after being donated to "
                             f"{callee}() (arg {pos}, donated via "
                             f"donate_argnums) at line {node.lineno}; the "
                             f"buffer is deleted by the call"),
                    hint=("rebind the result over the donated reference "
                          f"(`{chain} = {callee}(...)`) before any further "
                          "read, or drop donation for this argument"),
                    key=(f"{NAME}:{sf.rel}:{fn.name}.{chain}"
                         f"@{callee}")))


def run(repo: Repo) -> list[Finding]:
    donated = collect_donated(repo)
    if not donated:
        return []
    findings: list[Finding] = []
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(sf, node, donated, findings)
    return findings
