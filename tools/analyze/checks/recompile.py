"""recompile: jit programs created where they cannot be cached.

The PR 4 bug class: ``streamed_matmul`` built ``jax.jit(...)`` inside the
per-call path, so every invocation traced and compiled from scratch —
correct results, 100x the latency, invisible without the compile-count
fixture. Statically visible shapes of the same hazard:

1. **closure-jit** — ``jax.jit`` created inside a function (the returned
   program's cache dies with the frame, and closures over per-call Python
   values silently specialize). Allowed when the enclosing function is
   itself memoized (``functools.lru_cache`` / ``functools.cache`` — the
   repo idiom for mesh-keyed program factories) at any enclosing level.
2. **jit-in-loop** — ``jax.jit`` called inside a ``for``/``while`` body:
   a fresh program per iteration, never cacheable.
3. **traced-knob** — a ``get_config()`` read inside a jit-decorated
   function body: the knob is baked in at trace time, so flipping the
   config silently does nothing until an unrelated retrace (these should
   be traced array arguments, or read by the caller and passed in).
"""

from __future__ import annotations

import ast

from ..core import Finding, Repo, dotted

NAME = "recompile"
SCOPE = "files"

_CACHE_DECOS = {"lru_cache", "cache", "cached_property"}


def _is_jit_call(node: ast.Call) -> bool:
    d = dotted(node.func) or ""
    if d in {"jax.jit", "jit", "pjit", "jax.pjit"}:
        return True
    # functools.partial(jax.jit, ...) builds a jit when later applied; the
    # partial itself is the creation site
    if d.split(".")[-1] == "partial" and node.args:
        return (dotted(node.args[0]) or "").endswith("jit")
    return False


def _is_cached_fn(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        d = dotted(dec if not isinstance(dec, ast.Call) else dec.func) or ""
        if d.split(".")[-1] in _CACHE_DECOS:
            return True
    return False


def _is_jitted_fn(fn) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and _is_jit_call(dec):
            return True
        d = dotted(dec) or ""
        if d in {"jax.jit", "jit"}:
            return True
    return False


def _scan(sf, node, fn_stack, loop_depth, findings, in_decorator=False):
    """Recursive walk tracking the enclosing function stack and loop depth."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        # a bare-@jax.jit decoration on a def nested inside a function is a
        # jit creation with no Call node — same closure-jit hazard
        enclosing_fns = [f for f in fn_stack
                         if isinstance(f, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]
        if (enclosing_fns and _is_jitted_fn(node)
                and not any(_is_cached_fn(f) for f in enclosing_fns)
                and not sf.ignored(node.lineno, NAME)):
            findings.append(Finding(
                check=NAME, path=sf.rel, line=node.lineno,
                message=(f"jitted {node.name}() defined inside "
                         f"{enclosing_fns[-1].name}() — the compile cache "
                         f"dies with the call frame and closed-over Python "
                         f"values re-specialize it per call (the PR 4 "
                         f"streamed_matmul bug class)"),
                hint="move the jit to module scope, or memoize the factory "
                     "with @functools.lru_cache keyed on everything the "
                     "program closes over",
                key=f"{NAME}:{sf.rel}:{enclosing_fns[-1].name}"
                    f".{node.name}@closure"))
        # decorators evaluate at def time in the OUTER scope, and a jitted
        # decoration is reported by the def-based branch above — visit them
        # with the outer stack and the Call-based jit check muted
        for dec in getattr(node, "decorator_list", ()):
            _scan(sf, dec, fn_stack, loop_depth, findings, in_decorator=True)
        fn_stack = fn_stack + [node]
        loop_depth = 0  # a loop outside a def does not loop the def body
        for name, field in ast.iter_fields(node):
            if name == "decorator_list":
                continue
            children = field if isinstance(field, list) else [field]
            for child in children:
                if isinstance(child, ast.AST):
                    _scan(sf, child, fn_stack, loop_depth, findings)
        return
    in_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
    if in_loop:
        loop_depth += 1

    if isinstance(node, ast.Call) and _is_jit_call(node) and not in_decorator:
        line = node.lineno
        if not sf.ignored(line, NAME):
            if loop_depth > 0:
                findings.append(Finding(
                    check=NAME, path=sf.rel, line=line,
                    message="jax.jit program created inside a loop body — "
                            "one fresh trace+compile per iteration",
                    hint="hoist the jit to module scope (or a memoized "
                         "factory) and call the cached program in the loop",
                    key=f"{NAME}:{sf.rel}:loop@{line}"))
            else:
                enclosing = [f for f in fn_stack
                             if isinstance(f, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))]
                # decorator expressions evaluate at def time in the OUTER
                # scope: a jit decorating a module-scope def is fine even
                # though the Call node sits on the FunctionDef
                deco_of = enclosing[-1] if enclosing else None
                if (deco_of is not None and node in getattr(
                        deco_of, "decorator_list", ())):
                    enclosing = enclosing[:-1]
                if enclosing and not any(_is_cached_fn(f)
                                         for f in enclosing):
                    fname = enclosing[-1].name
                    findings.append(Finding(
                        check=NAME, path=sf.rel, line=line,
                        message=(f"jax.jit program created inside "
                                 f"{fname}() — the compile cache dies "
                                 f"with the call frame and closed-over "
                                 f"Python values re-specialize it per "
                                 f"call (the PR 4 streamed_matmul bug "
                                 f"class)"),
                        hint=("move the jit to module scope, or memoize "
                              "the factory with @functools.lru_cache "
                              "keyed on everything the program closes "
                              "over"),
                        key=f"{NAME}:{sf.rel}:{fname}@closure"))

    # traced-knob: config read inside a jitted function body
    if (isinstance(node, ast.Call)
            and (dotted(node.func) or "").split(".")[-1] == "get_config"
            and any(_is_jitted_fn(f) for f in fn_stack)
            and not sf.ignored(node.lineno, NAME)):
        jf = [f for f in fn_stack if _is_jitted_fn(f)][-1]
        findings.append(Finding(
            check=NAME, path=sf.rel, line=node.lineno,
            message=(f"get_config() read inside jitted {jf.name}() — the "
                     f"knob's value is baked in at trace time; changing "
                     f"the config later silently does nothing"),
            hint="read the knob in the caller and pass it as a traced "
                 "array argument (or a static_argnames entry if it must "
                 "re-specialize)",
            key=f"{NAME}:{sf.rel}:{jf.name}@traced-knob"))

    for child in ast.iter_child_nodes(node):
        _scan(sf, child, fn_stack, loop_depth, findings)


def run(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for sf in repo.py_files():
        if sf.tree is None:
            continue
        _scan(sf, sf.tree, [], 0, findings)
    return findings
