"""Check registry. Each module exposes ``NAME``, ``SCOPE`` ("files" = runs
over the parsed AST set, "repo" = needs the whole tree: docs, tests,
bench scripts) and ``run(repo) -> list[Finding]``."""

from __future__ import annotations

from ..core import Finding, Repo
from . import consistency, donation, hostsync, locks, recompile, testcov

_MODULES = (locks, donation, recompile, hostsync, consistency, testcov)

CHECKS = {m.NAME: m for m in _MODULES}


def get_checks(names=None, scope: str | None = None):
    mods = [CHECKS[n] for n in names] if names else list(_MODULES)
    if scope is not None:
        mods = [m for m in mods if m.SCOPE == scope]
    return mods


def run_checks(repo: Repo, names=None, scope: str | None = None
               ) -> list[Finding]:
    findings: list[Finding] = []
    # a file the analyzer cannot parse is itself a finding, never a crash
    for sf in repo.py_files():
        if sf.parse_error is not None:
            findings.append(Finding(
                check="parse", path=sf.rel,
                line=sf.parse_error.lineno or 1,
                message=f"file does not parse: {sf.parse_error.msg}",
                key=f"parse:{sf.rel}"))
    for mod in get_checks(names, scope):
        findings.extend(mod.run(repo))
    return findings
