"""test-hygiene: every registered fault point is exercised by a test.

``utils/faults.py`` rejects unknown point names at ``inject()`` time, so a
typo cannot silently never fire — but nothing stops a point from being
*registered* and then never exercised. A fault point with zero chaos tests
is a claim ("this failure mode is survivable") nobody has checked.

The check parses ``KNOWN_POINTS`` out of the faults module's AST and
requires each point name to appear as a string literal somewhere under
``tests/`` (fixtures excluded). Appearance is deliberately loose — an
``inject("serve.step", ...)``, a parametrize list, or a helper table all
count; the point is to force *a* test to name the point, not to prescribe
how it is driven.
"""

from __future__ import annotations

import ast

from ..core import EXCLUDE_PARTS, Finding, Repo

NAME = "test-hygiene"
SCOPE = "repo"

FAULTS_REL = "marlin_tpu/utils/faults.py"
TESTS_REL = "tests"


def known_points(repo: Repo) -> tuple[list[str], int]:
    """(points, lineno) parsed from the KNOWN_POINTS literal; ([], 0) when
    the faults module is absent (fixture trees)."""
    sf = repo.file(FAULTS_REL)
    if sf is None or sf.tree is None:
        return [], 0
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                   for t in node.targets):
            continue
        val = node.value
        if isinstance(val, ast.Call) and val.args:  # frozenset({...})
            val = val.args[0]
        if isinstance(val, (ast.Set, ast.List, ast.Tuple)):
            pts = [el.value for el in val.elts
                   if isinstance(el, ast.Constant)
                   and isinstance(el.value, str)]
            return sorted(pts), node.lineno
    return [], 0


def _test_literals(repo: Repo) -> set[str]:
    """Every string constant in every test file (AST-level, so commented-out
    mentions don't count as coverage)."""
    lits: set[str] = set()
    base = repo.root / TESTS_REL
    if not base.is_dir():
        return lits
    for p in sorted(base.rglob("*.py")):
        if EXCLUDE_PARTS.intersection(p.relative_to(repo.root).parts):
            continue
        sf = repo.file(str(p.relative_to(repo.root)))
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                lits.add(node.value)
    return lits


def run(repo: Repo) -> list[Finding]:
    points, lineno = known_points(repo)
    if not points:
        return []
    lits = _test_literals(repo)
    findings = []
    for pt in points:
        if pt in lits:
            continue
        findings.append(Finding(
            check=NAME, path=FAULTS_REL, line=lineno,
            message=(f"fault point {pt!r} is registered in KNOWN_POINTS "
                     f"but no test under {TESTS_REL}/ ever names it — the "
                     f"failure mode it models is untested"),
            hint=(f"add a chaos test that inject()s a fault at {pt!r} and "
                  f"asserts the system survives (see tests/test_faults.py "
                  f"for the idiom)"),
            key=f"{NAME}:{FAULTS_REL}:{pt}@untested"))
    return findings
