"""Shared driver for the marlin-analyze checks.

One :class:`Repo` is built per run (parsed files are cached on it), every
check receives it and returns :class:`Finding`\\ s, and the CLI diffs the
result against the checked-in suppression baseline. Stdlib-only by design:
the analyzer must run on a box that cannot even import jax.

Annotation comments (anywhere in a source line; the bare-comment form
applies to the next code line):

- ``# analyze: ignore[<check>]`` — suppress that check's findings on the
  annotated line (``ignore`` alone suppresses every check). Put the *why*
  in the rest of the comment; the annotation is the mechanism, the prose
  is the contract.
- ``# analyze: single-writer`` — on a ``self.<field> = ...`` line: declare
  the field single-writer by design, class-wide (lock-discipline).
- ``# analyze: hot-loop`` — on a ``def`` line: opt the function into the
  host-sync hot-path set even though its name doesn't match the patterns.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterator

__all__ = ["Finding", "SourceFile", "Repo", "load_baseline", "save_baseline",
           "split_by_baseline", "render_text", "render_json"]

_ANNOT_RE = re.compile(r"#\s*analyze:\s*([a-z-]+)(?:\[([^\]]*)\])?")


@dataclasses.dataclass
class Finding:
    """One analyzer result, with enough context to fix it.

    ``key`` is the stable identity used by the baseline file — built from
    symbol names, never line numbers, so unrelated edits don't churn the
    baseline.
    """

    check: str
    path: str          # repo-root-relative
    line: int
    message: str
    hint: str = ""
    severity: str = "error"   # "error" gates; "warn" reports only
    key: str = ""

    def __post_init__(self):
        if not self.key:
            self.key = f"{self.check}:{self.path}:{self.line}"

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed Python file: source text, AST, and ``# analyze:``
    annotations resolved to the code line they govern."""

    def __init__(self, path: Path, rel: str):
        self.abspath = path
        self.rel = rel
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.parse_error: SyntaxError | None = None
        try:
            self.tree: ast.Module | None = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a finding, never a crash
            self.tree = None
            self.parse_error = e
        #: line -> set of (name, arg) annotation tuples governing that line
        self.annotations: dict[int, set[tuple[str, str]]] = {}
        self._scan_annotations()

    def _scan_annotations(self) -> None:
        pending: set[tuple[str, str]] = set()
        for i, raw in enumerate(self.lines, start=1):
            found = {(m.group(1), (m.group(2) or "").strip())
                     for m in _ANNOT_RE.finditer(raw)}
            stripped = raw.strip()
            if stripped.startswith("#"):
                # standalone comment: annotation carries to the next code line
                pending |= found
                continue
            if stripped:
                here = found | pending
                if here:
                    self.annotations[i] = here
                pending = set()
            # blank lines keep the pending set alive

    def annotated(self, line: int, name: str, arg: str | None = None) -> bool:
        for n, a in self.annotations.get(line, ()):
            if n != name:
                continue
            if arg is None or not a or arg in {s.strip() for s in a.split(",")}:
                return True
        return False

    def ignored(self, line: int, check: str) -> bool:
        """True when the line carries ``# analyze: ignore`` for ``check``
        (or the blanket form)."""
        for n, a in self.annotations.get(line, ()):
            if n == "ignore" and (not a or check in
                                  {s.strip() for s in a.split(",")}):
                return True
        return False


#: directories never scanned (seeded-violation fixtures live under
#: tests/fixtures/analyze and MUST NOT leak into the repo gate)
EXCLUDE_PARTS = {"fixtures", "__pycache__", ".git", "node_modules"}

#: default scan set for the per-file AST checks
DEFAULT_PY_ROOTS = ("marlin_tpu",)


class Repo:
    """The analyzed tree. ``py_files()`` yields parsed sources under the
    AST-check roots; ``file()``/``text()`` fetch arbitrary repo-relative
    paths (docs, bench scripts) for the repo-scope checks. Everything is
    cached per instance, so N checks parse each file once."""

    def __init__(self, root: str | Path, py_roots=DEFAULT_PY_ROOTS,
                 explicit_files: list[Path] | None = None):
        self.root = Path(root).resolve()
        self.py_roots = tuple(py_roots)
        self.explicit_files = [Path(p).resolve() for p in explicit_files or []]
        self._cache: dict[str, SourceFile | None] = {}

    def _rel(self, p: Path) -> str:
        try:
            return str(p.resolve().relative_to(self.root))
        except ValueError:
            return str(p)

    def _load(self, p: Path) -> SourceFile | None:
        rel = self._rel(p)
        if rel not in self._cache:
            self._cache[rel] = (SourceFile(p, rel) if p.is_file() else None)
        return self._cache[rel]

    def file(self, rel: str) -> SourceFile | None:
        return self._load(self.root / rel)

    def text(self, rel: str) -> str | None:
        p = self.root / rel
        return p.read_text(encoding="utf-8", errors="replace") \
            if p.is_file() else None

    def exists(self, rel: str) -> bool:
        return (self.root / rel).exists()

    def _iter_root(self, sub: str) -> Iterator[Path]:
        base = self.root / sub
        if base.is_file():
            yield base
            return
        if not base.is_dir():
            return
        for p in sorted(base.rglob("*.py")):
            # exclusion is root-relative, so a Repo rooted *inside* a
            # fixture tree still scans its own files
            if EXCLUDE_PARTS.intersection(p.relative_to(self.root).parts):
                continue
            yield p

    def py_files(self, roots=None) -> Iterator[SourceFile]:
        """Parsed sources for the AST checks: the explicit file list when
        one was given on the CLI, else everything under ``roots``."""
        if self.explicit_files:
            for p in self.explicit_files:
                sf = self._load(p)
                if sf is not None:
                    yield sf
            return
        for sub in roots or self.py_roots:
            for p in self._iter_root(sub):
                sf = self._load(p)
                if sf is not None:
                    yield sf


# --------------------------------------------------------------- baseline

def load_baseline(path: str | Path) -> dict[str, str]:
    """``key -> reason`` from the suppression file; {} when absent."""
    p = Path(path)
    if not p.is_file():
        return {}
    data = json.loads(p.read_text())
    out = {}
    for e in data.get("entries", []):
        out[e["key"]] = e.get("reason", "")
    return out


def save_baseline(path: str | Path, findings: list[Finding],
                  reason: str) -> None:
    """Regenerate the suppression file from the current finding set. Every
    entry carries a reason string — a baseline without a why is a mute
    button, not a decision."""
    entries = [{"key": f.key, "reason": reason,
                "location": f.location(), "message": f.message}
               for f in sorted(findings, key=lambda f: f.key)]
    payload = {"version": 1,
               "note": ("Suppressed pre-existing findings. Regenerate "
                        "deliberately via `make -C tools analyze "
                        "BASELINE=update REASON='...'`; never hand-edit "
                        "keys."),
               "entries": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def split_by_baseline(findings: list[Finding], baseline: dict[str, str]):
    """(new, suppressed, stale_keys): findings not in the baseline, findings
    the baseline covers, and baseline keys that no longer match anything
    (candidates for pruning)."""
    new, suppressed = [], []
    seen = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - seen)
    return new, suppressed, stale


# --------------------------------------------------------------- rendering

def render_text(findings: list[Finding], suppressed: list[Finding] = (),
                stale: list[str] = ()) -> str:
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.check)):
        out.append(f"{f.location()}: [{f.check}] {f.severity}: {f.message}")
        if f.hint:
            out.append(f"    hint: {f.hint}")
    if suppressed:
        out.append(f"({len(suppressed)} pre-existing finding(s) suppressed "
                   f"by baseline)")
    for k in stale:
        out.append(f"stale baseline entry (no matching finding): {k}")
    errors = sum(1 for f in findings if f.severity == "error")
    warns = len(findings) - errors
    out.append(f"analyze: {errors} error(s), {warns} warning(s)"
               + (" — clean" if not findings else ""))
    return "\n".join(out)


def render_json(findings: list[Finding], suppressed: list[Finding] = (),
                stale: list[str] = ()) -> str:
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in findings],
        "suppressed": [f.to_dict() for f in suppressed],
        "stale_baseline_keys": list(stale),
    }, indent=2) + "\n"


# ------------------------------------------------------------ AST helpers

def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    """Dotted name of the called object, else None."""
    return dotted(call.func)


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
