"""marlin-analyze: repo-aware static analysis for marlin_tpu.

AST-level companions to the runtime chaos/bench gates: the invariants the
serving engine, the fault harness, and the docs promise each other are
checked ahead of time instead of relying on reviewer vigilance. Run as

    python -m tools.analyze                  # whole repo, baseline-gated
    python -m tools.analyze path/to/file.py  # per-file AST checks only
    make -C tools analyze-gate               # CI entry (self-tested)

See docs/static_analysis.md for the check catalog, the annotation
comments (``# analyze: single-writer``, ``# analyze: ignore[<check>]``),
and the baseline workflow.
"""

from .core import Finding, Repo, load_baseline, render_json, render_text
from .checks import CHECKS, get_checks, run_checks

__all__ = ["Finding", "Repo", "CHECKS", "get_checks", "run_checks",
           "load_baseline", "render_json", "render_text"]
