"""CLI: ``python -m tools.analyze [paths...]``.

Default run: every check over the repo, findings diffed against the
checked-in suppression baseline (``tools/analyze/baseline.json``); exits 1
when any non-baselined error-severity finding remains — the shape ``make
analyze-gate`` wires into ``make check``. Stdlib-only: runs on a box that
cannot import jax.

Modes:

- ``--json`` — machine-readable findings (schema: version/findings/
  suppressed/stale_baseline_keys; each finding carries check, path, line,
  message, hint, severity, key).
- ``--update-baseline --reason '...'`` — regenerate the baseline from the
  current *new* finding set (existing suppressions keep their reasons;
  stale keys are pruned). A reason is mandatory: a suppression without a
  why is a mute button.
- ``--no-baseline`` — report everything, ignore the suppression file.
- ``--check NAME`` (repeatable) / ``--list`` — select / enumerate checks.
- ``--selftest`` — run each check against its seeded-violation fixture
  under ``tests/fixtures/analyze/`` and assert it fires there and stays
  silent on the clean fixture; proves the gate can still catch what it
  claims to catch.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .checks import CHECKS, run_checks
from .core import (Finding, Repo, load_baseline, render_json, render_text,
                   split_by_baseline)

#: files-scope check -> seeded-violation fixture (repo-scope checks run
#: over the consistency_tree mini-repo instead)
FIXTURES = {
    "lock-discipline": "bad_locks.py",
    "donation": "bad_donation.py",
    "recompile": "bad_recompile.py",
    "host-sync": "bad_hostsync.py",
}
FIXTURE_DIR = "tests/fixtures/analyze"
CLEAN_FIXTURE = "clean.py"
TREE_FIXTURE = "consistency_tree"


def _selftest(root: Path) -> int:
    """Exit status: 0 when every check fires on its seeded fixture and all
    stay silent on the clean one."""
    fdir = root / FIXTURE_DIR
    failures: list[str] = []
    ok: list[str] = []

    def expect(label: str, findings: list[Finding], check: str,
               want: bool) -> None:
        hits = [f for f in findings if f.check == check]
        if bool(hits) == want:
            ok.append(f"{label}: {'fires' if want else 'silent'} "
                      f"({len(hits)} finding(s))")
        else:
            failures.append(
                f"{label}: expected {'findings' if want else 'silence'}, "
                f"got {len(hits)}")

    for check, fixture in sorted(FIXTURES.items()):
        path = fdir / fixture
        if not path.is_file():
            failures.append(f"{check}: fixture {path} missing")
            continue
        repo = Repo(fdir, explicit_files=[path])
        expect(f"{check} on {fixture}",
               run_checks(repo, names=[check]), check, True)
    clean = fdir / CLEAN_FIXTURE
    if clean.is_file():
        repo = Repo(fdir, explicit_files=[clean])
        for check in FIXTURES:
            expect(f"{check} on {CLEAN_FIXTURE}",
                   run_checks(repo, names=[check]), check, False)
    else:
        failures.append(f"clean fixture {clean} missing")
    tree = fdir / TREE_FIXTURE
    if tree.is_dir():
        repo = Repo(tree)
        findings = run_checks(repo, names=["doc-sync", "test-hygiene"])
        for check in ("doc-sync", "test-hygiene"):
            expect(f"{check} on {TREE_FIXTURE}/", findings, check, True)
    else:
        failures.append(f"fixture tree {tree} missing")

    for line in ok:
        print(f"  ok: {line}")
    for line in failures:
        print(f"  FAIL: {line}")
    print(f"analyze --selftest: {len(ok)} ok, {len(failures)} failed")
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="marlin_tpu repo-aware static analysis")
    ap.add_argument("paths", nargs="*",
                    help="specific .py files to analyze (default: the "
                         "whole package + repo-scope checks)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree containing this "
                         "tool)")
    ap.add_argument("--check", action="append", dest="checks",
                    metavar="NAME", help="run only this check (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list available checks and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="JSON output")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: "
                         "tools/analyze/baseline.json under the root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the suppression file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to suppress the current "
                         "finding set (requires --reason for new entries)")
    ap.add_argument("--reason", default="",
                    help="reason string recorded for new baseline entries")
    ap.add_argument("--selftest", action="store_true",
                    help="verify every check fires on its seeded fixture")
    args = ap.parse_args(argv)

    if args.list:
        for name, mod in sorted(CHECKS.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{name:<16} [{mod.SCOPE:<5}] {doc}")
        return 0

    root = Path(args.root).resolve() if args.root \
        else Path(__file__).resolve().parents[2]
    if args.selftest:
        return _selftest(root)

    for name in args.checks or ():
        if name not in CHECKS:
            print(f"unknown check {name!r} (have: "
                  f"{', '.join(sorted(CHECKS))})", file=sys.stderr)
            return 2

    explicit = [Path(p) for p in args.paths] or None
    if explicit:
        missing = [p for p in explicit if not p.is_file()]
        if missing:
            print(f"no such file: {', '.join(map(str, missing))}",
                  file=sys.stderr)
            return 2
    repo = Repo(root, explicit_files=explicit)
    # explicit file runs skip the repo-scope checks (they analyze the whole
    # tree regardless of which file you asked about)
    scope = "files" if explicit and not args.checks else None
    findings = run_checks(repo, names=args.checks, scope=scope)

    baseline_path = Path(args.baseline) if args.baseline \
        else root / "tools" / "analyze" / "baseline.json"
    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = split_by_baseline(findings, baseline)

    if args.update_baseline:
        if any(f.key not in baseline for f in findings) and not args.reason:
            print("--update-baseline with new findings requires "
                  "--reason '...'", file=sys.stderr)
            return 2
        entries = [{"key": f.key,
                    "reason": baseline.get(f.key) or args.reason,
                    "location": f.location(), "message": f.message}
                   for f in sorted(findings, key=lambda f: f.key)]
        payload = {"version": 1,
                   "note": ("Suppressed findings, each with a reason. "
                            "Regenerate via `make -C tools analyze "
                            "BASELINE=update REASON='...'`; never "
                            "hand-edit keys."),
                   "entries": entries}
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(json.dumps(payload, indent=2) + "\n")
        print(f"baseline updated: {len(entries)} entr(y/ies) -> "
              f"{baseline_path}")
        return 0

    if args.as_json:
        sys.stdout.write(render_json(new, suppressed, stale))
    else:
        print(render_text(new, suppressed, stale))
    return 1 if any(f.severity == "error" for f in new) else 0


if __name__ == "__main__":
    raise SystemExit(main())
