#!/usr/bin/env python
"""Bench regression gate: diff two BENCH_ALL.json-shaped files.

``python tools/bench_compare.py BASE NEW`` compares per-config measurements
(the ``[{config, value, unit, detail, ...}]`` list bench_all.py writes),
prints a markdown summary, and exits nonzero when anything regressed beyond
tolerance — the CI tripwire that keeps the BENCH numbers from silently
sliding between rounds.

What counts as a regression:

- a **throughput/accuracy config** (GFLOP/s, tok/s, ktok/s, steps/s, ...)
  whose NEW value fell below ``BASE * (1 - tolerance)``;
- a **latency config** (ms, s, ms/iter, s/sweep, rel err) whose NEW value
  rose above ``BASE * (1 + tolerance)``;
- a serve config whose ``ttft p50 N ms`` detail (bench_all embeds it in the
  record detail) rose beyond the same bound — TTFT is the serving headline
  and must not hide inside an unchanged tok/s;
- a router config whose ``prefix-hit-rate X`` detail fell beyond tolerance
  when both sides carry it — routing that stops landing shared prefixes on
  the warm replica regresses cost per token long before tok/s notices;
- an elastic-fleet config whose ``replica-hours-saved F`` detail fell
  beyond tolerance when both sides carry it — the fleet controller's whole
  point is serving the diurnal trace on fewer replica-hours than the
  peak-sized static fleet, so the saving is gated higher-is-better;
- a ``*_FAILED`` error record in NEW with no counterpart in BASE (a config
  that used to run and now crashes is the worst regression of all);
- a config present in BASE but missing from NEW is *reported* (dropped)
  but does not fail the gate — partial sweeps are routine.

``roofline_frac`` (bench_all's utilization ride-along) is shown when either
side carries it, informational only: utilization explains a throughput
regression, it does not define one.

**Host-drift sentinel**: records flagged ``"control": true`` (bench_all's
``serve_control*`` — a fixed pure-numpy workload no repo change can touch)
are never gated themselves. When a control present on BOTH sides fell
beyond tolerance, the host itself got slower between the two runs, and
every speed regression that moved *with* it is downgraded to
``WARN(host-drift)`` — reported, not failed. Accuracy configs (``rel
err``) are never downgraded: machine weather does not change arithmetic.

Per-config overrides: ``--threshold serve_load64=0.1`` (repeatable) tightens
or loosens one config without moving the global ``--tolerance``.

``--only PREFIX`` (repeatable) restricts the gate to configs whose name
starts with a prefix — ``--only serve`` is the serving-records gate behind
``make -C tools serve-gate`` (a subsystem PR gates its own records without
a full BENCH sweep on both sides).

``make bench-gate`` (tools/Makefile) runs this over the checked-in fixture
pair; pointing NEW at ``bench_gate_regressed.json`` proves the gate fires.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

#: units where smaller is better; anything else (GFLOP/s, tok/s, steps/s,
#: ktok/s, families, ...) is larger-is-better
LOWER_BETTER = {"ms", "s", "ms/iter", "s/sweep", "rel err"}

#: units shown but never gated: roofline fractions are utilization
#: *explanations* (and nominal on CPU — docs/performance.md), they swing
#: with load mix far more than any sane tolerance and must not fail CI on
#: their own — the throughput/latency configs they explain are the gate
INFORMATIONAL = {"frac"}

_TTFT_RE = re.compile(r"ttft p50 (\d+(?:\.\d+)?) ms")
_HIT_RE = re.compile(r"prefix-hit-rate (\d+(?:\.\d+)?)")
_SAVED_RE = re.compile(r"replica-hours-saved (\d+(?:\.\d+)?)")
_CALIB_RE = re.compile(r"calib-headroom (\d+(?:\.\d+)?)")

#: units a slower *host* explains — eligible for the control-sentinel
#: downgrade; accuracy ("rel err") is excluded on purpose
_HOST_SENSITIVE = {"GFLOP/s", "tok/s", "ktok/s", "steps/s", "ms", "s",
                   "ms/iter", "s/sweep"}


def load(path: str) -> dict[str, dict]:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"{path}: expected a BENCH_ALL.json-shaped list")
    return {r["config"]: r for r in data if isinstance(r, dict)
            and "config" in r}


def _ttft_ms(rec: dict) -> float | None:
    m = _TTFT_RE.search(str(rec.get("detail", "")))
    return float(m.group(1)) if m else None


def _hit_rate(rec: dict) -> float | None:
    # the structured ride-along when present, the detail string otherwise
    # (BASE files from earlier rounds predate the extra field)
    v = rec.get("router_prefix_hit_rate")
    if isinstance(v, (int, float)):
        return float(v)
    m = _HIT_RE.search(str(rec.get("detail", "")))
    return float(m.group(1)) if m else None


def _hours_saved(rec: dict) -> float | None:
    m = _SAVED_RE.search(str(rec.get("detail", "")))
    return float(m.group(1)) if m else None


def _calib_headroom(rec: dict) -> float | None:
    m = _CALIB_RE.search(str(rec.get("detail", "")))
    return float(m.group(1)) if m else None


def _is_control(name: str, rec: dict) -> bool:
    return bool(rec.get("control")) or "_control" in name \
        or name.endswith("control")


def _frac(rec: dict):
    v = rec.get("roofline_frac")
    return f"{v:.3f}" if isinstance(v, (int, float)) else ""


def host_drift(base: dict[str, dict], new: dict[str, dict],
               tolerance: float) -> float | None:
    """Worst fractional slide across control sentinels present on both
    sides, or None when no pair exists / none slid beyond tolerance. A
    negative return means the host got at least that much slower."""
    worst = None
    for name, b in base.items():
        n = new.get(name)
        if n is None or not _is_control(name, b):
            continue
        try:
            bv, nv = float(b["value"]), float(n["value"])
        except (TypeError, ValueError):
            continue
        if bv <= 0:
            continue
        delta = (nv - bv) / bv
        if delta < -tolerance and (worst is None or delta < worst):
            worst = delta
    return worst


def compare(base: dict[str, dict], new: dict[str, dict],
            tolerance: float = 0.25,
            thresholds: dict[str, float] | None = None) -> tuple[list, bool]:
    """Rows ``(config, base_str, new_str, delta_str, unit, status, note)``
    plus the overall regressed flag."""
    thresholds = thresholds or {}
    drift = host_drift(base, new, tolerance)
    rows, regressed = [], False
    for name in sorted(set(base) | set(new)):
        b, n = base.get(name), new.get(name)
        if n is None:
            rows.append((name, b["value"], "-", "", b["unit"], "dropped",
                         _frac(b)))
            continue
        if b is None:
            status = "ok"
            if n.get("unit") == "error" or name.endswith("_FAILED"):
                status, regressed = "REGRESSION", True
            rows.append((name, "-", n["value"], "", n.get("unit", ""),
                         status if status != "ok" else "new", _frac(n)))
            continue
        unit = n.get("unit", b.get("unit", ""))
        tol = thresholds.get(name, tolerance)
        bv, nv = float(b["value"]), float(n["value"])
        if unit == "error":
            # failed on both sides: broken, but not newly broken
            rows.append((name, bv, nv, "", unit, "still-failing", ""))
            continue
        if _is_control(name, n):
            # the sentinel measures the host, not the repo — never gated
            delta = (nv - bv) / abs(bv) if bv else 0.0
            rows.append((name, bv, nv, f"{delta * 100:+.1f}%", unit,
                         "control", ""))
            continue
        if unit in INFORMATIONAL:
            delta = (nv - bv) / abs(bv) if bv else 0.0
            rows.append((name, bv, nv, f"{delta * 100:+.1f}%", unit,
                         "info", _frac(n)))
            continue
        lower_better = unit in LOWER_BETTER
        if bv == 0:
            # no relative delta off a zero baseline — but a lower-is-better
            # config rising off exact zero (e.g. rel err 0 -> 0.5) is a
            # regression of arbitrary relative size, so it always fires
            delta_str = ""
            bad = lower_better and nv > 0
        else:
            delta = (nv - bv) / abs(bv)
            delta_str = f"{delta * 100:+.1f}%"
            bad = (delta > tol) if lower_better else (delta < -tol)
        status = "REGRESSION" if bad else "ok"
        note = _frac(n)
        # the serving TTFT leg: parsed from the detail string both sides
        bt, nt = _ttft_ms(b), _ttft_ms(n)
        if bt and nt and nt > bt * (1 + tol):
            bad = True
            status = "REGRESSION"
            note = (note + " " if note else "") + \
                f"ttft p50 {bt:.0f}->{nt:.0f} ms"
        # the router prefix-affinity leg: higher-better hit rate gated
        # only when both sides report it (pre-affinity BASE files don't)
        bh, nh = _hit_rate(b), _hit_rate(n)
        hit_bad = bh is not None and nh is not None and bh > 0 \
            and nh < bh * (1 - tol)
        if hit_bad:
            bad = True
            status = "REGRESSION"
            note = (note + " " if note else "") + \
                f"prefix-hit-rate {bh:.3f}->{nh:.3f}"
        # the elastic-fleet leg: higher-better replica-hours saving gated
        # only when both sides report it (pre-fleet BASE files don't)
        bsv, nsv = _hours_saved(b), _hours_saved(n)
        saved_bad = bsv is not None and nsv is not None and bsv > 0 \
            and nsv < bsv * (1 - tol)
        if saved_bad:
            bad = True
            status = "REGRESSION"
            note = (note + " " if note else "") + \
                f"replica-hours-saved {bsv:.3f}->{nsv:.3f}"
        # the HBM-ledger admission-calibration leg: serve_mem's
        # calibrated-vs-raw headroom (AOT_MEMORY.json), gated only when
        # both sides report a number ("n/a" or pre-ledger BASE skips) —
        # a collapse means the calibration table stopped tightening
        # admission, never machine weather
        bc, nc = _calib_headroom(b), _calib_headroom(n)
        calib_bad = bc is not None and nc is not None and bc > 0 \
            and nc < bc * (1 - tol)
        if calib_bad:
            bad = True
            status = "REGRESSION"
            note = (note + " " if note else "") + \
                f"calib-headroom {bc:.2f}->{nc:.2f}"
        if bad and drift is not None and unit in _HOST_SENSITIVE \
                and not hit_bad and not saved_bad and not calib_bad:
            # the control slid with the candidate: machine weather, not a
            # code regression — report loudly, fail nothing (a hit-rate
            # drop is a routing property, a replica-hours saving is a
            # control property — neither is ever weather)
            status = "WARN(host-drift)"
            note = (note + " " if note else "") + \
                f"control slid {drift * 100:+.1f}%"
            bad = False
        if bad:
            regressed = True
        rows.append((name, bv, nv, delta_str, unit, status, note))
    return rows, regressed


def markdown(rows: list, base_path: str, new_path: str) -> str:
    out = [f"# Bench gate: `{new_path}` vs `{base_path}`", "",
           "| Config | Base | New | Δ | Unit | Status | Note |",
           "|---|---|---|---|---|---|---|"]
    for name, bv, nv, delta, unit, status, note in rows:
        flag = "**REGRESSION**" if status == "REGRESSION" else status
        out.append(f"| {name} | {bv} | {nv} | {delta} | {unit} | {flag} "
                   f"| {note} |")
    bad = sum(1 for r in rows if r[5] == "REGRESSION")
    out.append("")
    out.append(f"{'**GATE FAILED**' if bad else 'gate passed'}: "
               f"{bad} regression(s) over {len(rows)} config(s)")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two BENCH_ALL.json files; exit 1 on regression")
    ap.add_argument("base", help="baseline BENCH_ALL.json-shaped file")
    ap.add_argument("new", help="candidate file to gate")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional slide per config "
                         "(default 0.25)")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="CONFIG=TOL",
                    help="per-config tolerance override (repeatable)")
    ap.add_argument("--only", action="append", default=[],
                    metavar="PREFIX",
                    help="gate only configs whose name starts with PREFIX "
                         "(repeatable; default: all configs)")
    ap.add_argument("--out", default=None,
                    help="also write the markdown summary here")
    args = ap.parse_args(argv)
    thresholds = {}
    for spec in args.threshold:
        name, _, tol = spec.partition("=")
        if not tol:
            ap.error(f"--threshold wants CONFIG=TOL, got {spec!r}")
        thresholds[name] = float(tol)
    try:
        base, new = load(args.base), load(args.new)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    if args.only:
        def keep(d):
            return {k: v for k, v in d.items()
                    if any(k.startswith(p) for p in args.only)}
        base, new = keep(base), keep(new)
        if not base and not new:
            print(f"bench_compare: no config matches --only "
                  f"{args.only}", file=sys.stderr)
            return 2
    rows, regressed = compare(base, new, args.tolerance, thresholds)
    md = markdown(rows, args.base, args.new)
    sys.stdout.write(md)
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
    return 1 if regressed else 0


if __name__ == "__main__":
    raise SystemExit(main())
