#!/bin/bash
# Round-5 follow-up batch: the flash-gated configs the main recovery batch
# skipped because tpu_smoke.py had a sys.path bug (fixed) at the moment the
# relay came back. Waits for the main batch (and any other TPU client) to
# exit, then re-probes the relay, re-runs the smoke, and on pass runs the
# skipped legs.
#
# Same discipline as on_recovery.sh: one TPU client at a time, no kills,
# no timed phase under CPU contention, no batch on a CPU-fallback backend.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/r5_followup.log
exec >>"$LOG" 2>&1

# single-instance guard: a double nohup-launch must not yield two batches
exec 9>/tmp/r5_followup.lock
flock -n 9 || { echo "another r5_followup instance holds the lock; exiting"; exit 0; }

ts() { date -u +%H:%M:%S; }

other_tpu_clients() {
  # same matcher as on_recovery.sh's tpu_clients(): orphaned "import jax"
  # probes and standalone smoke runs ARE lease-claiming clients; only the
  # build driver (whose prompt embeds these names) and this script's own
  # grep are excluded.
  pgrep -af "import jax|on_recovery|bench\.py|bench_all\.py|tpu_smoke|hbm_probe" \
    2>/dev/null | grep -v "claude -p" | grep -v "r5_followup" | grep -q .
}
cpu_load() {
  pgrep -af "pytest" 2>/dev/null | grep -v "claude -p" | grep -q .
}

# one combined gate, re-evaluated as a unit immediately before the probe:
# a TPU client appearing during a long cpu_load wait must re-block the batch
while other_tpu_clients || cpu_load; do
  echo "$(ts) waiting: tpu_client=$(other_tpu_clients && echo yes || echo no) cpu_load=$(cpu_load && echo yes || echo no)"
  sleep 60
done

# Relay-alive gate (same as on_recovery.sh): tpu_smoke exits 0 on a CPU
# fallback by design, so it must NOT be the only gate — a re-wedged relay
# would send the 256k-1M legs to CPU where they hang for days or record
# garbage numbers.
echo "$(ts) probing relay"
out=$(python -c "import jax; d = jax.devices(); print('NDEV', len(d), d[0].platform)" 2>&1 | grep -E "NDEV|Error" | tail -1)
echo "$(ts) probe: $out"
case "$out" in
  NDEV*cpu*) echo "$(ts) cpu fallback — relay re-wedged; aborting followup"; exit 1 ;;
  NDEV*) ;;
  *) echo "$(ts) probe failed — aborting followup"; exit 1 ;;
esac

export MARLIN_BENCH_ROUND=r5
echo "$(ts) follow-up batch starts"

echo "$(ts) [1] pallas kernel smoke (sys.path fixed)"
if ! python tools/tpu_smoke.py; then
  echo "$(ts) SMOKE FAILED — flash kernels do not run on this chip; stopping"
  exit 1
fi

echo "$(ts) [2] decode prompt sweep (flash prefill legs; re-runs the whole"
echo "         decode config — BENCH_ALL entries are keyed, latest wins)"
python bench_all.py decode

echo "$(ts) [3] long-context: lct_long + attn_long at 256k"
python bench_all.py lct_long attn_long

echo "$(ts) [4] escalation: 512k"
MARLIN_BENCH_LCT_SEQ=524288 MARLIN_BENCH_ATTN_SEQ=524288 \
  python bench_all.py lct_long attn_long

echo "$(ts) [5] escalation: 1M (bf16 — f32 exceeds HBM at 1M per AOT_MEMORY)"
MARLIN_BENCH_LCT_SEQ=1048576 MARLIN_BENCH_ATTN_SEQ=1048576 \
  MARLIN_BENCH_LCT_DTYPE=bfloat16 python bench_all.py lct_long attn_long

echo "$(ts) follow-up batch done"
