// genmat — offline matrix data generator.
//
// Parity with the reference's tools/generateMatrix.cpp (26-line CLI that
// prints "row:val,val,..." lines of uniform random floats in [0, 5) to
// stdout; tools/README.md: ./genMat rows cols > file). This implementation
// adds an optional seed argument for reproducibility and uses a fixed-width
// fast PRNG + buffered output so multi-GB matrices generate at IO speed.
//
// Build: make -C tools      Usage: ./genmat rows cols [seed] > matrix.txt
//
// The emitted format is exactly what marlin_tpu.io.load_matrix_file (and the
// reference's MTUtils.loadMatrixFile) parses.

#include <cstdint>
#include <cstdio>
#include <cstdlib>

namespace {

// xorshift128+ — small, fast, seedable.
struct Rng {
  uint64_t s0, s1;
  explicit Rng(uint64_t seed) {
    // splitmix64 to fill state from the seed
    auto next = [&seed]() {
      seed += 0x9e3779b97f4a7c15ULL;
      uint64_t z = seed;
      z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
      z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
      return z ^ (z >> 31);
    };
    s0 = next();
    s1 = next();
  }
  uint64_t next() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  // uniform in [0, 5) like the reference generator
  double uniform5() { return 5.0 * (next() >> 11) * (1.0 / 9007199254740992.0); }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s rows cols [seed] > matrix.txt\n", argv[0]);
    return 1;
  }
  const long rows = std::strtol(argv[1], nullptr, 10);
  const long cols = std::strtol(argv[2], nullptr, 10);
  const uint64_t seed = argc > 3 ? std::strtoull(argv[3], nullptr, 10) : 42;
  if (rows <= 0 || cols <= 0) {
    std::fprintf(stderr, "rows and cols must be positive\n");
    return 1;
  }

  Rng rng(seed);
  // ~16 bytes per value is plenty for "%.6g,"
  const size_t buf_size = 1 << 20;
  static char buf[1 << 20];
  std::setvbuf(stdout, buf, _IOFBF, buf_size);

  for (long i = 0; i < rows; ++i) {
    std::printf("%ld:", i);
    for (long j = 0; j < cols; ++j) {
      std::printf(j + 1 == cols ? "%.6g" : "%.6g,", rng.uniform5());
    }
    std::putchar('\n');
  }
  std::fflush(stdout);
  return 0;
}
