// parse_common — helpers shared by the native IO libraries (textio.cpp,
// chunkstore.cpp): the reference's separator rule, the fast float parser,
// and the whole-file read buffer. Header-only so each .so compiles its own
// copy (no cross-library linkage; the two libraries stay independently
// loadable via ctypes).
//
// Portability: libstdc++ only grew floating-point from_chars/to_chars in
// GCC 11 (__cpp_lib_to_chars). On older toolchains the parser falls back to
// strtod — correctly rounded too, just without the Eisel-Lemire fast path —
// so the native libraries build (and run) instead of silently ceding the
// data plane to the pure-Python parser.

#pragma once

#include <cerrno>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace marlin_native {

inline const char* skip_seps(const char* p, const char* end) {
  // the reference's separator rule: ",\s?|\s+"
  while (p < end && (*p == ',' || *p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// Fast float parse. With FP from_chars (Eisel-Lemire): correctly rounded,
// locale-free, bounded by `end`, ~4x faster than strtod. Without it: strtod
// on the NUL-terminated file buffer (see FileBuf) — the token ends before
// `end`, so strtod cannot scan out of bounds; a result past `end` is
// rejected. Both paths keep Python float()'s '1e400' -> inf / '1e-400' -> 0
// semantics; leading '+' is skipped for parity with float().
inline const char* parse_value(const char* q, const char* end, double* out) {
  if (q < end && *q == '+') ++q;
#if defined(__cpp_lib_to_chars)
  auto r = std::from_chars(q, end, *out);
  if (r.ec == std::errc()) return r.ptr;
  if (r.ec != std::errc::result_out_of_range) return nullptr;
  // fall through to strtod for its ±HUGE_VAL / ±0 out-of-range semantics
#endif
  char* next = nullptr;
  *out = std::strtod(q, &next);
  if (next == q || next > end) return nullptr;
  return next;
}

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
  // Read the whole file into a NUL-terminated buffer. Every step is
  // checked: an unseekable/unsizeable stream (ftell -1), a failed or SHORT
  // fread (the file shrank, or the path is a directory — Linux fopen()s
  // directories happily and only fread fails with EISDIR) returns a
  // negative errno instead of silently parsing an empty or truncated
  // buffer as a smaller matrix.
  int read(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -errno;
    long n = -1;
    if (std::fseek(f, 0, SEEK_END) == 0) n = std::ftell(f);
    if (n < 0 || std::fseek(f, 0, SEEK_SET) != 0) {
      int rc = errno ? -errno : -EIO;
      std::fclose(f);
      return rc;
    }
    data = static_cast<char*>(std::malloc(n + 1));
    if (!data) {
      std::fclose(f);
      return -ENOMEM;
    }
    errno = 0;
    size = std::fread(data, 1, n, f);
    if (size != static_cast<size_t>(n) || std::ferror(f)) {
      int rc = errno ? -errno : -EIO;
      std::fclose(f);
      return rc;
    }
    data[size] = '\0';
    std::fclose(f);
    return 0;
  }
};

}  // namespace marlin_native
