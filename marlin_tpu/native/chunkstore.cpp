// chunkstore — the native binary chunk container behind the out-of-core
// data plane (marlin_tpu/io/chunkstore.py binds this via ctypes).
//
// BENCH_ALL.json config 4 names the problem this solves: the tall-skinny
// Gramian runs ~10,900 GFLOP/s device-resident but single-digit GFLOP/s
// end-to-end, because the host side of the stream is a text parser. The
// prefetch pipeline (PR 2) proved the overlap works and left the producer
// as the wall; this library replaces the producer with an mmap'd binary
// format the OS page cache can feed at memory speed, checksum-validated,
// with dtype conversion (f64/f32 -> bf16/f32/f64) done in C outside the
// GIL — ctypes releases the GIL for the duration of every call, and
// mcs_read additionally fans the touched chunks over a small std::thread
// pool. The reader fills caller-provided buffers: no per-chunk Python
// allocation, no pickling, no parse.
//
// MarlinChunk container layout (little-endian, fixed — offsets of every
// chunk are computable from the file header, which is what makes mmap'd
// random-access windows ("scatter/gather of arbitrary chunk_rows windows")
// O(1)):
//
//   FileHeader (64 B): magic "MRLNCHK1", version, dtype, nrows, ncols,
//                      chunk_rows, nchunks
//   chunk k (k = 0..nchunks-1), at 64 + k * (32 + chunk_rows*rowbytes):
//     ChunkHeader (32 B): magic "MCHK", crc32c(body), row_offset, nrows,
//                         body_bytes
//     body: row-major values, nrows*ncols elements of dtype
//
// Only the last chunk may be short. The CRC is Castagnoli (CRC32C), the
// storage-checksum polynomial; a flipped byte anywhere in a chunk body is
// detected at read time (-EBADMSG), and a truncated file is detected at
// open time (the expected size is computable — -EIO, "short mmap").
//
// Exported C ABI (0 on success, negative errno-style on error; handles are
// opaque pointers):
//   mcs_writer_open / mcs_writer_append / mcs_writer_close / mcs_writer_abort
//   mcs_open / mcs_info / mcs_read / mcs_close
//   mcs_from_text  — transcode the row-text format (reuses the textio
//                    parser helpers from parse_common.h)
//   mcs_crc32c     — the checksum itself, for tests/tools
//
// Build: make -C marlin_tpu/native   (produces libmarlin_chunkstore.so)

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "parse_common.h"

namespace {

using marlin_native::FileBuf;
using marlin_native::parse_value;
using marlin_native::skip_seps;

constexpr char kFileMagic[8] = {'M', 'R', 'L', 'N', 'C', 'H', 'K', '1'};
constexpr uint32_t kChunkMagic = 0x4B48434Du;  // "MCHK" little-endian
constexpr uint32_t kVersion = 1;

// dtype codes shared with the Python binding (io/chunkstore.py DTYPES)
enum Dtype : int32_t { kF32 = 1, kF64 = 2, kBF16 = 3 };

inline int64_t itemsize(int32_t dtype) {
  switch (dtype) {
    case kF32: return 4;
    case kF64: return 8;
    case kBF16: return 2;
    default: return 0;
  }
}

#pragma pack(push, 1)
struct FileHeader {
  char magic[8];
  uint32_t version;
  int32_t dtype;
  int64_t nrows;
  int64_t ncols;
  int64_t chunk_rows;
  int64_t nchunks;
  uint64_t reserved[2];
};
struct ChunkHeader {
  uint32_t magic;
  uint32_t crc32c;
  int64_t row_offset;
  int64_t nrows;
  int64_t body_bytes;
};
#pragma pack(pop)
static_assert(sizeof(FileHeader) == 64, "FileHeader must be 64 bytes");
static_assert(sizeof(ChunkHeader) == 32, "ChunkHeader must be 32 bytes");

// ------------------------------------------------------------------ crc32c
// Castagnoli CRC-32 (poly 0x1EDC6F41, reflected 0x82F63B78) — the storage
// checksum (iSCSI, ext4, leveldb). Table-driven software implementation;
// the function-local static initializer is thread-safe (C++11 magic
// statics), so concurrent reader threads share one table.
const uint32_t* crc32c_table() {
  static const auto* table = [] {
    auto* t = new uint32_t[256];
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
    return t;
  }();
  return table;
}

uint32_t crc32c(const void* data, int64_t n) {
  const uint32_t* t = crc32c_table();
  const auto* p = static_cast<const uint8_t*>(data);
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < n; ++i) c = t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- conversion
// bf16 <-> f32: round-to-nearest-even truncation of the f32 bit pattern,
// matching ml_dtypes/JAX semantics (NaN stays quiet NaN).
inline uint16_t f32_to_bf16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  if ((x & 0x7FFFFFFFu) > 0x7F800000u) return static_cast<uint16_t>((x >> 16) | 0x0040u);
  x += 0x7FFFu + ((x >> 16) & 1u);
  return static_cast<uint16_t>(x >> 16);
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t x = static_cast<uint32_t>(h) << 16;
  float f;
  std::memcpy(&f, &x, 4);
  return f;
}

template <typename S, typename D>
void convert_loop(const void* src, void* dst, int64_t count) {
  const S* s = static_cast<const S*>(src);
  D* d = static_cast<D*>(dst);
  for (int64_t i = 0; i < count; ++i) d[i] = static_cast<D>(s[i]);
}

// src/dst described by dtype codes; count elements. Same-dtype is memcpy;
// bf16 endpoints go through f32 (f64 -> bf16 double-rounds via f32, the
// same path numpy/ml_dtypes take).
int convert_rows(const void* src, int32_t sdt, void* dst, int32_t ddt,
                 int64_t count) {
  if (sdt == ddt) {
    std::memcpy(dst, src, count * itemsize(sdt));
    return 0;
  }
  const auto* s8 = static_cast<const uint8_t*>(src);
  auto* d8 = static_cast<uint8_t*>(dst);
  if (sdt == kF32 && ddt == kF64) convert_loop<float, double>(src, dst, count);
  else if (sdt == kF64 && ddt == kF32) convert_loop<double, float>(src, dst, count);
  else if (sdt == kF32 && ddt == kBF16) {
    const float* s = reinterpret_cast<const float*>(s8);
    uint16_t* d = reinterpret_cast<uint16_t*>(d8);
    for (int64_t i = 0; i < count; ++i) d[i] = f32_to_bf16(s[i]);
  } else if (sdt == kF64 && ddt == kBF16) {
    const double* s = reinterpret_cast<const double*>(s8);
    uint16_t* d = reinterpret_cast<uint16_t*>(d8);
    for (int64_t i = 0; i < count; ++i) d[i] = f32_to_bf16(static_cast<float>(s[i]));
  } else if (sdt == kBF16 && ddt == kF32) {
    const uint16_t* s = reinterpret_cast<const uint16_t*>(s8);
    float* d = reinterpret_cast<float*>(d8);
    for (int64_t i = 0; i < count; ++i) d[i] = bf16_to_f32(s[i]);
  } else if (sdt == kBF16 && ddt == kF64) {
    const uint16_t* s = reinterpret_cast<const uint16_t*>(s8);
    double* d = reinterpret_cast<double*>(d8);
    for (int64_t i = 0; i < count; ++i) d[i] = static_cast<double>(bf16_to_f32(s[i]));
  } else {
    return -EINVAL;
  }
  return 0;
}

// ------------------------------------------------------------------ writer
struct McsWriter {
  FILE* f = nullptr;
  int32_t dtype = 0;
  int64_t ncols = 0;
  int64_t chunk_rows = 0;
  int64_t rows_written = 0;  // rows in flushed chunks
  int64_t nchunks = 0;
  int64_t buffered = 0;  // rows pending in buf
  std::vector<uint8_t> buf;
};

int flush_chunk(McsWriter* w) {
  if (w->buffered == 0) return 0;
  int64_t body = w->buffered * w->ncols * itemsize(w->dtype);
  ChunkHeader ch{kChunkMagic, crc32c(w->buf.data(), body), w->rows_written,
                 w->buffered, body};
  if (std::fwrite(&ch, 1, sizeof(ch), w->f) != sizeof(ch)) return -EIO;
  if (std::fwrite(w->buf.data(), 1, body, w->f) != static_cast<size_t>(body))
    return -EIO;
  w->rows_written += w->buffered;
  w->nchunks += 1;
  w->buffered = 0;
  return 0;
}

}  // namespace

extern "C" {

uint32_t mcs_crc32c(const void* data, int64_t n) { return crc32c(data, n); }

void* mcs_writer_open(const char* path, int32_t dtype, int64_t ncols,
                      int64_t chunk_rows, int32_t* err) {
  *err = 0;
  if (itemsize(dtype) == 0 || ncols <= 0 || chunk_rows <= 0) {
    *err = -EINVAL;
    return nullptr;
  }
  FILE* f = std::fopen(path, "wb");
  if (!f) {
    *err = -errno;
    return nullptr;
  }
  // placeholder header: finalized (nrows/nchunks) on close
  FileHeader hdr{};
  if (std::fwrite(&hdr, 1, sizeof(hdr), f) != sizeof(hdr)) {
    *err = -EIO;
    std::fclose(f);
    return nullptr;
  }
  auto* w = new McsWriter;
  w->f = f;
  w->dtype = dtype;
  w->ncols = ncols;
  w->chunk_rows = chunk_rows;
  w->buf.resize(chunk_rows * ncols * itemsize(dtype));
  return w;
}

// Append nrows row-major rows (src_dtype in {f32, f64}); the writer
// converts to the stored dtype and flushes chunk_rows-sized chunks as they
// fill. Chunk size on disk is a property of the FILE, not of the append
// granularity — callers may append one row at a time.
int mcs_writer_append(void* handle, const void* rows, int64_t nrows,
                      int32_t src_dtype) {
  auto* w = static_cast<McsWriter*>(handle);
  if (!w || nrows < 0 || (src_dtype != kF32 && src_dtype != kF64))
    return -EINVAL;
  int64_t isz = itemsize(w->dtype);
  int64_t src_isz = itemsize(src_dtype);
  const auto* src = static_cast<const uint8_t*>(rows);
  while (nrows > 0) {
    int64_t take = std::min(nrows, w->chunk_rows - w->buffered);
    int rc = convert_rows(src, src_dtype,
                          w->buf.data() + w->buffered * w->ncols * isz,
                          w->dtype, take * w->ncols);
    if (rc != 0) return rc;
    w->buffered += take;
    src += take * w->ncols * src_isz;
    nrows -= take;
    if (w->buffered == w->chunk_rows) {
      if (int frc = flush_chunk(w); frc != 0) return frc;
    }
  }
  return 0;
}

int mcs_writer_close(void* handle) {
  auto* w = static_cast<McsWriter*>(handle);
  if (!w) return -EINVAL;
  int rc = flush_chunk(w);
  if (rc == 0) {
    FileHeader hdr{};
    std::memcpy(hdr.magic, kFileMagic, 8);
    hdr.version = kVersion;
    hdr.dtype = w->dtype;
    hdr.nrows = w->rows_written;
    hdr.ncols = w->ncols;
    hdr.chunk_rows = w->chunk_rows;
    hdr.nchunks = w->nchunks;
    if (std::fseek(w->f, 0, SEEK_SET) != 0 ||
        std::fwrite(&hdr, 1, sizeof(hdr), w->f) != sizeof(hdr))
      rc = -EIO;
  }
  if (std::fclose(w->f) != 0 && rc == 0) rc = errno ? -errno : -EIO;
  delete w;
  return rc;
}

void mcs_writer_abort(void* handle) {
  auto* w = static_cast<McsWriter*>(handle);
  if (!w) return;
  std::fclose(w->f);
  delete w;
}

// ------------------------------------------------------------------ reader
struct McsReader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t size = 0;
  FileHeader hdr{};
  int64_t rowbytes = 0;
  int64_t stride = 0;  // bytes per full chunk incl. header
};

void mcs_close(void* handle);

void* mcs_open(const char* path, int32_t* err) {
  *err = 0;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    *err = -errno;
    return nullptr;
  }
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    *err = -errno;
    ::close(fd);
    return nullptr;
  }
  if (static_cast<size_t>(st.st_size) < sizeof(FileHeader)) {
    *err = -EIO;  // shorter than its own header: torn write / not a store
    ::close(fd);
    return nullptr;
  }
  void* map = ::mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    *err = -errno;
    ::close(fd);
    return nullptr;
  }
  auto* r = new McsReader;
  r->fd = fd;
  r->map = static_cast<const uint8_t*>(map);
  r->size = st.st_size;
  std::memcpy(&r->hdr, r->map, sizeof(FileHeader));
  const FileHeader& h = r->hdr;
  int64_t isz = itemsize(h.dtype);
  bool valid = std::memcmp(h.magic, kFileMagic, 8) == 0 &&
               h.version == kVersion && isz > 0 && h.ncols > 0 &&
               h.chunk_rows > 0 && h.nrows >= 0;
  if (valid) {
    int64_t expect_chunks =
        h.nrows == 0 ? 0 : (h.nrows + h.chunk_rows - 1) / h.chunk_rows;
    valid = h.nchunks == expect_chunks;
  }
  if (!valid) {
    *err = -EINVAL;
    mcs_close(r);
    return nullptr;
  }
  r->rowbytes = h.ncols * isz;
  r->stride = sizeof(ChunkHeader) + h.chunk_rows * r->rowbytes;
  // the whole layout is computable — a size mismatch is a torn/truncated
  // file (short mmap) or trailing garbage, both fatal at open
  int64_t expect = sizeof(FileHeader);
  if (h.nchunks > 0) {
    int64_t last_rows = h.nrows - (h.nchunks - 1) * h.chunk_rows;
    expect += (h.nchunks - 1) * r->stride + sizeof(ChunkHeader) +
              last_rows * r->rowbytes;
  }
  if (static_cast<int64_t>(r->size) < expect) {
    *err = -EIO;
    mcs_close(r);
    return nullptr;
  }
  if (static_cast<int64_t>(r->size) > expect) {
    *err = -EINVAL;
    mcs_close(r);
    return nullptr;
  }
  return r;
}

int mcs_info(void* handle, int32_t* dtype, int64_t* nrows, int64_t* ncols,
             int64_t* chunk_rows, int64_t* nchunks) {
  auto* r = static_cast<McsReader*>(handle);
  if (!r) return -EINVAL;
  *dtype = r->hdr.dtype;
  *nrows = r->hdr.nrows;
  *ncols = r->hdr.ncols;
  *chunk_rows = r->hdr.chunk_rows;
  *nchunks = r->hdr.nchunks;
  return 0;
}

namespace {

// Validate + (optionally) checksum one chunk, then convert the rows the
// window touches into the caller's buffer. The CRC covers the whole chunk
// body, so even a partial-window read of a chunk verifies all of it —
// corruption is never skipped just because the window missed the bad byte.
int read_one_chunk(const McsReader* r, int64_t c, int64_t row_start,
                   int64_t nrows, uint8_t* out, int32_t out_dtype,
                   int64_t out_rowbytes, bool verify) {
  const FileHeader& h = r->hdr;
  const uint8_t* base = r->map + sizeof(FileHeader) + c * r->stride;
  ChunkHeader ch;
  std::memcpy(&ch, base, sizeof(ch));
  int64_t expect_rows = std::min(h.chunk_rows, h.nrows - c * h.chunk_rows);
  if (ch.magic != kChunkMagic || ch.row_offset != c * h.chunk_rows ||
      ch.nrows != expect_rows || ch.body_bytes != expect_rows * r->rowbytes)
    return -EINVAL;
  const uint8_t* body = base + sizeof(ChunkHeader);
  if (verify && crc32c(body, ch.body_bytes) != ch.crc32c) return -EBADMSG;
  int64_t lo = std::max(row_start, c * h.chunk_rows);
  int64_t hi = std::min(row_start + nrows, c * h.chunk_rows + expect_rows);
  return convert_rows(body + (lo - c * h.chunk_rows) * r->rowbytes, h.dtype,
                      out + (lo - row_start) * out_rowbytes, out_dtype,
                      (hi - lo) * h.ncols);
}

}  // namespace

// Gather rows [row_start, row_start+nrows) into `out` (row-major,
// out_dtype), validating each touched chunk's CRC when verify != 0. The
// touched chunks fan out over up to `threads` std::threads — combined with
// ctypes' GIL release this is the "multi-threaded parse/convert outside
// the GIL" half of the data plane.
int mcs_read(void* handle, int64_t row_start, int64_t nrows, void* out,
             int32_t out_dtype, int32_t threads, int32_t verify) {
  auto* r = static_cast<McsReader*>(handle);
  if (!r || itemsize(out_dtype) == 0 || row_start < 0 || nrows < 0 ||
      row_start + nrows > r->hdr.nrows)
    return -EINVAL;
  if (nrows == 0) return 0;
  int64_t c0 = row_start / r->hdr.chunk_rows;
  int64_t c1 = (row_start + nrows - 1) / r->hdr.chunk_rows;
  int64_t out_rowbytes = r->hdr.ncols * itemsize(out_dtype);
  auto* o = static_cast<uint8_t*>(out);
  int64_t nchunks = c1 - c0 + 1;
  int nthreads = std::max(1, std::min<int>({threads, 64,
                                            static_cast<int>(nchunks)}));
  if (nthreads == 1) {
    for (int64_t c = c0; c <= c1; ++c) {
      int rc = read_one_chunk(r, c, row_start, nrows, o, out_dtype,
                              out_rowbytes, verify != 0);
      if (rc != 0) return rc;
    }
    return 0;
  }
  std::atomic<int64_t> next{c0};
  std::atomic<int> first_err{0};
  auto work = [&] {
    for (;;) {
      int64_t c = next.fetch_add(1);
      if (c > c1 || first_err.load(std::memory_order_relaxed) != 0) return;
      int rc = read_one_chunk(r, c, row_start, nrows, o, out_dtype,
                              out_rowbytes, verify != 0);
      if (rc != 0) {
        int expected = 0;
        first_err.compare_exchange_strong(expected, rc);
        return;
      }
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(nthreads - 1);
  for (int t = 0; t < nthreads - 1; ++t) pool.emplace_back(work);
  work();
  for (auto& t : pool) t.join();
  return first_err.load();
}

void mcs_close(void* handle) {
  auto* r = static_cast<McsReader*>(handle);
  if (!r) return;
  if (r->map) ::munmap(const_cast<uint8_t*>(r->map), r->size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

// ----------------------------------------------------------- text converter
// Transcode the row-text format ("rowIdx:v,v,...") into a chunk file —
// the mc_write converter reusing the textio parser (parse_common.h). Rows
// must be contiguous and in order (0..m-1) with rectangular width, the
// same contract as the streaming text iterator (io/text.py
// iter_matrix_file_chunks): the chunk container is row-major by
// construction, so a gapped/shuffled file must go through the buffering
// loader first. A partial output file is unlinked on failure — a torn
// sidecar must never shadow its source.
int mcs_from_text(const char* src, const char* dst, int64_t chunk_rows,
                  int32_t dtype, int64_t* out_rows, int64_t* out_cols) {
  FileBuf buf;
  if (int rc = buf.read(src); rc != 0) return rc;
  int32_t werr = 0;
  void* w = nullptr;
  std::vector<double> rowbuf;
  int64_t ncols = -1, row = 0;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  int rc = 0;
  while (p < end && rc == 0) {
    const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* colon =
        static_cast<const char*>(std::memchr(p, ':', line_end - p));
    if (!colon) {
      for (const char* q = p; q < line_end; ++q) {
        if (*q != ' ' && *q != '\t' && *q != '\r') {
          rc = -EINVAL;
          break;
        }
      }
    } else {
      char* after = nullptr;
      long long ridx = std::strtoll(p, &after, 10);
      if (after == p || !after || after > colon || ridx != row) {
        rc = -EINVAL;  // non-contiguous/out-of-order rows: see docstring
        break;
      }
      int64_t j = 0;
      const char* q = colon + 1;
      while (q < line_end) {
        q = skip_seps(q, line_end);
        if (q >= line_end) break;
        double v;
        const char* next = parse_value(q, line_end, &v);
        if (!next) {
          rc = -EINVAL;
          break;
        }
        if (ncols < 0)
          rowbuf.push_back(v);
        else if (j < ncols)
          rowbuf[j] = v;
        ++j;
        q = next;
      }
      if (rc != 0) break;
      if (ncols < 0) {
        ncols = j;
        if (ncols == 0) {
          rc = -EINVAL;
          break;
        }
        w = mcs_writer_open(dst, dtype, ncols, chunk_rows, &werr);
        if (!w) {
          rc = werr;
          break;
        }
      }
      if (j != ncols) {
        rc = -EINVAL;  // ragged row: rectangular contract
        break;
      }
      rc = mcs_writer_append(w, rowbuf.data(), 1, kF64);
      ++row;
    }
    p = line_end + 1;
  }
  if (rc == 0 && w == nullptr) rc = -EINVAL;  // empty file: nothing to store
  if (rc == 0) rc = mcs_writer_close(w);
  else if (w) mcs_writer_abort(w);
  if (rc != 0) std::remove(dst);
  else {
    *out_rows = row;
    *out_cols = ncols;
  }
  return rc;
}

}  // extern "C"
