"""ctypes bindings for the native IO libraries, with auto-build.

The reference's data loading is Spark-JVM-side (MTUtils loaders); the
TPU-native runtime keeps the data plane in C++ and binds it here via ctypes —
no pybind11 dependency. Two libraries:

- ``libmarlin_textio.so``   — row-text parser/writer (textio.cpp)
- ``libmarlin_chunkstore.so`` — MarlinChunk binary container (chunkstore.cpp),
  the mmap'd data plane behind marlin_tpu.io.chunkstore

If a shared object is missing we try one ``make`` (the toolchain is a
build-time requirement, not runtime) and fall back to the pure-Python paths
otherwise — but never *silently*: a failed build emits a one-time
``RuntimeWarning`` carrying the captured make stderr, and ``build_error()``
exposes it so tests and the bench harness can assert which path actually ran
(a quietly-shadowed native plane is a 100x perf bug that looks like a pass).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmarlin_textio.so")
_CHUNK_SO = os.path.join(_HERE, "libmarlin_chunkstore.so")
_lib = None
_chunk_lib = None
_tried_build = False
_build_error: str | None = None
_warned = False


def _run_make() -> None:
    """One ``make`` over native/; capture failure text into ``_build_error``.

    make no-ops when the .so files are newer than the sources and rebuilds
    after a .cpp/.h edit (a stale binary would silently shadow fixes
    otherwise). A missing toolchain or compile error lands in
    ``_build_error`` — surfaced by :func:`build_error` and warned once in
    :func:`_load`.
    """
    global _tried_build, _build_error
    if _tried_build:
        return
    _tried_build = True
    try:
        proc = subprocess.run(["make", "-s", "-C", _HERE],
                              capture_output=True, timeout=120, text=True)
    except Exception as e:  # make missing, timeout, ...
        _build_error = f"{type(e).__name__}: {e}"
        return
    if proc.returncode != 0:
        err = (proc.stderr or proc.stdout or "").strip()
        _build_error = (f"make exited {proc.returncode}: "
                        f"{err or '(no output)'}")


def build_error() -> str | None:
    """Why the last native build attempt failed, or None.

    None means either the build succeeded or no build was attempted yet
    (nothing has called into the native layer). Tests and bench use this to
    assert the native path genuinely ran rather than being silently shadowed
    by the pure-Python fallback.
    """
    return _build_error


def _warn_once(missing: str) -> None:
    global _warned
    if _warned or _build_error is None:
        return
    _warned = True
    warnings.warn(
        f"marlin_tpu native build failed ({missing} unavailable; falling "
        f"back to the pure-Python data plane, expect ~100x slower IO): "
        f"{_build_error}",
        RuntimeWarning, stacklevel=3)


def _load():
    global _lib
    if _lib is not None:
        return _lib
    _run_make()
    if not os.path.exists(_SO):
        _warn_once(os.path.basename(_SO))
        return None
    lib = ctypes.CDLL(_SO)
    lib.mt_count_matrix.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mt_count_matrix.restype = ctypes.c_int
    lib.mt_load_matrix.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.mt_load_matrix.restype = ctypes.c_int
    lib.mt_save_matrix.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.mt_save_matrix.restype = ctypes.c_int
    lib.mt_save_coo.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.mt_save_coo.restype = ctypes.c_int
    lib.mt_save_coo_f32.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.mt_save_coo_f32.restype = ctypes.c_int
    _lib = lib
    return _lib


def _load_chunkstore():
    """Bind libmarlin_chunkstore.so; None (with the one-time warning) if the
    build failed. ctypes releases the GIL for the duration of every call, so
    mcs_read's parse/verify/convert runs truly parallel to Python."""
    global _chunk_lib
    if _chunk_lib is not None:
        return _chunk_lib
    _run_make()
    if not os.path.exists(_CHUNK_SO):
        _warn_once(os.path.basename(_CHUNK_SO))
        return None
    lib = ctypes.CDLL(_CHUNK_SO)
    lib.mcs_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mcs_crc32c.restype = ctypes.c_uint32
    lib.mcs_writer_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.mcs_writer_open.restype = ctypes.c_void_p
    lib.mcs_writer_append.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.mcs_writer_append.restype = ctypes.c_int
    lib.mcs_writer_close.argtypes = [ctypes.c_void_p]
    lib.mcs_writer_close.restype = ctypes.c_int
    lib.mcs_writer_abort.argtypes = [ctypes.c_void_p]
    lib.mcs_writer_abort.restype = None
    lib.mcs_open.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_int32)]
    lib.mcs_open.restype = ctypes.c_void_p
    lib.mcs_info.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mcs_info.restype = ctypes.c_int
    lib.mcs_read.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.mcs_read.restype = ctypes.c_int
    lib.mcs_close.argtypes = [ctypes.c_void_p]
    lib.mcs_close.restype = None
    lib.mcs_from_text.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.mcs_from_text.restype = ctypes.c_int
    _chunk_lib = lib
    return _chunk_lib


def available() -> bool:
    return _load() is not None


def chunkstore_available() -> bool:
    return _load_chunkstore() is not None


def load_matrix_text(path: str) -> np.ndarray | None:
    """Parse a row-text matrix file natively; None if the library is absent."""
    lib = _load()
    if lib is None:
        return None
    import errno as _errno

    rows, cols = ctypes.c_int64(), ctypes.c_int64()
    rc = lib.mt_count_matrix(path.encode(), ctypes.byref(rows), ctypes.byref(cols))
    if -rc == _errno.EINVAL:
        raise ValueError(f"unparseable numeric token in {path}")
    if rc != 0:
        raise OSError(-rc, f"native count failed for {path}")
    out = np.zeros((rows.value, cols.value), np.float64)
    rc = lib.mt_load_matrix(path.encode(), out, rows.value, cols.value)
    if -rc == _errno.EINVAL:
        raise ValueError(f"unparseable numeric token in {path}")
    if rc != 0:
        raise OSError(-rc, f"native load failed for {path}")
    return out


def save_matrix_text(path: str, data: np.ndarray) -> bool:
    """Write a row-text matrix file natively; False if the library is absent."""
    lib = _load()
    if lib is None:
        return False
    arr = np.ascontiguousarray(data, np.float64)
    rc = lib.mt_save_matrix(path.encode(), arr, arr.shape[0], arr.shape[1])
    if rc != 0:
        raise OSError(-rc, f"native save failed for {path}")
    return True


def save_coo_text(path: str, rows, cols, vals) -> bool:
    """Write "i j v" COO lines natively; False if the library is absent.
    f32 values take the ~5x-faster shortest-f32 formatter (exact for them);
    anything else is written as shortest-f64."""
    lib = _load()
    if lib is None:
        return False
    r = np.ascontiguousarray(rows, np.int64)
    c = np.ascontiguousarray(cols, np.int64)
    vals = np.asarray(vals)
    if not (r.shape == c.shape == vals.shape and r.ndim == 1):
        raise ValueError(f"COO arrays must be equal-length 1-D, got "
                         f"{r.shape}/{c.shape}/{vals.shape}")
    if vals.dtype == np.float32:
        v = np.ascontiguousarray(vals)
        rc = lib.mt_save_coo_f32(path.encode(), r, c, v, r.shape[0])
    else:
        v = np.ascontiguousarray(vals, np.float64)
        rc = lib.mt_save_coo(path.encode(), r, c, v, r.shape[0])
    if rc != 0:
        raise OSError(-rc, f"native COO save failed for {path}")
    return True
