"""ctypes bindings for the native text-IO library, with auto-build.

The reference's data loading is Spark-JVM-side (MTUtils loaders); the
TPU-native runtime keeps the data plane in C++ (textio.cpp) and binds it here
via ctypes — no pybind11 dependency. If the shared object is missing, we try
one `make` (the toolchain is a build-time requirement, not runtime), and fall
back to the pure-Python parser in marlin_tpu.io.text otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmarlin_textio.so")
_lib = None
_tried_build = False


def _load():
    global _lib, _tried_build
    if _lib is not None:
        return _lib
    if not _tried_build:
        # always let make decide — it no-ops when the .so is newer than the
        # source, and rebuilds after a textio.cpp edit (a stale binary would
        # silently shadow fixes otherwise)
        _tried_build = True
        try:
            subprocess.run(["make", "-s", "-C", _HERE],
                           capture_output=True, timeout=120)
        except Exception:
            pass
    if os.path.exists(_SO):
        lib = ctypes.CDLL(_SO)
        lib.mt_count_matrix.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mt_count_matrix.restype = ctypes.c_int
        lib.mt_load_matrix.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.mt_load_matrix.restype = ctypes.c_int
        lib.mt_save_matrix.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.mt_save_matrix.restype = ctypes.c_int
        lib.mt_save_coo.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
        ]
        lib.mt_save_coo.restype = ctypes.c_int
        lib.mt_save_coo_f32.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
        ]
        lib.mt_save_coo_f32.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def load_matrix_text(path: str) -> np.ndarray | None:
    """Parse a row-text matrix file natively; None if the library is absent."""
    lib = _load()
    if lib is None:
        return None
    import errno as _errno

    rows, cols = ctypes.c_int64(), ctypes.c_int64()
    rc = lib.mt_count_matrix(path.encode(), ctypes.byref(rows), ctypes.byref(cols))
    if -rc == _errno.EINVAL:
        raise ValueError(f"unparseable numeric token in {path}")
    if rc != 0:
        raise OSError(-rc, f"native count failed for {path}")
    out = np.zeros((rows.value, cols.value), np.float64)
    rc = lib.mt_load_matrix(path.encode(), out, rows.value, cols.value)
    if -rc == _errno.EINVAL:
        raise ValueError(f"unparseable numeric token in {path}")
    if rc != 0:
        raise OSError(-rc, f"native load failed for {path}")
    return out


def save_matrix_text(path: str, data: np.ndarray) -> bool:
    """Write a row-text matrix file natively; False if the library is absent."""
    lib = _load()
    if lib is None:
        return False
    arr = np.ascontiguousarray(data, np.float64)
    rc = lib.mt_save_matrix(path.encode(), arr, arr.shape[0], arr.shape[1])
    if rc != 0:
        raise OSError(-rc, f"native save failed for {path}")
    return True


def save_coo_text(path: str, rows, cols, vals) -> bool:
    """Write "i j v" COO lines natively; False if the library is absent.
    f32 values take the ~5x-faster shortest-f32 formatter (exact for them);
    anything else is written as shortest-f64."""
    lib = _load()
    if lib is None:
        return False
    r = np.ascontiguousarray(rows, np.int64)
    c = np.ascontiguousarray(cols, np.int64)
    vals = np.asarray(vals)
    if not (r.shape == c.shape == vals.shape and r.ndim == 1):
        raise ValueError(f"COO arrays must be equal-length 1-D, got "
                         f"{r.shape}/{c.shape}/{vals.shape}")
    if vals.dtype == np.float32:
        v = np.ascontiguousarray(vals)
        rc = lib.mt_save_coo_f32(path.encode(), r, c, v, r.shape[0])
    else:
        v = np.ascontiguousarray(vals, np.float64)
        rc = lib.mt_save_coo(path.encode(), r, c, v, r.shape[0])
    if rc != 0:
        raise OSError(-rc, f"native COO save failed for {path}")
    return True
