// textio — native parser/writer for the Marlin row-text matrix format.
//
// The reference's data plane is JVM-side: Spark textFile + per-line
// String.split parsing (MTUtils.loadMatrixFile, utils/MTUtils.scala:286-300).
// Python's equivalent (str.split + float()) parses at ~30 MB/s, which turns
// multi-GB matrix loads into minutes. This C library parses the
// "rowIdx:v,v,..." format at memory-bandwidth-ish speed and is exposed to
// Python via ctypes (marlin_tpu/native/__init__.py) with a pure-Python
// fallback when the shared object hasn't been built.
//
// Build: make -C marlin_tpu/native   (produces libmarlin_textio.so)
//
// Exported C ABI (all return 0 on success, negative on error):
//   mt_count_matrix(path, *rows, *cols)   — scan pass: dimensions
//   mt_load_matrix(path, out, rows, cols) — parse pass: fill row-major f64
//   mt_save_matrix(path, data, rows, cols)— write the same format
//   mt_save_coo(path, rows, cols, vals, nnz) — "i j v" COO lines
//     (CoordinateMatrix text format, matrix/CoordinateMatrix.scala entries;
//      std::to_chars shortest round-trip, matching Python repr() precision)

#include <cerrno>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "parse_common.h"

namespace {

using marlin_native::FileBuf;
using marlin_native::parse_value;
using marlin_native::skip_seps;

// Shortest-round-trip value formatter. FP to_chars where libstdc++ has it
// (GCC 11+); otherwise printf with the dtype's round-trip precision (%.17g
// f64 / %.9g f32 — longer than shortest for some values, still exact).
inline char* format_value(char* p, char* cap, double v) {
#if defined(__cpp_lib_to_chars)
  return std::to_chars(p, cap, v).ptr;
#else
  return p + std::snprintf(p, cap - p, "%.17g", v);
#endif
}

inline char* format_value(char* p, char* cap, float v) {
#if defined(__cpp_lib_to_chars)
  return std::to_chars(p, cap, v).ptr;
#else
  return p + std::snprintf(p, cap - p, "%.9g", static_cast<double>(v));
#endif
}

}  // namespace

extern "C" {

int mt_count_matrix(const char* path, int64_t* rows, int64_t* cols) {
  FileBuf buf;
  if (int rc = buf.read(path); rc != 0) return rc;
  int64_t max_row = -1, ncols = 0;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  while (p < end) {
    const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* colon = static_cast<const char*>(std::memchr(p, ':', line_end - p));
    if (!colon) {
      // only blank lines may lack the "rowIdx:" prefix — anything else is
      // not this format (the Python parser raises there too)
      for (const char* q = p; q < line_end; ++q) {
        if (*q != ' ' && *q != '\t' && *q != '\r') return -EINVAL;
      }
    } else {
      char* after = nullptr;
      long long r = std::strtoll(p, &after, 10);
      if (after == p || !after || after > colon) return -EINVAL;  // bad row idx
      {
        if (r > max_row) max_row = r;
        // count values on every line: ragged inputs get the max width,
        // matching the Python parser's behavior. An unparseable token is a
        // hard error (the Python parser raises there too) — never silently
        // truncate.
        int64_t line_cols = 0;
        const char* q = colon + 1;
        while (q < line_end) {
          q = skip_seps(q, line_end);
          if (q >= line_end) break;
          double v;
          const char* next = parse_value(q, line_end, &v);
          if (!next) return -EINVAL;
          ++line_cols;
          q = next;
        }
        if (line_cols > ncols) ncols = line_cols;
      }
    }
    p = line_end + 1;
  }
  *rows = max_row + 1;
  *cols = ncols;
  return 0;
}

int mt_load_matrix(const char* path, double* out, int64_t rows, int64_t cols) {
  FileBuf buf;
  if (int rc = buf.read(path); rc != 0) return rc;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  while (p < end) {
    const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* colon = static_cast<const char*>(std::memchr(p, ':', line_end - p));
    if (!colon) {
      for (const char* q = p; q < line_end; ++q) {
        if (*q != ' ' && *q != '\t' && *q != '\r') return -EINVAL;
      }
    } else {
      char* after = nullptr;
      long long r = std::strtoll(p, &after, 10);
      if (after == p || !after || after > colon) return -EINVAL;
      if (r >= 0 && r < rows) {
        double* row_out = out + r * cols;
        const char* q = colon + 1;
        int64_t j = 0;
        while (q < line_end && j < cols) {
          q = skip_seps(q, line_end);
          if (q >= line_end) break;
          double v;
          const char* next = parse_value(q, line_end, &v);
          if (!next) return -EINVAL;  // corrupt token: fail, don't zero-fill
          row_out[j++] = v;
          q = next;
        }
      }
    }
    p = line_end + 1;
  }
  return 0;
}

int mt_save_matrix(const char* path, const double* data, int64_t rows, int64_t cols) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -errno;
  static char iobuf[1 << 20];
  std::setvbuf(f, iobuf, _IOFBF, sizeof(iobuf));
  for (int64_t i = 0; i < rows; ++i) {
    std::fprintf(f, "%lld:", static_cast<long long>(i));
    const double* row = data + i * cols;
    for (int64_t j = 0; j < cols; ++j) {
      std::fprintf(f, j + 1 == cols ? "%.17g" : "%.17g,", row[j]);
    }
    std::fputc('\n', f);
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"

namespace {

// Format into a big user-space buffer with to_chars (shortest round-trip,
// like Python repr) and flush in MB-scale fwrites — 10^8 nnz in ~20 s where
// the per-line Python writer took minutes (matrix/sparse.py). The f32
// overload is ~5x faster per value AND exact for f32-originated data (the
// CoordinateMatrix value type, matching the reference's Float entries,
// matrix/CoordinateMatrix.scala:14) — shortest-repr of the f64 image of an
// f32 would pay up to 17 digits for nothing.
template <typename V>
int save_coo_impl(const char* path, const int64_t* rows, const int64_t* cols,
                  const V* vals, int64_t nnz) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -errno;
  constexpr size_t kCap = size_t{1} << 22;
  constexpr size_t kMaxLine = 96;  // 2 int64s + value + separators, worst case
  char* buf = static_cast<char*>(std::malloc(kCap));
  if (!buf) {
    std::fclose(f);
    return -ENOMEM;
  }
  size_t used = 0;
  for (int64_t k = 0; k < nnz; ++k) {
    if (used + kMaxLine > kCap) {
      if (std::fwrite(buf, 1, used, f) != used) {
        std::free(buf);
        std::fclose(f);
        return -EIO;
      }
      used = 0;
    }
    char* p = buf + used;
    char* cap = buf + kCap;
    p = std::to_chars(p, cap, static_cast<long long>(rows[k])).ptr;
    *p++ = ' ';
    p = std::to_chars(p, cap, static_cast<long long>(cols[k])).ptr;
    *p++ = ' ';
    p = format_value(p, cap, vals[k]);
    *p++ = '\n';
    used = p - buf;
  }
  int rc = 0;
  if (used && std::fwrite(buf, 1, used, f) != used) rc = -EIO;
  std::free(buf);
  if (std::fclose(f) != 0 && rc == 0) rc = -errno;
  return rc;
}

}  // namespace

extern "C" {

int mt_save_coo(const char* path, const int64_t* rows, const int64_t* cols,
                const double* vals, int64_t nnz) {
  return save_coo_impl(path, rows, cols, vals, nnz);
}

int mt_save_coo_f32(const char* path, const int64_t* rows, const int64_t* cols,
                    const float* vals, int64_t nnz) {
  return save_coo_impl(path, rows, cols, vals, nnz);
}

}  // extern "C"
