"""Blocked dense factorizations on sharded global arrays.

The reference implements block LU / Cholesky / inverse as driver-orchestrated
panel+trailing-update loops: each iteration filters the pivot block out of the
RDD, *collects it to the driver*, factorizes it with Breeze there, broadcasts
the factors back, applies panel updates, and shuffle-multiplies the trailing
submatrix (DenseVecMatrix.scala:283-466 LU, 475-561 Cholesky, 568-764 inverse).
The per-iteration driver round-trip is its scalability bottleneck (SURVEY.md §3.3).

TPU-first, the whole factorization is ONE jitted XLA program: a
``lax.fori_loop`` over block columns where the pivot block is factorized
*on-device* (``jax.lax.linalg.lu`` / ``jnp.linalg.cholesky`` on a b×b slice —
the "collect+broadcast" disappears into XLA's implicit data movement), panel
updates are masked triangular solves over full-width panels (static shapes for
XLA; masks replace the shrinking trailing extents), and the trailing update is
a full-size rank-b GEMM with masked operands — zero contribution outside the
trailing region, so no dynamic shapes anywhere.

Pivoting matches the reference's choice: partial pivoting *within the pivot
block only* (the reference LUs just the collected pivot block,
DenseVecMatrix.scala:345-349), with row swaps applied across the full width and
the global permutation accumulated.

Numerical trade-off, stated: panel updates multiply by the explicitly inverted
b×b pivot triangles (one small solve per step, then MXU GEMMs across the
panel) instead of running n-wide triangular solves. For an ill-conditioned
pivot block (κ ≈ 1/eps) the inverse carries κ·eps relative error into the
panel, where backward-stable solves would not — the same trade the reference
makes by broadcasting pivot inverses (DenseVecMatrix.scala:370-387), and
consistent with block-local pivoting already bounding stability. Accuracy-
critical callers with adversarial inputs should use mode="local" (LAPACK-style
full factorization).

Square inputs are padded with an identity tail so the padded problem stays
nonsingular; block size comes from the config knobs that mirror
``marlin.lu.basesize``/``marlin.cholesky.basesize``/``marlin.inverse.basesize``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from ..config import get_config
from ..mesh import pad_to_multiple

__all__ = ["lu_decompose", "cholesky_decompose", "inverse"]


def _pad_with_identity(a: jax.Array, n_pad: int) -> jax.Array:
    """Embed the n×n matrix in an n_pad×n_pad one with an identity tail block,
    so factorizations of the padded matrix restrict to the original."""
    n = a.shape[0]
    if n_pad == n:
        return a
    out = jnp.zeros((n_pad, n_pad), a.dtype)
    out = out.at[:n, :n].set(a)
    pad_diag = jnp.arange(n, n_pad)
    return out.at[pad_diag, pad_diag].set(jnp.ones((), a.dtype))


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_lu(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked LU with block-local partial pivoting.
    Returns (LU-combined, global permutation vector)."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    perm0 = jnp.arange(n, dtype=jnp.int32)
    col_idx = jnp.arange(n)
    row_idx = jnp.arange(n)[:, None]

    eye_b = jnp.eye(block)

    def body(i, carry):
        a, gperm = carry
        o = i * block
        piv = jax.lax.dynamic_slice(a, (o, o), (block, block))
        lu, _, p = jax.lax.linalg.lu(piv)
        l11 = jnp.tril(lu, -1) + jnp.eye(block, dtype=a.dtype)
        u11 = jnp.triu(lu)
        # invert the small triangles once (b×b solves), so the full-width
        # panel updates become GEMMs on the MXU instead of n-wide triangular
        # solves — the same trick the reference's panel updates use
        # (broadcast pivot inverse, DenseVecMatrix.scala:370-387)
        l11_inv = solve(l11, eye_b.astype(a.dtype), lower=True, unit_diagonal=True)
        u11_inv = solve(u11.T, eye_b.astype(a.dtype), lower=True).T

        # Row panel (rows o:o+b, full width): permute rows, then
        #   cols <  o      -> permuted L-part unchanged
        #   o..o+b         -> the combined lu block
        #   cols >= o+b    -> U12 = L11^{-1} (P A12)
        rpan = jax.lax.dynamic_slice(a, (o, 0), (block, n))
        rpan = rpan[p, :]
        u12 = jnp.dot(l11_inv, rpan, precision="highest")
        in_block = (col_idx[None, :] >= o) & (col_idx[None, :] < o + block)
        lu_wide = jax.lax.dynamic_update_slice(jnp.zeros_like(rpan), lu, (0, o))
        rpan_new = jnp.where(
            col_idx[None, :] < o, rpan, jnp.where(in_block, lu_wide, u12)
        )
        a = jax.lax.dynamic_update_slice(a, rpan_new, (o, 0))

        # Column panel (full height, cols o:o+b): rows >= o+b get
        # L21 = A21 U11^{-1}; rows above keep what's already written.
        cpan = jax.lax.dynamic_slice(a, (0, o), (n, block))
        l21 = jnp.dot(cpan, u11_inv, precision="highest")
        below = row_idx >= o + block
        cpan_new = jnp.where(below, l21, cpan)
        a = jax.lax.dynamic_update_slice(a, cpan_new, (0, o))

        # Trailing update with masked operands: zero outside the trailing
        # region, so the full-size GEMM only touches A22.
        l21_m = jnp.where(below, l21, jnp.zeros((), a.dtype))
        u12_m = jnp.where(col_idx[None, :] >= o + block, u12, jnp.zeros((), a.dtype))
        a = a - jnp.dot(l21_m, u12_m, precision="highest")

        # Accumulate the global permutation.
        gseg = jax.lax.dynamic_slice(gperm, (o,), (block,))
        gperm = jax.lax.dynamic_update_slice(gperm, gseg[p], (o,))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
        return a, gperm

    return jax.lax.fori_loop(0, nb, body, (a, perm0))


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_cholesky(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked Cholesky (lower). No pivoting (SPD input)."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    row_idx = jnp.arange(n)[:, None]

    eye_b = jnp.eye(block)

    def body(i, a):
        o = i * block
        piv = jax.lax.dynamic_slice(a, (o, o), (block, block))
        l11 = jnp.linalg.cholesky(piv)
        l11_inv = solve(l11, eye_b.astype(a.dtype), lower=True)

        cpan = jax.lax.dynamic_slice(a, (0, o), (n, block))
        l21 = jnp.dot(cpan, l11_inv.T, precision="highest")
        below = row_idx >= o + block
        at_block = (row_idx >= o) & (row_idx < o + block)
        l11_tall = jax.lax.dynamic_update_slice(jnp.zeros_like(cpan), l11, (o, 0))
        cpan_new = jnp.where(below, l21, jnp.where(at_block, l11_tall, cpan))
        a = jax.lax.dynamic_update_slice(a, cpan_new, (0, o))

        l21_m = jnp.where(below, l21, jnp.zeros((), a.dtype))
        a = a - jnp.dot(l21_m, l21_m.T, precision="highest")
        # restore the block column (the rank-b update also touched it)
        a = jax.lax.dynamic_update_slice(a, cpan_new, (0, o))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
        return a

    a = jax.lax.fori_loop(0, nb, body, a)
    return jnp.tril(a)


def _require_square(mat):
    if mat.num_rows() != mat.num_cols():
        raise ValueError(f"factorization needs a square matrix, got {mat.shape}")


def _mode_to_local(mode: str, n: int) -> bool:
    cfg = get_config()
    if mode in ("local", "breeze"):  # "breeze" kept as a parity alias
        return True
    if mode in ("dist", "distspark"):
        return False
    if mode == "auto":  # reference: n > 6000 -> dist (DenseVecMatrix.scala:289-298)
        return n <= cfg.local_fallback_dim
    raise ValueError(f"unknown factorization mode: {mode}")


def lu_decompose(mat, mode: str = "auto", block_size: int | None = None):
    """Block LU with partial pivoting (DenseVecMatrix.luDecompose,
    DenseVecMatrix.scala:283-466). Returns ``(L, U, perm)`` where ``perm`` is
    the row-permutation vector: ``A[perm] == L @ U``."""
    _require_square(mat)
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        lu, _, p = jax.lax.linalg.lu(a)
        l = jnp.tril(lu, -1) + jnp.eye(n, dtype=a.dtype)
        u = jnp.triu(lu)
        return mat._wrap(l), mat._wrap(u), np.asarray(jax.device_get(p))

    b = block_size or get_config().lu_base_size
    b = min(b, n)
    n_pad = pad_to_multiple(n, b)
    a_pad = _pad_with_identity(a, n_pad)
    sharding = NamedSharding(mat.mesh, mat.spec) if n_pad % _grid(mat) == 0 else None
    lu_pad, perm = _blocked_lu(a_pad, b, sharding)
    lu_log = lu_pad[:n, :n]
    l = jnp.tril(lu_log, -1) + jnp.eye(n, dtype=a.dtype)
    u = jnp.triu(lu_log)
    return mat._wrap(l), mat._wrap(u), np.asarray(jax.device_get(perm[:n]))


def _grid(mat) -> int:
    """LCM-ish divisor check helper: the row-axis shard count of the matrix."""
    ax = mat.spec[0] if len(mat.spec) > 0 else None
    return mat.mesh.shape[ax] if ax is not None else 1


def cholesky_decompose(mat, mode: str = "auto", block_size: int | None = None):
    """Block Cholesky, lower factor (DenseVecMatrix.choleskyDecompose,
    DenseVecMatrix.scala:475-561). Returns L with ``A == L @ Lᵀ``."""
    _require_square(mat)
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        return mat._wrap(jnp.linalg.cholesky(a))
    b = block_size or get_config().cholesky_base_size
    b = min(b, n)
    n_pad = pad_to_multiple(n, b)
    a_pad = _pad_with_identity(a, n_pad)
    sharding = NamedSharding(mat.mesh, mat.spec) if n_pad % _grid(mat) == 0 else None
    l_pad = _blocked_cholesky(a_pad, b, sharding)
    return mat._wrap(l_pad[:n, :n])


@functools.partial(jax.jit, static_argnames=("block",))
def _inverse_via_lu(a: jax.Array, block: int):
    lu_pad, perm = _blocked_lu(a, block)
    n = a.shape[0]
    solve = jax.scipy.linalg.solve_triangular
    l = jnp.tril(lu_pad, -1) + jnp.eye(n, dtype=a.dtype)
    u = jnp.triu(lu_pad)
    # A[perm] = L U  =>  A^{-1} = (U^{-1} L^{-1}) P  where P x = x[perm]
    pa_inv = solve(u, solve(l, jnp.eye(n, dtype=a.dtype), lower=True, unit_diagonal=True))
    return pa_inv[:, jnp.argsort(perm)][:, :n]  # apply P on the right


def inverse(mat, mode: str = "auto", block_size: int | None = None):
    """Matrix inverse (DenseVecMatrix.inverse, DenseVecMatrix.scala:568-764).
    The reference runs a blocked Gauss-Jordan-style forward + backward sweep
    with driver-factorized pivots; here it is blocked LU + two sharded
    triangular solves in one XLA program."""
    _require_square(mat)
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        return mat._wrap(jnp.linalg.inv(a))
    b = block_size or get_config().inverse_base_size
    b = min(b, n)
    n_pad = pad_to_multiple(n, b)
    a_pad = _pad_with_identity(a, n_pad)
    inv_pad = _inverse_via_lu(a_pad, b)
    return mat._wrap(inv_pad[:n, :n])
