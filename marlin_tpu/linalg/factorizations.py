"""Blocked dense factorizations on sharded global arrays.

The reference implements block LU / Cholesky / inverse as driver-orchestrated
panel+trailing-update loops: each iteration filters the pivot block out of the
RDD, *collects it to the driver*, factorizes it with Breeze there, broadcasts
the factors back, applies panel updates, and shuffle-multiplies the trailing
submatrix (DenseVecMatrix.scala:283-466 LU, 475-561 Cholesky, 568-764 inverse).
The per-iteration driver round-trip is its scalability bottleneck (SURVEY.md §3.3).

TPU-first, the whole factorization is ONE jitted XLA program with the pivot
block factorized *on-device* (``jax.lax.linalg.lu`` / ``jnp.linalg.cholesky``
on a b×b slice — the "collect+broadcast" disappears into XLA's implicit data
movement). Two schedules exist (``schedule=`` on the public functions):

- ``"shrinking"`` (LU default up to 64 block steps): the Python loop over
  block columns unrolls at trace time, so every step's panel/trailing slices
  have their true static shrinking shapes — the ideal 2n³/3 FLOPs, at the
  cost of one compiled GEMM shape per step.
- ``"masked"`` (Cholesky default): a single ``lax.fori_loop`` body reused for
  every step — full-width panels with masked operands (zero contribution
  outside the trailing region), one compiled shape total but ~3× the ideal
  FLOPs. This is the scalable-step-count form and the only one for
  ``pivot="panel"``.

``"auto"`` resolves per op from the r5 on-chip shoot-out (8192²): LU
shrinking 2758 vs masked 2069 GFLOP/s, but Cholesky masked 1480 vs
shrinking 1319 — see ``_resolve_schedule``.

Pivoting: the default (``pivot="block"``) matches the reference's choice —
partial pivoting *within the pivot block only* (the reference LUs just the
collected pivot block, DenseVecMatrix.scala:345-349) — with row swaps applied
across the full width and the global permutation accumulated.
``pivot="panel"`` upgrades to LAPACK getrf-style full-height panel pivoting
(pivot search over the entire trailing column), which handles singular or
ill-conditioned pivot blocks the block-local strategy cannot, at the cost of a
serial per-column panel phase.

Numerical trade-off, stated: panel updates multiply by the explicitly inverted
b×b pivot triangles (one small solve per step, then MXU GEMMs across the
panel) instead of running n-wide triangular solves. For an ill-conditioned
pivot block (κ ≈ 1/eps) the inverse carries κ·eps relative error into the
panel, where backward-stable solves would not — the same trade the reference
makes by broadcasting pivot inverses (DenseVecMatrix.scala:370-387), and
consistent with block-local pivoting already bounding stability. Accuracy-
critical callers with adversarial inputs should use mode="local" (LAPACK-style
full factorization).

Square inputs are padded with an identity tail so the padded problem stays
nonsingular; block size comes from the config knobs that mirror
``marlin.lu.basesize``/``marlin.cholesky.basesize``/``marlin.inverse.basesize``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ..config import get_config
from ..mesh import pad_to_multiple

__all__ = ["lu_decompose", "cholesky_decompose", "inverse", "PIVOT_STRATEGIES",
           "SCHEDULES"]

PIVOT_STRATEGIES = ("block", "panel")
SCHEDULES = ("auto", "shrinking", "masked")

# above this many block steps the unrolled shrinking schedule's per-step
# compilation cost outweighs its 3x FLOP saving; fall back to the single
# fori_loop program
_MAX_UNROLL_STEPS = 64


def _require_pivot(pivot: str) -> None:
    if pivot not in PIVOT_STRATEGIES:
        raise ValueError(
            f"unknown pivot strategy: {pivot!r} (one of {PIVOT_STRATEGIES})"
        )


def _resolve_schedule(schedule: str, nb: int, pivot: str = "block",
                      op: str = "lu") -> str:
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown schedule: {schedule!r} (one of {SCHEDULES})")
    if schedule == "shrinking" and pivot == "panel":
        raise ValueError('schedule="shrinking" supports pivot="block" only '
                         '(panel pivoting keeps the masked full-width loop)')
    if schedule == "auto":
        # Measured on the v5e (BENCH_ALL r5, 8192²): LU shrinking beats
        # masked 2758 vs 2069 GFLOP/s, but Cholesky masked beats shrinking
        # 1480 vs 1319 — Cholesky's symmetric trailing update keeps the MXU
        # busier in the single fori_loop program than LU's, so the unrolled
        # schedule's per-step compile cost is not repaid there.
        if op == "cholesky":
            return "masked"
        return ("shrinking" if pivot == "block" and nb <= _MAX_UNROLL_STEPS
                else "masked")
    return schedule


def _pad_with_identity(a: jax.Array, n_pad: int) -> jax.Array:
    """Embed the n×n matrix in an n_pad×n_pad one with an identity tail block,
    so factorizations of the padded matrix restrict to the original."""
    n = a.shape[0]
    if n_pad == n:
        return a
    out = jnp.zeros((n_pad, n_pad), a.dtype)
    out = out.at[:n, :n].set(a)
    pad_diag = jnp.arange(n, n_pad)
    return out.at[pad_diag, pad_diag].set(jnp.ones((), a.dtype))


def _trailing_update(a: jax.Array, o, block: int, u12: jax.Array):
    """Shared epilogue of both LU variants: write U12 right of the panel and
    subtract the masked rank-b outer product — zero outside the trailing
    region, so the full-size GEMM only touches A22. Expects the column panel
    of ``a`` to already hold L21 below the diagonal block."""
    n = a.shape[0]
    col_idx = jnp.arange(n)
    row_idx = jnp.arange(n)[:, None]
    right = col_idx[None, :] >= o + block
    rpan = jax.lax.dynamic_slice(a, (o, 0), (block, n))
    a = jax.lax.dynamic_update_slice(a, jnp.where(right, u12, rpan), (o, 0))
    cpan = jax.lax.dynamic_slice(a, (0, o), (n, block))
    below = row_idx >= o + block
    l21_m = jnp.where(below, cpan, jnp.zeros((), a.dtype))
    u12_m = jnp.where(right, u12, jnp.zeros((), a.dtype))
    return a - jnp.dot(l21_m, u12_m, precision="highest")


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_lu(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked LU with block-local partial pivoting.
    Returns (LU-combined, global permutation vector)."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    perm0 = jnp.arange(n, dtype=jnp.int32)
    col_idx = jnp.arange(n)
    row_idx = jnp.arange(n)[:, None]

    eye_b = jnp.eye(block)

    def body(i, carry):
        a, gperm = carry
        o = i * block
        piv = jax.lax.dynamic_slice(a, (o, o), (block, block))
        lu, _, p = jax.lax.linalg.lu(piv)
        l11 = jnp.tril(lu, -1) + jnp.eye(block, dtype=a.dtype)
        u11 = jnp.triu(lu)
        # invert the small triangles once (b×b solves), so the full-width
        # panel updates become GEMMs on the MXU instead of n-wide triangular
        # solves — the same trick the reference's panel updates use
        # (broadcast pivot inverse, DenseVecMatrix.scala:370-387)
        l11_inv = solve(l11, eye_b.astype(a.dtype), lower=True, unit_diagonal=True)
        u11_inv = solve(u11.T, eye_b.astype(a.dtype), lower=True).T

        # Row panel (rows o:o+b): permute rows, keep the permuted L-part left
        # of the panel, write the combined lu block into the diagonal; the
        # right part (U12) is handled by the shared epilogue.
        rpan = jax.lax.dynamic_slice(a, (o, 0), (block, n))
        rpan = rpan[p, :]
        in_block = (col_idx[None, :] >= o) & (col_idx[None, :] < o + block)
        lu_wide = jax.lax.dynamic_update_slice(jnp.zeros_like(rpan), lu, (0, o))
        a = jax.lax.dynamic_update_slice(
            a, jnp.where(in_block, lu_wide, rpan), (o, 0)
        )

        # Column panel (full height, cols o:o+b): rows >= o+b get
        # L21 = A21 U11^{-1}; rows above keep what's already written.
        cpan = jax.lax.dynamic_slice(a, (0, o), (n, block))
        l21 = jnp.dot(cpan, u11_inv, precision="highest")
        below = row_idx >= o + block
        a = jax.lax.dynamic_update_slice(a, jnp.where(below, l21, cpan), (0, o))

        u12 = jnp.dot(l11_inv, rpan, precision="highest")
        a = _trailing_update(a, o, block, u12)

        # Accumulate the global permutation.
        gseg = jax.lax.dynamic_slice(gperm, (o,), (block,))
        gperm = jax.lax.dynamic_update_slice(gperm, gseg[p], (o,))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
        return a, gperm

    return jax.lax.fori_loop(0, nb, body, (a, perm0))


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_lu_panel_pivot(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked LU with *full-height panel pivoting* (LAPACK
    getrf-style): each elimination column selects its pivot over the entire
    trailing column, not just the b×b pivot block — the stability the
    reference gives up by factorizing only the collected pivot block.

    The sequential elimination runs on the (n × b) panel buffer only
    (O(n·b) work per column); the chosen swaps are then replayed across the
    full width in one O(n·b) pass (LAPACK's laswp), and the trailing update
    is the shared masked rank-b GEMM. Returns (LU-combined, permutation)."""
    n = a.shape[0]
    nb = n // block
    perm0 = jnp.arange(n, dtype=jnp.int32)
    row_idx = jnp.arange(n)
    eye_b = jnp.eye(block)
    solve = jax.scipy.linalg.solve_triangular
    panel_col_idx = jnp.arange(block)

    def swap_rows(x, r1, r2):
        row1 = x[r1]
        row2 = x[r2]
        x = x.at[r1].set(row2)
        return x.at[r2].set(row1)

    def body(i, carry):
        a, gperm = carry
        o = i * block
        cpan0 = jax.lax.dynamic_slice(a, (0, o), (n, block))

        # --- panel factorization with full-height pivoting, column by column,
        # entirely within the (n, b) panel buffer
        def col_step(j, carry_p):
            pan, pivots = carry_p
            c = o + j
            col = jax.lax.dynamic_slice(pan, (0, j), (n, 1))[:, 0]
            mag = jnp.where(row_idx >= c, jnp.abs(col), -1.0)
            piv = jnp.argmax(mag)
            pan = swap_rows(pan, c, piv)
            pivots = pivots.at[j].set(piv)
            col = jax.lax.dynamic_slice(pan, (0, j), (n, 1))[:, 0]
            pivot_val = col[c]
            safe = jnp.where(jnp.abs(pivot_val) > 0, pivot_val, 1.0)
            factor = jnp.where(row_idx > c, col / safe, 0.0)
            pivot_row = jax.lax.dynamic_slice(pan, (c, 0), (1, block))[0]
            update = factor[:, None] * jnp.where(panel_col_idx > j, pivot_row,
                                                 0.0)[None, :]
            pan = pan - update
            newcol = jnp.where(row_idx > c, factor, col)
            pan = jax.lax.dynamic_update_slice(pan, newcol[:, None], (0, j))
            return pan, pivots

        pan, pivots = jax.lax.fori_loop(
            0, block, col_step, (cpan0, jnp.zeros((block,), jnp.int32))
        )

        # --- replay the swaps across the full matrix + permutation (laswp);
        # columns outside the panel are untouched by the elimination, so
        # applying the same swap sequence afterwards is equivalent
        def apply_swap(j, carry_s):
            a, gperm = carry_s
            c = o + j
            piv = pivots[j]
            return swap_rows(a, c, piv), swap_rows(gperm, c, piv)

        a, gperm = jax.lax.fori_loop(0, block, apply_swap, (a, gperm))
        a = jax.lax.dynamic_update_slice(a, pan, (0, o))

        # --- shared epilogue: U12 from the panel's unit-lower triangle
        lu_blk = jax.lax.dynamic_slice(a, (o, o), (block, block))
        l11 = jnp.tril(lu_blk, -1) + jnp.eye(block, dtype=a.dtype)
        l11_inv = solve(l11, eye_b.astype(a.dtype), lower=True, unit_diagonal=True)
        rpan = jax.lax.dynamic_slice(a, (o, 0), (block, n))
        u12 = jnp.dot(l11_inv, rpan, precision="highest")
        a = _trailing_update(a, o, block, u12)
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
        return a, gperm

    a, gperm = jax.lax.fori_loop(0, nb, body, (a, perm0))
    return a, gperm


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_lu_shrinking(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked LU, block-local pivoting, *shrinking-extent*
    schedule: the step offsets are static, so the Python loop unrolls at trace
    time and every panel/trailing slice has its true (shrinking) static shape —
    no masks, no wasted work. The masked ``_blocked_lu`` executes ~3× the
    ideal 2n³/3 FLOPs (full-width rank-b GEMMs with zero-masked operands);
    this schedule executes the ideal count at the cost of one compiled GEMM
    shape per block step (fine for the tens of steps real sizes produce)."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    gperm = jnp.arange(n, dtype=jnp.int32)
    eye_b = jnp.eye(block, dtype=a.dtype)

    for i in range(nb):
        o = i * block
        piv = jax.lax.slice(a, (o, o), (o + block, o + block))
        lu, _, p = jax.lax.linalg.lu(piv)
        l11 = jnp.tril(lu, -1) + eye_b
        u11 = jnp.triu(lu)
        l11_inv = solve(l11, eye_b, lower=True, unit_diagonal=True)
        u11_inv = solve(u11.T, eye_b, lower=True).T

        # permute the whole row stripe (columns left of the panel carry
        # already-final L entries and must swap with it, like laswp)
        stripe = jax.lax.slice(a, (o, 0), (o + block, n))[p]
        gseg = jax.lax.dynamic_slice(gperm, (o,), (block,))
        gperm = jax.lax.dynamic_update_slice(gperm, gseg[p], (o,))

        a = jax.lax.dynamic_update_slice(a, stripe, (o, 0))
        a = jax.lax.dynamic_update_slice(a, lu, (o, o))
        if o + block < n:
            right = stripe[:, o + block:]
            u12 = jnp.dot(l11_inv, right, precision="highest")
            below = jax.lax.slice(a, (o + block, o), (n, o + block))
            l21 = jnp.dot(below, u11_inv, precision="highest")
            trail = jax.lax.slice(a, (o + block, o + block), (n, n))
            trail = trail - jnp.dot(l21, u12, precision="highest")
            a = jax.lax.dynamic_update_slice(a, u12, (o, o + block))
            a = jax.lax.dynamic_update_slice(a, l21, (o + block, o))
            a = jax.lax.dynamic_update_slice(a, trail, (o + block, o + block))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
    return a, gperm


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_cholesky_shrinking(a: jax.Array, block: int, sharding=None):
    """Shrinking-extent blocked Cholesky (lower) — same schedule trade as
    :func:`_blocked_lu_shrinking`."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    eye_b = jnp.eye(block, dtype=a.dtype)

    for i in range(nb):
        o = i * block
        piv = jax.lax.slice(a, (o, o), (o + block, o + block))
        l11 = jnp.linalg.cholesky(piv)
        a = jax.lax.dynamic_update_slice(a, l11, (o, o))
        if o + block < n:
            l11_inv = solve(l11, eye_b, lower=True)
            below = jax.lax.slice(a, (o + block, o), (n, o + block))
            l21 = jnp.dot(below, l11_inv.T, precision="highest")
            trail = jax.lax.slice(a, (o + block, o + block), (n, n))
            trail = trail - jnp.dot(l21, l21.T, precision="highest")
            a = jax.lax.dynamic_update_slice(a, l21, (o + block, o))
            a = jax.lax.dynamic_update_slice(a, trail, (o + block, o + block))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
    return jnp.tril(a)


@functools.partial(jax.jit, static_argnames=("block", "sharding"))
def _blocked_cholesky(a: jax.Array, block: int, sharding=None):
    """Right-looking blocked Cholesky (lower). No pivoting (SPD input)."""
    n = a.shape[0]
    nb = n // block
    solve = jax.scipy.linalg.solve_triangular
    row_idx = jnp.arange(n)[:, None]

    eye_b = jnp.eye(block)

    def body(i, a):
        o = i * block
        piv = jax.lax.dynamic_slice(a, (o, o), (block, block))
        l11 = jnp.linalg.cholesky(piv)
        l11_inv = solve(l11, eye_b.astype(a.dtype), lower=True)

        cpan = jax.lax.dynamic_slice(a, (0, o), (n, block))
        l21 = jnp.dot(cpan, l11_inv.T, precision="highest")
        below = row_idx >= o + block
        at_block = (row_idx >= o) & (row_idx < o + block)
        l11_tall = jax.lax.dynamic_update_slice(jnp.zeros_like(cpan), l11, (o, 0))
        cpan_new = jnp.where(below, l21, jnp.where(at_block, l11_tall, cpan))
        a = jax.lax.dynamic_update_slice(a, cpan_new, (0, o))

        l21_m = jnp.where(below, l21, jnp.zeros((), a.dtype))
        a = a - jnp.dot(l21_m, l21_m.T, precision="highest")
        # restore the block column (the rank-b update also touched it)
        a = jax.lax.dynamic_update_slice(a, cpan_new, (0, o))
        if sharding is not None:
            a = jax.lax.with_sharding_constraint(a, sharding)
        return a

    a = jax.lax.fori_loop(0, nb, body, a)
    return jnp.tril(a)


def _require_square(mat):
    if mat.num_rows() != mat.num_cols():
        raise ValueError(f"factorization needs a square matrix, got {mat.shape}")


def _mode_to_local(mode: str, n: int) -> bool:
    cfg = get_config()
    if mode in ("local", "breeze"):  # "breeze" kept as a parity alias
        return True
    if mode in ("dist", "distspark"):
        return False
    if mode == "auto":  # reference: n > 6000 -> dist (DenseVecMatrix.scala:289-298)
        return n <= cfg.local_fallback_dim
    raise ValueError(f"unknown factorization mode: {mode}")


def lu_decompose(mat, mode: str = "auto", block_size: int | None = None,
                 pivot: str = "block", schedule: str = "auto"):
    """Block LU with partial pivoting (DenseVecMatrix.luDecompose,
    DenseVecMatrix.scala:283-466). Returns ``(L, U, perm)`` where ``perm`` is
    the row-permutation vector: ``A[perm] == L @ U``. ``perm`` stays a device
    array — forcing it to host here would insert a blocking sync into every
    call (dispatch is async; fetch when you need the values).

    ``pivot``: "block" restricts pivot search to the b×b pivot block (the
    reference's choice — fast, weaker on adversarial inputs); "panel" searches
    the full trailing column per elimination step (LAPACK getrf behavior —
    handles e.g. a singular pivot block with good pivots below it).

    ``schedule``: "shrinking" unrolls the block steps with true shrinking
    trailing extents (ideal 2n³/3 FLOPs, one compiled GEMM shape per step);
    "masked" is the single fori_loop program with full-width masked updates
    (~3× the FLOPs, one compiled shape total). "auto" picks shrinking for
    block-pivot factorizations up to 64 steps."""
    _require_square(mat)
    _require_pivot(pivot)
    _resolve_schedule(schedule, 1, pivot)  # arg validation in EVERY mode
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        lu, _, p = jax.lax.linalg.lu(a)
        l = jnp.tril(lu, -1) + jnp.eye(n, dtype=a.dtype)
        u = jnp.triu(lu)
        return mat._wrap(l), mat._wrap(u), p

    b = block_size or get_config().lu_base_size
    b = min(b, n)
    n_pad, sharding = _pad_and_sharding(mat, n, b)
    a_pad = _pad_with_identity(a, n_pad)
    sched = _resolve_schedule(schedule, n_pad // b, pivot)
    if pivot == "panel":
        factor = _blocked_lu_panel_pivot
    else:
        factor = _blocked_lu_shrinking if sched == "shrinking" else _blocked_lu
    lu_pad, perm = factor(a_pad, b, sharding)
    lu_log = lu_pad[:n, :n]
    l = jnp.tril(lu_log, -1) + jnp.eye(n, dtype=a.dtype)
    u = jnp.triu(lu_log)
    return mat._wrap(l), mat._wrap(u), perm[:n]


def _grid(mat) -> int:
    """LCM-ish divisor check helper: the row-axis shard count of the matrix."""
    ax = mat.spec[0] if len(mat.spec) > 0 else None
    return mat.mesh.shape[ax] if ax is not None else 1


def _pad_and_sharding(mat, n: int, block: int):
    """Padded size + sharding constraint for a blocked factorization.

    Pads to lcm(block, row-shard-count) so the distributed-mode sharding
    constraint ALWAYS applies — previously a non-dividing padded size silently
    dropped the constraint and let GSPMD place the loop however it pleased."""
    n_pad = pad_to_multiple(n, math.lcm(block, _grid(mat)))
    return n_pad, NamedSharding(mat.mesh, mat.spec)


def cholesky_decompose(mat, mode: str = "auto", block_size: int | None = None,
                       schedule: str = "auto"):
    """Block Cholesky, lower factor (DenseVecMatrix.choleskyDecompose,
    DenseVecMatrix.scala:475-561). Returns L with ``A == L @ Lᵀ``.
    ``schedule`` as in :func:`lu_decompose`, except ``"auto"`` resolves to
    ``"masked"`` here: measured on chip (r5, 8192²) the single fori_loop
    program beats the unrolled shrinking schedule for Cholesky (1480 vs
    1319 GFLOP/s) even though the reverse holds for LU."""
    _require_square(mat)
    _resolve_schedule(schedule, 1, op="cholesky")  # arg validation in EVERY mode
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        return mat._wrap(jnp.linalg.cholesky(a))
    b = block_size or get_config().cholesky_base_size
    b = min(b, n)
    n_pad, sharding = _pad_and_sharding(mat, n, b)
    a_pad = _pad_with_identity(a, n_pad)
    sched = _resolve_schedule(schedule, n_pad // b, op="cholesky")
    chol = (_blocked_cholesky_shrinking if sched == "shrinking"
            else _blocked_cholesky)
    l_pad = chol(a_pad, b, sharding)
    return mat._wrap(l_pad[:n, :n])


@functools.partial(jax.jit,
                   static_argnames=("block", "pivot", "sharding", "schedule"))
def _inverse_via_lu(a: jax.Array, block: int, pivot: str = "block",
                    sharding=None, schedule: str = "masked"):
    if pivot == "panel":
        factor = _blocked_lu_panel_pivot
    else:
        factor = (_blocked_lu_shrinking if schedule == "shrinking"
                  else _blocked_lu)
    lu_pad, perm = factor(a, block, sharding)
    n = a.shape[0]
    solve = jax.scipy.linalg.solve_triangular
    l = jnp.tril(lu_pad, -1) + jnp.eye(n, dtype=a.dtype)
    u = jnp.triu(lu_pad)
    # A[perm] = L U  =>  A^{-1} = (U^{-1} L^{-1}) P  where P x = x[perm]
    pa_inv = solve(u, solve(l, jnp.eye(n, dtype=a.dtype), lower=True, unit_diagonal=True))
    return pa_inv[:, jnp.argsort(perm)][:, :n]  # apply P on the right


def inverse(mat, mode: str = "auto", block_size: int | None = None,
            pivot: str = "block", schedule: str = "auto"):
    """Matrix inverse (DenseVecMatrix.inverse, DenseVecMatrix.scala:568-764).
    The reference runs a blocked Gauss-Jordan-style forward + backward sweep
    with driver-factorized pivots; here it is blocked LU + two sharded
    triangular solves in one XLA program.

    ``pivot`` mirrors :func:`lu_decompose`: "panel" routes through the
    full-height panel-pivoted LU for ill-conditioned pivot blocks.
    ``schedule`` as in :func:`lu_decompose` (applies to the LU stage)."""
    _require_square(mat)
    _require_pivot(pivot)
    _resolve_schedule(schedule, 1, pivot)  # arg validation in EVERY mode
    n = mat.num_rows()
    a = mat.logical()
    if _mode_to_local(mode, n):
        return mat._wrap(jnp.linalg.inv(a))
    b = block_size or get_config().inverse_base_size
    b = min(b, n)
    n_pad, sharding = _pad_and_sharding(mat, n, b)
    a_pad = _pad_with_identity(a, n_pad)
    sched = _resolve_schedule(schedule, n_pad // b, pivot)
    inv_pad = _inverse_via_lu(a_pad, b, pivot, sharding, sched)
    return mat._wrap(inv_pad[:n, :n])
