"""Linear-system solves on distributed matrices.

The reference stops at the factorizations (its ALS solves tiny rank×rank
systems locally and its `inverse` exists mainly to substitute for solve —
ALSHelp.scala:388-392 even inverts explicitly). A factorization API without a
solve API forces users into explicit inverses, so the rebuild closes the gap:

- :func:`lu_solve` — reuse an ``(L, U, perm)`` from :func:`lu_decompose`
  against one or many right-hand sides (two sharded triangular solves).
- :func:`cholesky_solve` — the SPD counterpart, reusing ``L`` from
  :func:`cholesky_decompose`.
- :func:`solve` — factor-and-solve convenience with the same mode knobs.

Triangular solves lower to XLA's blocked TriangularSolve, which schedules fine
on TPU; no explicit inverse is ever formed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .factorizations import PIVOT_STRATEGIES, _mode_to_local, lu_decompose

__all__ = ["lu_solve", "cholesky_solve", "solve"]


def _as_array(x):
    """Distributed matrix/vector or plain array → jax array."""
    return x.logical() if hasattr(x, "logical") else jnp.asarray(x)


def _rhs_array(b):
    arr = _as_array(b)
    return (arr[:, None], True) if arr.ndim == 1 else (arr, False)


def _factor_and_rhs(factor, b):
    """Shared coercion/validation for the factor-reuse solvers: returns
    (factor array, 2-D rhs, was_vector)."""
    f_arr = _as_array(factor)
    rhs, was_vector = _rhs_array(b)
    if rhs.shape[0] != f_arr.shape[0]:
        raise ValueError(
            f"rhs has {rhs.shape[0]} rows, factorization is {f_arr.shape[0]}"
        )
    return f_arr, rhs, was_vector


@jax.jit
def _lu_solve_jit(l, u, perm, b):
    solve_tri = jax.scipy.linalg.solve_triangular
    pb = b[perm]
    y = solve_tri(l, pb, lower=True, unit_diagonal=True)
    return solve_tri(u, y, lower=False)


def lu_solve(l, u, perm, b):
    """Solve ``A x = b`` given ``A[perm] = L U`` from :func:`lu_decompose`.
    ``b``: vector, matrix, or distributed matrix/vector; returns an array of
    the same logical shape."""
    l_arr, rhs, was_vector = _factor_and_rhs(l, b)
    u_arr = _as_array(u)
    # jnp.asarray handles device arrays, numpy, and lists alike — no host
    # round trip (perm now stays on device through the whole solve chain)
    x = _lu_solve_jit(l_arr, u_arr, jnp.asarray(perm), rhs)
    return x[:, 0] if was_vector else x


@jax.jit
def _chol_solve_jit(l, b):
    solve_tri = jax.scipy.linalg.solve_triangular
    y = solve_tri(l, b, lower=True)
    return solve_tri(l.T, y, lower=False)


def cholesky_solve(l, b):
    """Solve ``A x = b`` given ``A = L Lᵀ`` from :func:`cholesky_decompose`
    (two triangular solves; the SPD counterpart of :func:`lu_solve`)."""
    l_arr, rhs, was_vector = _factor_and_rhs(l, b)
    x = _chol_solve_jit(l_arr, rhs)
    return x[:, 0] if was_vector else x


def solve(mat, b, mode: str = "auto", pivot: str = "block",
          block_size: int | None = None):
    """Solve ``mat @ x = b``. Small systems go through the fused local path
    (``jnp.linalg.solve``); large ones factor with the blocked distributed LU
    (``pivot``/``block_size`` forwarded) and back-substitute — never via an
    explicit inverse (the fix SURVEY.md §7 flags against ALSHelp.scala:388-392)."""
    if pivot not in PIVOT_STRATEGIES:
        raise ValueError(
            f"unknown pivot strategy: {pivot!r} (one of {PIVOT_STRATEGIES})"
        )
    n = mat.num_rows()
    if mat.num_cols() != n:
        raise ValueError(f"solve needs a square matrix, got {mat.shape}")
    rhs, was_vector = _rhs_array(b)
    if rhs.shape[0] != n:
        raise ValueError(f"rhs has {rhs.shape[0]} rows, matrix is {n}x{n}")
    if _mode_to_local(mode, n):
        x = jnp.linalg.solve(mat.logical(), rhs)
        return x[:, 0] if was_vector else x
    l, u, perm = lu_decompose(mat, mode=mode, pivot=pivot, block_size=block_size)
    return lu_solve(l, u, perm, b)
