from .factorizations import lu_decompose, cholesky_decompose, inverse  # noqa: F401
from .svd import compute_svd, lanczos, SVDResult  # noqa: F401
