from .factorizations import lu_decompose, cholesky_decompose, inverse  # noqa: F401
from .solve import cholesky_solve, lu_solve, solve  # noqa: F401
from .svd import compute_svd, lanczos, symmetric_eigs, SVDResult  # noqa: F401
