"""Truncated SVD via Gramian eigendecomposition or matrix-free Lanczos.

The reference's ``computeSVD`` (DenseVecMatrix.scala:1531-1652) auto-selects
between local LAPACK SVD, local ARPACK eigs of the Gramian, and "dist-eigs":
ARPACK's reverse-communication Lanczos loop running *on the driver* with each
``v ↦ AᵀA·v`` evaluated as a distributed aggregate — one full cluster
round-trip per Lanczos iteration (DenseVecMatrix.scala:1743-1834, SURVEY.md §3).

TPU-first, ARPACK disappears: :func:`symmetric_eigs` runs the Lanczos
recurrence itself as a ``lax.scan`` over a jitted matvec, so the *entire*
iteration — k steps, full reorthogonalization, collectives — is one XLA
program with zero host round-trips. :func:`lanczos` is the AᵀA specialization
used by SVD.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import get_config

__all__ = ["compute_svd", "lanczos", "symmetric_eigs", "SVDResult"]


@dataclasses.dataclass
class SVDResult:
    """Mirror of the reference's SVD case class (U, s, V)."""

    u: object | None  # DenseVecMatrix | None (None when compute_u=False)
    s: np.ndarray  # singular values, descending
    v: np.ndarray  # right singular vectors, (n, k)


def _lanczos_scan(matvec, v0, iters: int):
    """The Lanczos recurrence with twice-iterated classical Gram-Schmidt
    reorthogonalization; traced inline by the jitted wrappers below."""
    n = v0.shape[0]
    q0 = v0 / jnp.linalg.norm(v0)
    qs = jnp.zeros((iters + 1, n), v0.dtype).at[0].set(q0)

    def body(carry, i):
        qs, beta_prev = carry
        q = qs[i]
        w = matvec(q)
        alpha = jnp.dot(w, q)
        w = w - alpha * q - beta_prev * qs[i - 1] * (i > 0)
        for _ in range(2):
            w = w - qs.T @ (qs @ w)
        beta = jnp.linalg.norm(w)
        q_next = jnp.where(beta > 1e-12, w / jnp.maximum(beta, 1e-30),
                           jnp.zeros_like(w))
        qs = qs.at[i + 1].set(q_next)
        return (qs, beta), (alpha, beta)

    (qs, _), (alphas, betas) = jax.lax.scan(
        body, (qs, jnp.zeros((), v0.dtype)), jnp.arange(iters)
    )
    return alphas, betas, qs


@functools.partial(jax.jit, static_argnames=("iters",))
def _gram_lanczos_run(a, v0, iters: int):
    """Module-level jit: the AᵀA Lanczos compiles once per (shape, iters)."""

    def matvec(v):
        return jnp.dot(a.T, jnp.dot(a, v, precision="highest"), precision="highest")

    return _lanczos_scan(matvec, v0, iters)


@functools.lru_cache(maxsize=32)
def _runner_for(matvec):
    """Per-callable jitted runner: repeated calls with the *same function
    object* reuse the compiled scan (a fresh lambda necessarily recompiles)."""

    @functools.partial(jax.jit, static_argnames=("iters",))
    def run(v0, iters):
        return _lanczos_scan(matvec, v0, iters)

    return run


def _ritz_topk(alphas, betas, qs, k: int, num_iters: int):
    t = jnp.diag(alphas) + jnp.diag(betas[:-1], 1) + jnp.diag(betas[:-1], -1)
    evals, evecs = jnp.linalg.eigh(t)
    idx = jnp.argsort(-evals)[:k]
    vecs = qs[:num_iters].T @ evecs[:, idx]
    vecs = vecs / jnp.maximum(jnp.linalg.norm(vecs, axis=0, keepdims=True), 1e-30)
    return evals[idx], vecs


def _resolve_iters(n: int, k: int, num_iters: int | None) -> int:
    cfg = get_config()
    if num_iters is None:
        num_iters = min(n, max(2 * k + 1, min(n, k * cfg.lanczos_max_iter_factor)))
    return min(num_iters, n)


def symmetric_eigs(matvec, n: int, k: int, num_iters: int | None = None,
                   seed: int = 0, dtype=jnp.float32):
    """Top-k eigenpairs of a symmetric operator given only ``v ↦ A·v`` — the
    exact contract of the reference's ARPACK wrapper
    (EigenValueDecomposition.symmetricEigs, DenseVecMatrix.scala:1743-1834),
    with the reverse-communication loop replaced by a jitted Lanczos scan.
    ``matvec`` must be jax-traceable; pass the same function object across
    calls to reuse the compiled program. Returns (eigenvalues desc,
    vectors (n, k))."""
    num_iters = _resolve_iters(n, k, num_iters)
    v0 = jax.random.normal(jax.random.key(seed), (n,), dtype)
    alphas, betas, qs = _runner_for(matvec)(v0, num_iters)
    return _ritz_topk(alphas, betas, qs, k, num_iters)


def lanczos(a: jax.Array, k: int, num_iters: int | None = None, seed: int = 0):
    """Top-k eigenpairs of AᵀA — the AᵀA specialization used by the SVD path
    (the role of ARPACK ``dsaupd``/``dseupd`` in the reference). Compiles once
    per (shape, iters) via a module-level jit."""
    n = a.shape[1]
    num_iters = _resolve_iters(n, k, num_iters)
    v0 = jax.random.normal(jax.random.key(seed), (n,), a.dtype)
    alphas, betas, qs = _gram_lanczos_run(a, v0, num_iters)
    return _ritz_topk(alphas, betas, qs, k, num_iters)


def compute_svd(mat, k: int, mode: str = "auto", compute_u: bool = True,
                rcond: float = 1e-9, seed: int = 0) -> SVDResult:
    """Truncated SVD (DenseVecMatrix.computeSVD, DenseVecMatrix.scala:1531-1652).

    Modes, matching the reference's auto-selection (:1569-1588):
      - "local-svd": full jnp SVD of the gathered matrix (small n and m)
      - "local-eigs": eigh of the n×n Gramian (small n)
      - "dist-eigs": matrix-free Lanczos on the sharded array (large n)
    """
    m, n = mat.shape
    if k < 1 or k > n:
        raise ValueError(f"requested k={k} singular values for n={n}")
    cfg = get_config()
    if mode == "auto":
        if n < 100 or (k > n / 2 and n <= cfg.svd_local_dim):
            mode = "local-svd" if m <= cfg.svd_local_dim else "local-eigs"
        elif n <= cfg.svd_local_dim:
            mode = "local-eigs"
        else:
            mode = "dist-eigs"

    a = mat.logical()
    if mode == "local-svd":
        u_full, s_full, vt = jnp.linalg.svd(a, full_matrices=False)
        s, v = s_full[:k], vt[:k].T
        u = mat._wrap(u_full[:, :k]) if compute_u else None
        return SVDResult(u, np.asarray(s), np.asarray(v))
    if mode == "local-eigs":
        g = jnp.dot(a.T, a, precision="highest")
        evals, evecs = jnp.linalg.eigh(g)
        idx = jnp.argsort(-evals)[:k]
        evals_k, v = evals[idx], evecs[:, idx]
    elif mode == "dist-eigs":
        evals_k, v = lanczos(a, k, seed=seed)
    else:
        raise ValueError(f"unknown SVD mode: {mode}")

    s = jnp.sqrt(jnp.maximum(evals_k, 0.0))
    # drop numerically-zero singular values like the reference's sigma
    # threshold (DenseVecMatrix.scala:1598-1617)
    keep = int(jnp.sum(s > (s[0] * rcond)))
    s, v = s[:keep], v[:, :keep]
    u = None
    if compute_u:
        # U = A V Σ^{-1} (DenseVecMatrix.scala:1632-1650)
        u_arr = jnp.dot(a, v, precision="highest") / jnp.maximum(s, 1e-30)[None, :]
        u = mat._wrap(u_arr)
    return SVDResult(u, np.asarray(s), np.asarray(v))
