"""Device-mesh runtime for marlin_tpu.

The reference delegates all distribution to Spark: a driver builds an RDD DAG
and Spark schedules shuffle/broadcast over executors (SURVEY.md §0, §2.8). The
TPU-native equivalent is a static SPMD design: arrays carry a
``jax.sharding.NamedSharding`` over a 2-D device ``Mesh`` and XLA inserts ICI/DCN
collectives. This module owns mesh construction, the process-level distributed
bring-up (the analog of ``new SparkContext``, examples/MatrixMultiply.scala:37),
and small sharding helpers used across the library.

Mesh axes are named ``"rows"`` and ``"cols"``: a row-partitioned matrix (the
reference's ``DenseVecMatrix``, matrix/DenseVecMatrix.scala:41-44) is sharded
``P("rows", None)``; a 2-D block-partitioned matrix (``BlockMatrix``,
matrix/BlockMatrix.scala:28) is sharded ``P("rows", "cols")``.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
COLS = "cols"

_default_mesh: Mesh | None = None


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> None:
    """Multi-host bring-up. Replaces Spark's driver/executor process management
    (the reference's L0, SURVEY.md §1): on a multi-host TPU slice each host calls
    this once before building meshes; single-host callers may skip it entirely.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def best_grid(n_devices: int) -> tuple[int, int]:
    """Factor ``n_devices`` into the most square (rows, cols) grid, preferring
    rows >= cols. This is the default 2-D layout; the CARMA heuristic
    (parallel/carma.py) overrides it per-multiply when shapes are skewed."""
    best = (n_devices, 1)
    for r in range(1, int(math.isqrt(n_devices)) + 1):
        if n_devices % r == 0:
            best = (n_devices // r, r)
    return best


def create_mesh(
    shape: Sequence[int] | None = None,
    axis_names: Sequence[str] = (ROWS, COLS),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Create a mesh over the given (or all) devices.

    ``shape=None`` picks a near-square 2-D grid over all devices. Pass
    ``shape=(n, 1)`` for a purely row-sharded ("DenseVecMatrix-like") layout.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = best_grid(len(devs))
    size = int(np.prod(shape))
    if size > len(devs):
        raise ValueError(f"mesh shape {tuple(shape)} needs {size} devices, have {len(devs)}")
    arr = np.array(devs[:size]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def default_mesh() -> Mesh:
    """The process-global mesh (lazily built over all devices). The analog of the
    single shared SparkContext every reference example threads through its API."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = create_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh | None) -> None:
    global _default_mesh
    _default_mesh = mesh


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS, None))


def block_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS, COLS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape(mesh: Mesh) -> tuple[int, int]:
    return mesh.shape[ROWS], mesh.shape[COLS]


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
