"""marlin_tpu — a TPU-native distributed dense/sparse linear-algebra framework.

A ground-up rebuild of the capabilities of PasaLab/marlin (a Spark-based
distributed matrix library; see SURVEY.md) designed for TPU: matrices are
global ``jax.Array``s sharded over a ``jax.sharding.Mesh``, distributed
multiplies are SPMD programs whose collectives XLA schedules over ICI/DCN, and
per-block math runs on the MXU instead of netlib BLAS.

Quick start::

    import marlin_tpu as mt

    mesh = mt.create_mesh()                      # all local devices
    a = mt.DenseVecMatrix.random(0, 8000, 8000, mesh=mesh)
    b = mt.DenseVecMatrix.random(1, 8000, 8000, mesh=mesh)
    c = a.multiply(b)                            # adaptive: broadcast vs RMM
    (l, u, p) = a.lu_decompose(mode="dist")
"""

from .config import MarlinConfig, config_context, get_config, set_config  # noqa: F401
from .mesh import (  # noqa: F401
    COLS,
    ROWS,
    create_mesh,
    default_mesh,
    initialize_distributed,
    set_default_mesh,
)
from .matrix import (  # noqa: F401
    BlockMatrix,
    CoordinateMatrix,
    DenseMatrix,
    DenseVecMatrix,
    DistributedIntVector,
    DistributedMatrix,
    DistributedVector,
    OutOfCoreMatrix,
    SparseVecMatrix,
)
from .parallel import (  # noqa: F401
    ChunkPrefetcher,
    matmul,
    prefetch_chunks,
    ring_attention,
    ring_matmul,
    rmm_matmul,
    split_method,
    streamed_gramian,
    streamed_matmul,
    tune_multiply,
    ulysses_attention,
)
from .linalg import cholesky_decompose, compute_svd, inverse, lanczos, lu_decompose  # noqa: F401
from .io import (  # noqa: F401
    load_block_matrix_file,
    load_coordinate_matrix,
    load_matrix_file,
    load_svm_den_vec_matrix,
    save_matrix,
)
from .utils import evaluate, timer  # noqa: F401
from .lazy import fuse  # noqa: F401
from . import obs  # noqa: F401
from . import random  # noqa: F401

__version__ = "0.3.0"
