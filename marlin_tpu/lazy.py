"""Fused (lazy) evaluation of matrix expressions.

The reference defers work by construction: every matrix op builds RDD lineage
and nothing runs until a Spark action forces the DAG (SURVEY.md §3.1 — "pure
DAG construction on the driver"). The TPU-native equivalent is tracing: all
matrix types are registered as pytrees (matrix/dense.py), so a function over
matrices can be handed to ``jax.jit`` and every chained method call — multiply,
add, scale, transpose, sum — fuses into ONE compiled XLA program with one
dispatch. This kills per-op dispatch overhead on chained expressions (the
eager path pays one dispatch per op — ROADMAP.md perf note) and lets XLA fuse
elementwise work into the matmuls it neighbors.

:func:`fuse` is the documented alias with the matrix-level contract spelled
out; it also works as a decorator factory (``@fuse`` or ``@fuse(donate=...)``).

Because tracing is compilation, the usual jit rules apply inside a fused
function: shapes/meshes/specs are static (a new operand geometry recompiles),
and host-side terminal ops (``to_numpy``, ``float(...)``, ``save``) belong
outside. Autodiff composes: ``jax.grad`` of a fused scalar loss over matrices
returns matrix-typed cotangents — a capability with no reference analog.
"""

from __future__ import annotations

import functools

import jax

__all__ = ["fuse"]


def fuse(fn=None, **jit_kwargs):
    """``jax.jit`` for matrix-level functions: one compiled dispatch for the
    whole expression chain.

    >>> @fuse
    ... def step(a, b, c):
    ...     return a.multiply(b).add(c).multiply(2.0)
    >>> out = step(a, b, c)   # one dispatch, XLA-fused

    Accepts the same keyword arguments as ``jax.jit`` (``donate_argnums``,
    ``static_argnames``, ...).
    """
    if fn is None:
        return functools.partial(fuse, **jit_kwargs)
    # analyze: ignore[recompile] — fuse IS the jit-creation API (a thin
    # alias); its call sites own the caching discipline and the recompile
    # check sees each of them directly
    return jax.jit(fn, **jit_kwargs)
