"""The continuous-batching serving engine: one worker thread, compiled decode.

:class:`ServeEngine` is the front half of an inference stack over the
library's compiled decode programs: concurrent callers ``submit`` requests;
an admission gate (queue depth + in-flight KV-cache HBM budget, request.py)
rejects overload with a reason; a batch former (batcher.py) buckets prompts
onto a small static shape set so compiles stay bounded; and a single worker
thread keeps the device fed. Two schedulers share that skeleton:

**Row-level** (``serve_rowlevel``, the default) changes the unit of
scheduling from "batch" to "slot-step". Each bucket owns a persistent
device-resident KV slab of ``max_batch`` slots (:class:`~.batcher.SlotPool`)
and TWO compiled programs — slot-targeted prefill
(:func:`~marlin_tpu.models.transformer.lm_prefill_slot`) and a single-token
decode step over the whole slab
(:func:`~marlin_tpu.models.transformer.lm_decode_rows`, donated KV buffers,
per-row positions and sampling knobs). Every worker iteration:

    refill freed slots from the queue (prefill-on-admit; the prompt's
    first token lands here — real TTFT)  →  retire rows that emitted
    their ``eos``, hit their step budget, or expired  →  run ONE decode
    step for all live rows  →  repeat

A finished row's slot refills on the very next step instead of riding out
its batch as a dummy, and a newly admitted request waits one step, not one
whole batch — the tokens/s and TTFT win at high offered load. Per-row
greedy output stays bit-identical to :func:`~marlin_tpu.models.transformer
.lm_generate` on the same prompt (greedy decode is composition-independent)
and the compile count is ≤ 2 programs per bucket, for ANY per-row mix of
sampling knobs (they are traced vectors).

**Gang** (``serve_rowlevel=False``, the fallback) runs one fused
``lm_generate_batch`` program per bucket to completion: all ``max_batch``
slot rows launch and land together (free slots carry inert dummy rows).
Simpler — one program per bucket, no per-step host sync — but a finished
row holds its slot as a dummy until the whole batch lands, and admissions
wait out the entire in-flight batch.

Lifecycle (both schedulers): ``drain()`` stops admission and completes
everything already accepted; ``close()`` stops admission, finishes the work
in flight (the gang batch / the live slots), and retires everything still
queued with a clean ``shutting_down`` Result. Both are terminal and
idempotent; the worker thread (named ``marlin-serve-*`` — the conftest leak
fixture watches the prefix) is joined before either returns. Chaos hooks
(utils/faults.py): ``serve.enqueue`` fires in ``submit``; ``serve.step``
fires before each gang batch launch / each row-level prefill — a fault
fails those requests with ``error`` Results; ``serve.decode_step`` fires
before each row-level decode step — a fault there fails only that step's
live rows and leaves the slot pool consistent. The engine keeps serving
after any of them.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref

import numpy as np

from ..config import get_config
from ..obs import perf, trace as obs_trace
from ..obs.collectors import compile_count as _compile_count
from ..obs.exposition import (register_health_provider,
                              unregister_health_provider)
from ..utils import faults
from .batcher import (BatchFormer, bucket_kv_bytes, bucket_program_key,
                      capture_bucket_costs, normalize_buckets, pick_bucket,
                      warmup_buckets)
from .metrics import ServeMetrics
from .request import (STATUS_ERROR, STATUS_EXPIRED, STATUS_OK,
                      STATUS_REJECTED, STATUS_SHUTTING_DOWN, AdmissionQueue,
                      Request, Result, ResultHandle)

__all__ = ["ServeEngine"]

_engine_ids = itertools.count()

# real-seconds cap on one condition wait under an INJECTED clock: bounds how
# stale the worker's view of a fake clock can get (tests advance it between
# polls). Real-clock engines never poll — they wait on the condition until
# notified or the exact max_wait hint elapses.
_POLL_CAP_S = 0.02


class _Entry:
    """One admitted request riding through the former to a batch slot.
    ``queue_s`` is stamped when the row-level scheduler claims the entry
    for a slot (the gang path derives it at dispatch instead). ``trace``
    is the request's span context (obs/trace.py), captured at submit and
    re-activated by the worker thread around every record the request
    produces — that cross-thread handoff is what joins one request's
    enqueue/prefill/result records into one trace in the JSONL."""

    __slots__ = ("request", "handle", "bucket", "cost", "enq_t", "queue_s",
                 "trace")

    def __init__(self, request, handle, bucket, cost, enq_t, trace=None):
        self.request = request
        self.handle = handle
        self.bucket = bucket
        self.cost = cost
        self.enq_t = enq_t
        self.queue_s = None
        self.trace = trace


class ServeEngine:
    """Continuous-batching inference engine over a trained LM.

    ``params``/``heads``/``compute_dtype``/``moe`` describe the model exactly
    as :func:`lm_generate_batch` takes them. Knobs default from the global
    config: ``buckets`` (``serve_buckets``), ``max_batch``
    (``serve_max_batch``), ``max_wait_ms`` (``serve_max_wait_ms``),
    ``queue_depth`` (``serve_queue_depth``); ``hbm_budget_bytes`` defaults to
    the planner's :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (0
    disables the byte gate). ``clock`` is the engine's *policy* clock
    (deadlines, max_wait, latency metrics) — injectable for deterministic
    tests; wall throughput is always measured on the real clock. ``log``
    overrides the default EventLog for ``serve`` records.

    ``rowlevel`` picks the scheduler (``serve_rowlevel`` by default): True =
    slot-step scheduling over persistent per-bucket KV slabs (prefill +
    decode-step programs, per-row retirement/refill); False = the gang
    fallback (one fused program per bucket runs a batch to completion).

    Usable as a context manager (``close()`` on exit); ``start=False`` defers
    the worker thread so tests can stage a queue before any dispatch."""

    def __init__(self, params: dict, heads: int, *, buckets=None,
                 max_batch: int | None = None,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compute_dtype: str | None = None, moe: tuple | None = None,
                 rowlevel: bool | None = None,
                 clock=time.monotonic, log=None, start: bool = True):
        cfg = get_config()
        self.params = params
        self.heads = heads
        self.compute_dtype = compute_dtype
        self.moe = moe
        self.rowlevel = bool(cfg.serve_rowlevel if rowlevel is None
                             else rowlevel)
        self.buckets = normalize_buckets(
            cfg.serve_buckets if buckets is None else buckets)
        self.max_batch = int(cfg.serve_max_batch if max_batch is None
                             else max_batch)
        wait_ms = cfg.serve_max_wait_ms if max_wait_ms is None else max_wait_ms
        depth = int(cfg.serve_queue_depth if queue_depth is None
                    else queue_depth)
        if hbm_budget_bytes is None:
            from ..models.planner import usable_hbm_bytes

            hbm_budget_bytes = usable_hbm_bytes()
        self._clock = clock
        self._real_clock = clock is time.monotonic
        self.metrics = ServeMetrics(log=log)
        self._queue = AdmissionQueue(depth, hbm_budget_bytes)
        self._cond = threading.Condition()
        self._former = BatchFormer(self.buckets, self.max_batch,
                                   max_wait=float(wait_ms) / 1e3)
        self._state = "running"  # running | draining | closing | closed
        self._started = False
        eid = next(_engine_ids)
        self._name = f"marlin-serve-{eid}"
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=self._name)
        # --- performance introspection (obs/perf.py) -----------------------
        # the step-time black box: per-iteration records from the worker
        # loop, dumped on worker faults, on close, and via GET /debug/flight
        self.flight = perf.FlightRecorder(name=self._name)
        self._heartbeat: float | None = None  # real clock; worker stamps it
        self._live_rows = 0                   # worker-written, healthz-read
        self._prog_keys: dict[tuple, str] = {}
        self._finalized = False
        # readiness: /healthz reports this engine's lifecycle and 503s once
        # it leaves "accepting" (weakref — the provider must never pin a
        # dead engine; terminal close/drain unregister explicitly)
        ref = weakref.ref(self)
        name = self._name

        def _health():
            eng = ref()
            if eng is None:
                # abandoned without close(): drop out silently — a dead
                # entry must not 503 an otherwise healthy process for one
                # probe (health_payload skips None)
                unregister_health_provider(name)
                return None
            return eng._health_info()

        register_health_provider(name, _health)
        if start:
            self.start()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the worker thread (idempotent; no-op once shutting down)."""
        with self._cond:
            if self._started or self._state != "running":
                return
            self._started = True
        self._thread.start()

    def warmup(self) -> int:
        """Compile every bucket's program(s) before traffic — the fused
        batch program per bucket in gang mode, the prefill + decode-step
        pair per bucket in row-level mode (batcher.warmup_buckets)."""
        return warmup_buckets(self.params, self.heads, self.buckets,
                              self.max_batch, self.compute_dtype, self.moe,
                              rowlevel=self.rowlevel)

    def pending(self) -> int:
        """Requests admitted but not yet retired (queued + in flight)."""
        return self._queue.count

    # ------------------------------------------------------- introspection

    def _health_info(self) -> dict:
        """The /healthz readiness payload for this engine: lifecycle state
        (``accepting`` while running), live slot rows, queue depth, and the
        worker heartbeat age (None until the worker's first iteration).
        Lock-free reads of GIL-atomic fields — the probe must never contend
        with the worker."""
        state = {"running": "accepting", "draining": "draining",
                 "closing": "closed", "closed": "closed"}[self._state]
        hb = self._heartbeat
        return {
            "state": state,
            "live_slots": self._live_rows,
            "queue_depth": self._queue.count,
            "worker_started": self._started,
            "heartbeat_age_s": (round(time.monotonic() - hb, 3)
                                if hb is not None else None),
        }

    def _prog_key(self, bucket) -> str:
        """The roofline-accounting key for this engine's programs at one
        bucket (cached — it sits on the per-step path)."""
        key = self._prog_keys.get(bucket)
        if key is None:
            key = self._prog_keys[bucket] = bucket_program_key(
                self.params, bucket, self.max_batch, self.compute_dtype)
        return key

    def _flight_dump(self, reason: str) -> None:
        """Dump the flight ring (never raises — rides failure paths)."""
        try:
            self.flight.dump(reason=reason)
        except Exception:
            pass

    def _finalize_obs(self) -> None:
        """Terminal observability flush (close/drain, idempotent): dump the
        flight ring and land the program-utilization snapshots
        (``kind="program"``/``ev="util"``) in the EventLog, then drop out
        of the /healthz registry — a terminated engine must not hold the
        process at 503."""
        if self._finalized:
            return
        self._finalized = True
        self._flight_dump("close")
        try:
            for prog in ("lm_decode_rows", "lm_prefill_slot",
                         "lm_generate_batch"):
                perf.get_program_costs().emit(prog)
        except Exception:
            pass
        unregister_health_provider(self._name)

    def drain(self) -> None:
        """Graceful stop: no new admissions (rejections say "draining"), but
        everything already accepted — queued and in flight — completes.
        Partial batches dispatch immediately. Terminal: the worker exits and
        is joined before this returns."""
        self._queue.close("engine draining (no new admissions)")
        self.start()  # a never-started engine still owes queued results
        with self._cond:
            if self._state == "running":
                self._state = "draining"
            self._cond.notify_all()
        if self._started:
            self._thread.join()
        with self._cond:
            self._state = "closed"
        self._finalize_obs()

    def close(self) -> None:
        """Fast stop: no new admissions, the batch in flight completes, and
        every still-queued request is retired with a clean
        ``shutting_down`` Result (never silently dropped). Idempotent."""
        self._queue.close("engine shutting down")
        with self._cond:
            if self._state == "closed":
                return
            self._state = "closing"
            leftovers = self._former.take_all()
            self._cond.notify_all()
        for e in leftovers:
            self._retire(e, Result(
                e.request.rid, STATUS_SHUTTING_DOWN,
                reason="engine closed before this request was scheduled"))
        if self._started:
            self._thread.join()
        with self._cond:
            self._state = "closed"
        self._finalize_obs()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- admission

    def submit(self, request: Request) -> ResultHandle:
        """Admit one request. Always returns a handle that will carry exactly
        one Result; overload / no-bucket / past-deadline submissions resolve
        immediately with ``rejected`` / ``expired`` status and a reason.

        Opens the request's span (a child of the caller's active span when
        there is one, else a fresh trace root), so every record the request
        ever produces — here and on the worker thread — shares one
        ``trace_id``."""
        ctx = obs_trace.child_of_current(f"serve.request.{request.rid}")
        with obs_trace.use(ctx):
            return self._submit(request, ctx)

    def _submit(self, request: Request, ctx) -> ResultHandle:
        faults.fire("serve.enqueue", path=str(request.rid))
        handle = ResultHandle(request)
        now = self._clock()
        bucket = pick_bucket(request.prompt.shape[0], request.steps,
                             self.buckets)
        if bucket is None:
            return self._refuse(handle, STATUS_REJECTED, (
                f"no bucket fits prompt_len={request.prompt.shape[0]} "
                f"steps={request.steps} (buckets {list(self.buckets)})"))
        if request.deadline is not None and request.deadline <= now:
            return self._refuse(handle, STATUS_EXPIRED, (
                f"deadline {request.deadline} already passed at submission "
                f"(now {now})"))
        cost = bucket_kv_bytes(self.params, self.heads, bucket,
                               self.compute_dtype)
        reason = self._queue.try_admit(cost)
        if reason is not None:
            return self._refuse(handle, STATUS_REJECTED, reason)
        entry = _Entry(request, handle, bucket, cost, now, trace=ctx)
        with self._cond:
            if self._state != "running":
                admitted = False
            else:
                self._former.add(entry)
                self._cond.notify_all()
                admitted = True
        if not admitted:  # raced with close(): resolve, don't strand
            self._queue.release(cost)
            return self._refuse(handle, STATUS_REJECTED,
                                "engine is shutting down")
        self.metrics.record_enqueue(request.rid, bucket, self._queue.count)
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    def _refuse(self, handle, status: str, reason: str) -> ResultHandle:
        handle._set(Result(handle.request.rid, status, reason=reason))
        if status == STATUS_REJECTED:
            self.metrics.record_reject(handle.request.rid, reason)
        else:
            self.metrics.record_result(handle.request.rid, status)
        return handle

    # ----------------------------------------------------------- worker loop

    def _run(self) -> None:
        if self.rowlevel:
            self._run_rowlevel()
        else:
            self._run_gang()

    def _run_gang(self) -> None:
        inflight = []
        try:
            while True:
                self._heartbeat = time.monotonic()
                batch = None
                with self._cond:
                    while True:
                        if self._state == "closing":
                            return
                        draining = self._state == "draining"
                        batch = self._former.next_batch(self._clock(),
                                                        force=draining)
                        if batch[0] is not None:
                            break
                        if draining:
                            return  # nothing pending; in-flight is us
                        hint = batch[1]
                        if self._real_clock:
                            # submit/drain/close all notify — idle waits
                            # need no polling on the real clock
                            self._cond.wait(hint)
                        else:
                            # injected clock: cap the real wait so advances
                            # between polls are observed promptly
                            self._cond.wait(
                                _POLL_CAP_S if hint is None
                                else min(max(hint, 1e-4), _POLL_CAP_S))
                inflight = batch[1]
                self._execute(*batch)
                inflight = []
        except BaseException:  # pragma: no cover - scheduler invariant
            # a dying worker must not strand submitters on .result(): fail
            # the batch it was holding plus everything still queued, then
            # re-raise for the thread log (_execute absorbs ordinary
            # Exceptions itself; this path is KeyboardInterrupt-class)
            with self._cond:
                leftovers = self._former.take_all()
                self._state = "closing"
            for e in leftovers + [e for e in inflight
                                  if not e.handle.done()]:
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason="serving worker died"))
            self._flight_dump("worker-died")
            raise

    def _retire(self, entry: _Entry, result: Result) -> None:
        entry.handle._set(result)
        self._queue.release(entry.cost)
        # re-activate the request's span on whichever thread retires it, so
        # the result record joins the request's trace
        with obs_trace.use(entry.trace):
            self.metrics.record_result(
                result.rid, result.status,
                bucket=result.metrics.get("bucket"),
                queue_s=result.metrics.get("queue_s"),
                total_s=result.metrics.get("total_s"),
                ttft_s=result.metrics.get("ttft_s"))
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)

    # ------------------------------------------------- row-level scheduler

    def _run_rowlevel(self) -> None:
        """The slot-step loop: each iteration refills freed slots from the
        queue (prefill-on-admit), retires finished/expired rows, and runs
        one decode step per bucket with live rows. ``pools`` maps bucket ->
        SlotPool and persists across iterations — the KV slab never leaves
        the device between steps."""
        pools: dict[tuple, object] = {}
        claimed: list[_Entry] = []
        try:
            while True:
                self._heartbeat = time.monotonic()
                claimed = []
                with self._cond:
                    while True:
                        if self._state == "closing":
                            # the live slots are the work in flight: finish
                            # them (close() already emptied the former)
                            if not any(p.live_slots()
                                       for p in pools.values()):
                                return
                            break
                        draining = self._state == "draining"
                        claimed = self._claim_rowlevel(pools)
                        if claimed or any(p.live_slots()
                                          for p in pools.values()):
                            break
                        if draining:
                            return  # nothing queued, nothing live
                        # no max_wait ripening in row-level mode: wait for
                        # a submit/drain/close notify (poll-capped under an
                        # injected clock, as in the gang loop)
                        self._cond.wait(None if self._real_clock
                                        else _POLL_CAP_S)
                self._admit_rowlevel(pools, claimed)
                claimed = []
                self._step_rowlevel(pools)
        except BaseException:  # pragma: no cover - scheduler invariant
            # as in the gang loop: a dying worker fails everything it was
            # holding — claimed-but-unslotted entries, live slots, and the
            # still-queued backlog — so no submitter is stranded
            with self._cond:
                leftovers = self._former.take_all()
                self._state = "closing"
            live = [p.entries[i] for p in pools.values()
                    for i in p.live_slots()]
            for e in leftovers + claimed + live:
                if not e.handle.done():
                    self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                           reason="serving worker died"))
            self._flight_dump("worker-died")
            raise

    def _claim_rowlevel(self, pools) -> list[_Entry]:
        """Claim queued entries for free slots, per bucket (called under the
        engine lock; prefill happens outside it)."""
        claimed = []
        for bucket in self._former.pending_buckets():
            pool = pools.get(bucket)
            free = self.max_batch if pool is None \
                else len(pool.free_slots())
            if free:
                claimed.extend(self._former.take_for_bucket(bucket, free))
        return claimed

    def _admit_rowlevel(self, pools, claimed) -> None:
        """Prefill each claimed entry into a free slot of its bucket's pool
        (created lazily). The first token lands here — the row's TTFT."""
        from .batcher import SlotPool
        from ..models.transformer import lm_prefill_slot

        for e in claimed:
            # the worker runs every request's admission inside that
            # request's span: its prefill record — and any compile the
            # bridge observes during it — joins the request's trace
            with obs_trace.use(e.trace):
                now = self._clock()
                r = e.request
                dl = r.deadline
                p, s = e.bucket
                if dl is not None and dl <= now:
                    self._retire(e, Result(
                        r.rid, STATUS_EXPIRED,
                        reason=f"deadline {dl} passed before dispatch "
                               f"(dispatched at {now})",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                e.queue_s = now - e.enq_t
                try:
                    faults.fire("serve.step", path=f"bucket-{p}x{s}")
                    pool = pools.get(e.bucket)
                    if pool is None:
                        pool = pools[e.bucket] = SlotPool(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype)
                        # no-warmup path: the bucket's cost model still
                        # lands with its first (lazy) compile
                        capture_bucket_costs(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype, self.moe,
                            rowlevel=True, key=self._prog_key(e.bucket))
                    slot = pool.free_slots()[0]
                    prompt = np.zeros((p,), np.int32)
                    n = r.prompt.shape[0]
                    prompt[:n] = r.prompt
                    t0 = time.perf_counter()
                    caches, tokens, first = lm_prefill_slot(
                        self.params, pool.caches, pool.tokens, slot, prompt,
                        n, heads=self.heads, max_len=p + s, seed=r.seed,
                        temperature=r.temperature, top_p=r.top_p,
                        top_k=r.top_k, compute_dtype=self.compute_dtype,
                        moe=self.moe)
                    first = int(first)  # device sync: the first token exists
                    wall = time.perf_counter() - t0
                except Exception as exc:
                    self._admit_failure(pools, e, exc)
                    continue
                pool.caches, pool.tokens = caches, tokens
                pool.assign(slot, e)
                pool.ttft_s[slot] = self._clock() - e.enq_t
                self.metrics.record_prefill(
                    e.bucket, wall, rid=r.rid,
                    program_key=self._prog_key(e.bucket))
                self.flight.record(
                    "prefill", bucket=[p, s], slot=slot, rid=r.rid,
                    seconds=wall, queue_depth=self._queue.count,
                    compiles=_compile_count())
                if r.steps == 1 or (r.eos is not None and first == r.eos):
                    self._retire_row(pool, slot, STATUS_OK, self._clock())
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _step_rowlevel(self, pools) -> None:
        """Retire expired live rows, then run ONE decode step per bucket
        with live rows and retire rows that finished on it. All buckets'
        step programs are DISPATCHED before any result is awaited — JAX
        dispatch is async, so bucket B's device work overlaps the host
        round-trip for bucket A instead of serializing behind it."""
        from ..models.transformer import lm_decode_rows

        launched = []
        for bucket, pool in list(pools.items()):
            now = self._clock()
            for i in pool.live_slots():
                dl = pool.entries[i].request.deadline
                if dl is not None and dl <= now:
                    self._retire_row(
                        pool, i, STATUS_EXPIRED, now,
                        reason=f"deadline {dl} passed mid-decode "
                               f"(now {now})")
            live = pool.live_slots()
            if not live:
                continue
            p, s = bucket
            try:
                faults.fire("serve.decode_step", path=f"bucket-{p}x{s}")
                t0 = time.perf_counter()
                caches, tokens, nxt = lm_decode_rows(
                    self.params, pool.caches, pool.tokens, pool.positions,
                    pool.steps_done, pool.seeds, pool.temperature,
                    pool.top_p, pool.top_k, heads=self.heads,
                    max_len=pool.max_len, compute_dtype=self.compute_dtype,
                    moe=self.moe)
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            pool.caches, pool.tokens = caches, tokens
            launched.append((bucket, pool, live, t0, nxt))
        for bucket, pool, live, t0, nxt in launched:
            try:
                nxt = np.asarray(nxt)  # sync; the per-row emitted tokens
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            wall = time.perf_counter() - t0
            self.metrics.record_step(bucket, len(live), self.max_batch, wall,
                                     program_key=self._prog_key(bucket))
            self.flight.record(
                "step", bucket=list(bucket), rows=len(live),
                seconds=wall, queue_depth=self._queue.count,
                compiles=_compile_count())
            now = self._clock()
            host_tokens = None  # one slab fetch shared by this step's retirees
            for i in live:
                pool.positions[i] += 1
                pool.steps_done[i] += 1
                r = pool.entries[i].request
                if ((r.eos is not None and int(nxt[i]) == r.eos)
                        or int(pool.steps_done[i]) >= r.steps):
                    if host_tokens is None:
                        host_tokens = np.asarray(pool.tokens)
                    self._retire_row(pool, i, STATUS_OK, now,
                                     host_tokens=host_tokens)
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _retire_row(self, pool, slot: int, status: str, now: float,
                    reason: str = "", host_tokens=None) -> None:
        """Retire one slot's row and free the slot — the ONLY path a live
        slot leaves the pool by, so every terminal status releases the
        admission budget exactly once. ``host_tokens`` lets a step that
        retires several rows share ONE slab fetch (the transfer is whole-slab
        either way: a per-slot device gather would compile one tiny
        executable per static slot index and break the
        zero-compiles-under-traffic guarantee)."""
        e = pool.entries[slot]
        metrics = {"bucket": pool.bucket, "slot": slot,
                   "queue_s": e.queue_s, "ttft_s": pool.ttft_s[slot],
                   "total_s": now - e.enq_t}
        if status == STATUS_OK:
            n = int(pool.lengths[slot])
            emitted = int(pool.steps_done[slot])
            if host_tokens is None:
                host_tokens = np.asarray(pool.tokens)
            toks = host_tokens[slot, : n + emitted].copy()
            result = Result(e.request.rid, STATUS_OK, tokens=toks,
                            metrics=metrics)
        else:
            result = Result(e.request.rid, status, reason=reason,
                            metrics=metrics)
        pool.release(slot)
        self._retire(e, result)

    def _fail_pool(self, pools, bucket, exc: Exception) -> None:
        """A decode step died: fail ONLY that step's live rows with error
        Results and leave the slot pool consistent (slots freed, budget
        released). If the failed call consumed the donated slab (a genuine
        post-dispatch failure, not an injected fault raised before launch),
        drop the pool — it is rebuilt zeroed on the next admission."""
        pool = pools[bucket]
        reason = f"decode step failed: {type(exc).__name__}: {exc}"
        self.flight.record("decode_fault", bucket=list(bucket),
                           rows=len(pool.live_slots()), error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        now = self._clock()
        for i in pool.live_slots():
            self._retire_row(pool, i, STATUS_ERROR, now, reason=reason)
        if self._slab_lost(pool):
            pools.pop(bucket)
        # the black box lands NOW, while the final iterations are still in
        # the ring — the post-mortem for exactly this failure class
        self._flight_dump("decode-step-failed")

    def _admit_failure(self, pools, entry: _Entry, exc: Exception) -> None:
        """A prefill died: the entry being admitted gets an error Result;
        co-resident live rows survive unless the failed call consumed the
        donated slab, in which case they fail too and the pool is dropped."""
        now = self._clock()
        reason = f"prefill failed: {type(exc).__name__}: {exc}"
        self._retire(entry, Result(
            entry.request.rid, STATUS_ERROR, reason=reason,
            metrics={"bucket": entry.bucket, "queue_s": entry.queue_s,
                     "total_s": now - entry.enq_t}))
        self.flight.record("prefill_fault", bucket=list(entry.bucket),
                           rid=entry.request.rid, error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        pool = pools.get(entry.bucket)
        if pool is not None and self._slab_lost(pool):
            for i in pool.live_slots():
                self._retire_row(pool, i, STATUS_ERROR, now,
                                 reason=f"slab lost to a failed prefill: "
                                        f"{reason}")
            pools.pop(entry.bucket)
        self._flight_dump("prefill-failed")

    @staticmethod
    def _slab_lost(pool) -> bool:
        """True when a failed donated call consumed the pool's arrays (the
        backends that implement donation delete the inputs on dispatch;
        injected faults raise before the call and never trip this)."""
        deleted = getattr(pool.tokens, "is_deleted", None)
        return bool(deleted and deleted())

    # ---------------------------------------------------- gang scheduler

    def _execute(self, group_key, entries) -> None:
        """One engine cycle: expire stale rows, prefill live rows into the
        bucket's fixed-width slot batch, run the compiled program, retire."""
        import jax

        from ..models.transformer import lm_generate_batch

        bucket, temperature, top_p, top_k, _ = group_key
        # sampled groups share one seed (the former keys on it); greedy
        # groups ignore the key entirely, so any member's seed serves
        p, s = bucket
        dispatch_t = self._clock()
        live = []
        for e in entries:
            dl = e.request.deadline
            if dl is not None and dl <= dispatch_t:
                self._retire(e, Result(
                    e.request.rid, STATUS_EXPIRED,
                    reason=f"deadline {dl} passed before dispatch "
                           f"(dispatched at {dispatch_t})",
                    metrics={"bucket": bucket,
                             "queue_s": dispatch_t - e.enq_t,
                             "total_s": dispatch_t - e.enq_t}))
            else:
                live.append(e)
        if not live:
            return
        self._live_rows = len(live)
        capture_bucket_costs(self.params, self.heads, bucket, self.max_batch,
                             self.compute_dtype, self.moe, rowlevel=False,
                             key=self._prog_key(bucket))
        try:
            faults.fire("serve.step", path=f"bucket-{p}x{s}")
            # prefill the claimed slots; free slots carry inert dummy rows so
            # the batch shape (and the compiled program) never varies
            prompts = np.zeros((self.max_batch, p), np.int32)
            lengths = np.ones((self.max_batch,), np.int32)
            for i, e in enumerate(live):
                n = e.request.prompt.shape[0]
                prompts[i, :n] = e.request.prompt
                lengths[i] = n
            key = jax.random.key(live[0].request.seed)
            t0 = time.perf_counter()
            out = np.asarray(lm_generate_batch(
                self.params, prompts, lengths, key, heads=self.heads,
                max_len=p + s, steps=s, temperature=temperature, top_p=top_p,
                top_k=top_k, compute_dtype=self.compute_dtype, moe=self.moe))
            wall = time.perf_counter() - t0
        except Exception as exc:
            reason = f"batch failed: {type(exc).__name__}: {exc}"
            self.flight.record("batch_fault", bucket=[p, s], rows=len(live),
                               error=reason, queue_depth=self._queue.count,
                               compiles=_compile_count())
            done_t = self._clock()
            for e in live:
                self._retire(e, Result(
                    e.request.rid, STATUS_ERROR, reason=reason,
                    metrics={"bucket": bucket,
                             "queue_s": dispatch_t - e.enq_t,
                             "total_s": done_t - e.enq_t}))
            self._live_rows = 0
            self._flight_dump("batch-failed")
            return
        done_t = self._clock()
        for i, e in enumerate(live):
            n = e.request.prompt.shape[0]
            self._retire(e, Result(
                e.request.rid, STATUS_OK,
                tokens=out[i, : n + e.request.steps].copy(),
                metrics={"bucket": bucket, "queue_s": dispatch_t - e.enq_t,
                         "ttft_s": done_t - e.enq_t,
                         "total_s": done_t - e.enq_t}))
        self.metrics.record_batch(bucket, len(live), self.max_batch,
                                  len(live) * s, wall,
                                  program_key=self._prog_key(bucket))
        self.flight.record("batch", bucket=[p, s], rows=len(live),
                           seconds=wall, queue_depth=self._queue.count,
                           compiles=_compile_count())
        self._live_rows = 0
