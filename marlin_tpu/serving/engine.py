"""The continuous-batching serving engine: one worker thread, compiled decode.

:class:`ServeEngine` is the front half of an inference stack over the
library's compiled decode programs: concurrent callers ``submit`` requests;
an admission gate (queue depth + in-flight KV-cache HBM budget, request.py)
rejects overload with a reason; a batch former (batcher.py) buckets prompts
onto a small static shape set so compiles stay bounded; and a single worker
thread keeps the device fed. Scheduling is row-level (the unit is the
slot-step, not the batch — the gang scheduler of PR 3 is retired and its
``rowlevel`` escape hatch removed). Two KV-cache backends share the
row-level skeleton:

**Paged** (``serve_paged``, the default): ONE device-resident page slab
(:mod:`.kvpool` over :func:`~marlin_tpu.models.transformer.init_kv_pages`)
shared by every bucket; each row holds a host-side block table of pages.
Admission charges the request's ACTUAL pages
(:func:`~marlin_tpu.models.planner.request_pages` — a short request in a
long bucket no longer reserves the bucket's worst case), completed full
prompt pages are prefix-shared copy-on-write across requests (a common
system prompt is prefilled once — :class:`~.kvpool.PagedKVPool`), and long
prompts prefill in bounded ``serve_prefill_chunk``-token chunks. Every
worker iteration:

    refill freed rows from the queue (page allocation + prefix match —
    host-side, cheap)  →  prefill at most ``serve_prefill_chunk`` prompt
    TOKENS, oldest row first, in page-aligned chunks (several short rows
    may share the budget; a long prompt takes one chunk and resumes next
    iteration; a row's final chunk emits its first token — real TTFT)
    →  retire rows that emitted ``eos``, hit their step budget, or
    expired  →  run ONE decode step per bucket over its live rows  →
    repeat

so one long prompt can never monopolize an iteration — decode steps
interleave between its chunks, bounding TTFT for everyone else. ≤ 3
compiled programs per bucket (chunked prefill + decode step, plus one
pool-wide page-copy program), for ANY per-row mix of sampling knobs.

**Dense slab** (``serve_paged=False``, the PR 4 control): each bucket owns
a persistent ``(max_batch, max_len, kvh, dh)`` slab
(:class:`~.batcher.SlotPool`), whole-prompt prefill on admit
(:func:`~marlin_tpu.models.transformer.lm_prefill_slot`), decode via
:func:`~marlin_tpu.models.transformer.lm_decode_rows` — 2 programs per
bucket, admission charged at the bucket worst case. The paged-vs-slab A/B
in ``bench_all.py serve`` runs this side.

Both backends keep the invariants PR 3/4 established: exactly one Result
per request, per-row greedy output bit-identical to
:func:`~marlin_tpu.models.transformer.lm_generate` on the unpadded prompt
(the paged decode literally reuses ``_decode_step``), and sampled rows on
composition-independent ``fold_in(key(seed), step)`` streams.

**Pluggable programs** (serving/programs/): LM decode is one
:class:`~.programs.BucketProgram` among several — ``ServeEngine(...,
programs=[ALSScoreProgram(model), ...])`` registers additional request
types (``Request.program``) that ride the SAME spine: admission prices
each program in its own resource-unit bytes against the one HBM budget,
the former buckets program requests under ``(name, *bucket)`` keys next to
LM's ``(prompt, steps)`` tuples, and the worker loop interleaves one-shot
program batches (:class:`~.programs.ProgramRowSet` rows, a single compiled
step per bucket) between LM prefill chunks and decode steps. Every
program's rows are drained, closed, crash-recovered, frozen and adopted by
the same code paths as LM rows — a program row just has no KV pages to
carry, so migration moves it through the queued/fallback lanes.

Lifecycle: ``drain()`` stops admission and completes everything already
accepted; ``close()`` stops admission, finishes the work in flight (live
and mid-prefill rows), and retires everything still queued with a clean
``shutting_down`` Result. Both are terminal and idempotent; the worker
thread (named ``marlin-serve-*`` — the conftest leak fixture watches the
prefix) is joined before either returns. Chaos hooks (utils/faults.py):
``serve.enqueue`` fires in ``submit``; ``serve.step`` fires before each
slab prefill; ``serve.prefill`` fires before each paged prefill CHUNK — a
fault fails/retries that one request and the pool stays consistent (the
chunk cursor makes prefill resumable, so a retry re-runs the prompt from
its shared prefix); ``serve.decode_step`` fires before each decode step —
a fault fails/retries only that step's live rows. The engine keeps serving
after any of them; if a failed donated call consumed the page slab, every
resident row fails/retries and the pool is rebuilt zeroed — the same
contract worker-crash recovery gives it (supervisor.py: pools dropped,
live rows requeued, page-unit admission reservations carried across
attempts).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
import weakref

import numpy as np

from ..config import get_config
from ..obs import memledger, perf, trace as obs_trace
from ..obs.collectors import compile_count as _compile_count
from ..obs.exposition import (register_health_provider,
                              register_kvpool_provider,
                              register_slo_provider,
                              unregister_health_provider,
                              unregister_kvpool_provider,
                              unregister_slo_provider)
from ..obs.metrics import get_registry
from ..utils import faults
from .batcher import (BatchFormer, bucket_program_key, capture_bucket_costs,
                      normalize_buckets, warmup_buckets)
from .kvpool import (PagedGroup, PagedKVPool, PagePoolExhausted,
                     auto_num_pages, capture_paged_costs, paged_program_key,
                     warmup_paged)
from .metrics import ServeMetrics
from .programs import PagedLMProgram, ProgramRowSet
from .request import (SHED_REASON_PREFIX, STATUS_ERROR, STATUS_EXPIRED,
                      STATUS_OK, STATUS_REJECTED, STATUS_SHUTTING_DOWN,
                      AdmissionQueue, Request, Result, ResultHandle)

__all__ = ["ServeEngine", "MigrationError"]


class MigrationError(RuntimeError):
    """A freeze/adopt handoff could not run (wrong backend or lifecycle
    state, or the target worker did not service the request in time). The
    router falls back to the PR 7 retry path on it."""

_engine_ids = itertools.count()
_mig_tokens = itertools.count()  # distinct migration-blob ledger names

# real-seconds cap on one condition wait under an INJECTED clock: bounds how
# stale the worker's view of a fake clock can get (tests advance it between
# polls). Real-clock engines never poll — they wait on the condition until
# notified or the exact max_wait hint elapses.
_POLL_CAP_S = 0.02


class _Entry:
    """One admitted request riding through the former to a row.
    ``queue_s`` is stamped when the scheduler claims the entry. ``trace``
    is the request's span context (obs/trace.py), captured at submit and
    re-activated by the worker thread around every record the request
    produces — that cross-thread handoff is what joins one request's
    enqueue/prefill/result records into one trace in the JSONL.

    ``attempt`` counts executions of this request (1-based); a retry
    re-queues a FRESH entry via :meth:`retry` — same request, handle,
    admission cost (the HBM reservation is carried, never re-charged), and
    original ``enq_t`` (latency is honest: it includes the failed
    attempts) — and marks this one ``superseded`` so a stale worker
    generation that still holds it can never retire it. The exactly-once
    Result is enforced twice over: superseded entries no-op in ``_retire``,
    and the admission budget is released only by whoever wins the handle's
    single ``_set``."""

    __slots__ = ("request", "handle", "bucket", "cost", "enq_t", "queue_s",
                 "trace", "attempt", "superseded")

    def __init__(self, request, handle, bucket, cost, enq_t, trace=None,
                 attempt=1):
        self.request = request
        self.handle = handle
        self.bucket = bucket
        self.cost = cost
        self.enq_t = enq_t
        self.queue_s = None
        self.trace = trace
        self.attempt = attempt
        self.superseded = False

    def retry(self) -> "_Entry":
        """The next-attempt twin (this entry becomes superseded)."""
        self.superseded = True
        return _Entry(self.request, self.handle, self.bucket, self.cost,
                      self.enq_t, trace=self.trace, attempt=self.attempt + 1)

    def attempts_left(self) -> bool:
        return self.attempt < self.request.max_attempts


class ServeEngine:
    """Continuous-batching inference engine over a trained LM.

    ``params``/``heads``/``compute_dtype``/``moe`` describe the model exactly
    as :func:`lm_generate_batch` takes them. Knobs default from the global
    config: ``buckets`` (``serve_buckets``), ``max_batch``
    (``serve_max_batch``), ``max_wait_ms`` (``serve_max_wait_ms``),
    ``queue_depth`` (``serve_queue_depth``); ``hbm_budget_bytes`` defaults to
    the planner's :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (0
    disables the byte gate). ``clock`` is the engine's *policy* clock
    (deadlines, max_wait, latency metrics) — injectable for deterministic
    tests; wall throughput is always measured on the real clock. ``log``
    overrides the default EventLog for ``serve`` records.

    ``paged`` picks the KV backend (``serve_paged`` by default): True = the
    paged pool (block tables over one shared page slab, prefix caching,
    chunked prefill; ``page_len``/``num_pages``/``prefill_chunk``/
    ``prefix_cache`` override the ``serve_*`` knobs); False = the dense
    per-bucket slot slab (the PR 4 control). The long-deprecated
    ``rowlevel`` kwarg is REMOVED (the gang scheduler it disabled retired
    in PR 8) — passing it raises; use ``serve_paged``/``paged`` to pick
    the KV backend.

    ``programs`` registers additional :class:`~.programs.BucketProgram`
    instances (ALS scoring, PageRank queries, classification, ...) served
    next to LM traffic — requests route by ``Request.program``.

    Usable as a context manager (``close()`` on exit); ``start=False`` defers
    the worker thread so tests can stage a queue before any dispatch."""

    def __init__(self, params: dict, heads: int, *, buckets=None,
                 max_batch: int | None = None,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compute_dtype: str | None = None, moe: tuple | None = None,
                 rowlevel: bool | None = None, paged: bool | None = None,
                 page_len: int | None = None, num_pages: int | None = None,
                 prefill_chunk: int | None = None,
                 prefix_cache: bool | None = None,
                 decode_kernel: str | None = None,
                 programs=None,
                 clock=time.monotonic, log=None, start: bool = True):
        cfg = get_config()
        self.params = params
        self.heads = heads
        self.compute_dtype = compute_dtype
        self.moe = moe
        if rowlevel is not None:
            raise ValueError(
                "ServeEngine(rowlevel=...) was removed: the gang scheduler "
                "it selected retired in PR 8 and scheduling is always "
                "row-level — use serve_paged/paged to pick the KV backend")
        self.rowlevel = True  # legacy attribute: always row-level now
        self.paged = bool(cfg.serve_paged if paged is None else paged)
        self.buckets = normalize_buckets(
            cfg.serve_buckets if buckets is None else buckets)
        self.max_batch = int(cfg.serve_max_batch if max_batch is None
                             else max_batch)
        wait_ms = cfg.serve_max_wait_ms if max_wait_ms is None else max_wait_ms
        depth = int(cfg.serve_queue_depth if queue_depth is None
                    else queue_depth)
        # --- paged-pool geometry (serving/kvpool.py) -----------------------
        # decode-attention backend, resolved once ('auto' → pallas on TPU,
        # gather elsewhere) so every program key / warmup / dispatch in
        # this engine agrees on it
        from ..models.transformer import resolve_decode_kernel

        self._decode_kernel = resolve_decode_kernel(
            cfg.serve_decode_kernel if decode_kernel is None
            else decode_kernel)
        self._page_len = int(cfg.serve_page_len if page_len is None
                             else page_len)
        if self.paged and self._decode_kernel == "pallas":
            # the fused kernel streams whole pages as sublane-aligned
            # blocks; round the page size up rather than fall back
            from ..ops.paged_attention import align_page_len

            self._page_len = align_page_len(self._page_len)
        self._prefill_chunk = int(cfg.serve_prefill_chunk
                                  if prefill_chunk is None else prefill_chunk)
        self._prefix_cache = bool(cfg.serve_prefix_cache
                                  if prefix_cache is None else prefix_cache)
        npages = int(cfg.serve_num_pages if num_pages is None else num_pages)
        if npages <= 0:
            npages = auto_num_pages(self.buckets, self.max_batch,
                                    self._page_len)
        self._num_pages = npages
        self._kvpool: PagedKVPool | None = None  # built lazily / on warmup
        if self.paged:
            from ..models.planner import kv_page_bytes

            self._page_bytes = kv_page_bytes(params, heads, self._page_len,
                                             compute_dtype)
        if hbm_budget_bytes is None:
            from ..models.planner import usable_hbm_bytes

            hbm_budget_bytes = usable_hbm_bytes()
        self._clock = clock
        self._real_clock = clock is time.monotonic
        self.metrics = ServeMetrics(log=log)
        self._queue = AdmissionQueue(depth, hbm_budget_bytes)
        self._cond = threading.Condition()
        self._former = BatchFormer(self.buckets, self.max_batch,
                                   max_wait=float(wait_ms) / 1e3)
        # Request.program routing table: LM (this engine's paged/slab path,
        # wrapped as the first BucketProgram) plus whatever the caller
        # registered. Former/pool keys for non-LM buckets are namespaced
        # (name, *bucket) tuples — a str head can never collide with LM's
        # (prompt, steps) int pairs
        self._programs: dict[str, object] = {"lm": PagedLMProgram(self)}
        for p in (programs or ()):
            if not getattr(p, "name", ""):
                raise ValueError(f"program {p!r} must set a non-empty .name")
            if p.name in self._programs:
                raise ValueError(f"duplicate program name {p.name!r}")
            self._programs[p.name] = p
        # running | draining | freezing | frozen | closing | closed —
        # freezing/frozen are the migration pause (freeze_rows): the worker
        # parks leaving its pools intact and the freezing thread takes over
        self._state = "running"
        #: worker mailbox for cross-engine migration ops (adopt_rows /
        #: export_prefixes / import_prefixes): (kind, payload, event, box)
        #: tuples serviced at the top of each worker iteration — the pool
        #: stays single-threaded, the requester waits on the event
        self._mig_inbox: collections.deque = collections.deque()
        self._started = False
        #: True while warmup() compiles bucket programs on the caller's
        #: thread — the supervisor's watchdog skips the stuck check (first
        #: compiles routinely outlast any sane watchdog_s; crash detection
        #: stays on), so a freshly scaled-out replica is never "recovered"
        #: mid-warmup
        self._warming = False
        eid = next(_engine_ids)
        self._name = f"marlin-serve-{eid}"
        # --- supervised recovery (serving/supervisor.py) -------------------
        # the worker generation: a recovery bumps it, spawns a fresh thread,
        # and any stale worker still unwinding exits at its next gen check
        # without touching shared state (its entries are superseded)
        self._gen = 0
        self._pools: dict[tuple, object] = {}   # current worker's slot pools
        self._claimed: list = []                # claimed-but-unslotted rows
        self._crash: tuple | None = None        # (exc, undone entries)
        self._on_crash = None                   # supervisor's prompt-wake cb
        self._abandoned = None                  # superseded wedged thread:
        # never joined (breaker opened on a stuck worker — close() must not
        # block on a thread that may never return from its device call)
        self._idle = False                      # worker parked in cond.wait
        # EWMA of per-request service seconds (ok results, engine clock) —
        # the deadline-admission estimate's only input
        self._service_ewma = 0.0
        self._thread = self._make_thread(0)
        # --- performance introspection (obs/perf.py) -----------------------
        # the step-time black box: per-iteration records from the worker
        # loop, dumped on worker faults, on close, and via GET /debug/flight
        self.flight = perf.FlightRecorder(name=self._name)
        self._heartbeat: float | None = None  # real clock; worker stamps it
        self._live_rows = 0                   # worker-written, healthz-read
        self._prog_keys: dict[tuple, str] = {}
        # per-bucket measured-peak admission ratio (obs/memledger.py),
        # resolved once on first admission to that bucket
        self._calib_ratios: dict[tuple, float] = {}
        self._finalized = False
        # readiness: /healthz reports this engine's lifecycle and 503s once
        # it leaves "accepting" (weakref — the provider must never pin a
        # dead engine; terminal close/drain unregister explicitly)
        ref = weakref.ref(self)
        name = self._name

        def _health():
            eng = ref()
            if eng is None:
                # abandoned without close(): drop out silently — a dead
                # entry must not 503 an otherwise healthy process for one
                # probe (health_payload skips None)
                unregister_health_provider(name)
                return None
            return eng._health_info()

        register_health_provider(name, _health)
        if self.paged:
            def _kvpool_report():
                eng = ref()
                if eng is None:
                    unregister_kvpool_provider(name)
                    return None
                return eng.kvpool_audit()

            register_kvpool_provider(name, _kvpool_report)
        # --- serving SLOs (obs/slo.py + obs/timeseries.py) -----------------
        # built only when objectives are configured (serve_slo) — otherwise
        # the hot path carries literally nothing (one None check per worker
        # iteration). The store and the SLO engine run on THIS engine's
        # injected clock; evaluation is scrape- and worker-driven (tick is
        # rate-limited), never a new thread.
        self._slo = None
        self._ts = None
        self._ts_collector = None
        if cfg.serve_slo:
            from ..obs.slo import SloEngine, objectives_from_config
            from ..obs.timeseries import TimeSeriesStore, install_collector

            self._ts = TimeSeriesStore(
                window_s=float(cfg.serve_ts_window_s),
                bucket_s=float(cfg.serve_ts_bucket_s), clock=clock)
            self.metrics.attach_timeseries(self._ts)
            self._slo = SloEngine(objectives_from_config(cfg), self._ts,
                                  scope=self._name, log=log, clock=clock)
            # scrape-driven pump, restricted to the objectives' families:
            # the registry is process-global (a labeled child per engine
            # ever created) while the store is a bounded per-engine ring —
            # an unfiltered pump would exhaust max_series in a long-lived
            # process and starve the latency-sample feed
            self._ts_collector = install_collector(
                self._ts, only=self._slo.pump_families)
            if cfg.serve_slo_shed:
                # graceful degradation: a breach arms admission shedding at
                # level = number of breached objectives (deeper breach ->
                # higher priority tiers shed); clear disarms. In-flight
                # work is never touched (request.py AdmissionQueue).
                slack = float(cfg.serve_slo_shed_slack_s)

                def _on_breach(ev, _q=self._queue, _slack=slack):
                    breached = ev.get("breached") or ()
                    if breached:
                        _q.set_shed(len(breached),
                                    reason=",".join(breached),
                                    protect_slack_s=_slack)
                    else:
                        _q.clear_shed()

                self._slo.add_breach_hook(_on_breach)

            def _slo_report():
                eng = ref()
                if eng is None:
                    unregister_slo_provider(name)
                    return None
                return eng._slo_payload()

            register_slo_provider(name, _slo_report)
        if start:
            self.start()

    # ------------------------------------------------------------- lifecycle

    def _make_thread(self, gen: int) -> threading.Thread:
        """A worker thread for one generation. Restarted generations keep
        the ``marlin-serve`` prefix (the conftest leak fixture and the
        flight recorder key on it) with a ``-r<gen>`` suffix."""
        name = self._name if gen == 0 else f"{self._name}-r{gen}"
        return threading.Thread(target=self._run, args=(gen,), daemon=True,
                                name=name)

    def start(self) -> None:
        """Start the worker thread (idempotent; no-op once shutting down)."""
        with self._cond:
            if self._started or self._state != "running":
                return
            self._started = True
        self._thread.start()

    def warmup(self) -> int:
        """Compile every bucket's programs before traffic: the chunked
        prefill + decode pair per bucket plus the shared page-copy program
        in paged mode (kvpool.warmup_paged, against THIS engine's pool —
        program identity includes the slab shape), the slot prefill +
        decode pair in slab mode (batcher.warmup_buckets). Call before the
        first submit — warmup drives the live pool."""
        self._warming = True
        try:
            if self.paged:
                with self._cond:  # never race a worker's lazy pool creation
                    pool = self._ensure_kvpool()
                n = warmup_paged(self.params, self.heads, self.buckets,
                                 self.max_batch, pool,
                                 self._prefill_chunk, self.compute_dtype,
                                 self.moe, kernel=self._decode_kernel)
            else:
                n = warmup_buckets(self.params, self.heads, self.buckets,
                                   self.max_batch, self.compute_dtype,
                                   self.moe)
            for name, prog in self._programs.items():
                if name != "lm":  # LM compiled above against the live pool
                    n += prog.warmup()
            return n
        finally:
            self._warming = False

    def swap_model(self, program: str, model) -> None:
        """Atomically install new weights on a resident BucketProgram (the
        hot-update seam: same shapes keep the compiled programs serving —
        the swap is an operand change, never a recompile). Raises for an
        unknown program or one without a ``swap_model`` hook; on success
        records one ``ev="swap"`` event +
        ``marlin_serve_program_swaps_total{program}``."""
        prog = self._programs.get(program)
        if prog is None:
            raise ValueError(
                f"unknown program {program!r} (this engine serves "
                f"{sorted(self._programs)})")
        hook = getattr(prog, "swap_model", None)
        if hook is None:
            raise ValueError(
                f"program {program!r} has no swap_model hook")
        hook(model)
        self.metrics.record_swap(program)

    def pending(self) -> int:
        """Requests admitted but not yet retired (queued + in flight)."""
        return self._queue.count

    # ------------------------------------------------------- introspection

    def _health_info(self) -> dict:
        """The /healthz readiness payload for this engine: lifecycle state
        (``accepting`` while running), live slot rows, queue depth, and the
        worker heartbeat age (None until the worker's first iteration).
        Lock-free reads of GIL-atomic fields — the probe must never contend
        with the worker."""
        state = {"running": "accepting", "draining": "draining",
                 "freezing": "draining", "frozen": "draining",
                 "closing": "closed", "closed": "closed"}[self._state]
        hb = self._heartbeat
        return {
            "state": state,
            "live_slots": self._live_rows,
            "queue_depth": self._queue.count,
            "worker_started": self._started,
            "heartbeat_age_s": (round(time.monotonic() - hb, 3)
                                if hb is not None else None),
        }

    def _slo_payload(self) -> dict | None:
        """The ``GET /debug/slo`` scope payload for this engine: the SLO
        engine's evaluation (ticked on the probe, so a scrape always sees
        a fresh-enough verdict without any poller thread) plus the health
        block and paged-pool gauges the ops console renders as topology.
        None when no objectives are configured (the provider prunes)."""
        slo = self._slo
        if slo is None:
            return None
        try:
            slo.tick(self._clock())
            p = slo.payload()
        except Exception:  # pragma: no cover - probe must never 500
            return None
        p["health"] = self._health_info()
        m = self.metrics
        p["pages"] = {"total": m.pages_total, "used": m.pages_used,
                      "shared": m.pages_shared}
        p["shed_level"] = self._queue.shed_level
        p["shed_count"] = self._queue.shed_count
        return p

    def _prog_key(self, bucket) -> str:
        """The roofline-accounting key for this engine's programs at one
        bucket (cached — it sits on the per-step path). Paged programs key
        the page geometry in too (kvpool.paged_program_key)."""
        key = self._prog_keys.get(bucket)
        if key is None:
            if self.paged:
                key = paged_program_key(self.params, bucket, self.max_batch,
                                        self._page_len, self.compute_dtype,
                                        self._decode_kernel)
            else:
                key = bucket_program_key(self.params, bucket, self.max_batch,
                                         self.compute_dtype)
            self._prog_keys[bucket] = key
        return key

    def _calibrate_cost(self, request, pbucket, cost: int) -> int:
        """Measured-peak admission calibration (obs/memledger.py): scale
        the planner's per-bucket charge by the compiler-measured
        peak/planner ratio for this bucket's program key, so admission
        stops over-admitting by the 4-5x the slab arithmetic under-counts
        (AOT_MEMORY.json serve_buckets). LM only — one-shot programs
        price their actual padded device row; the ratio resolves once per
        bucket (live ProgramCosts first, the AOT table second, 1.0 when
        neither measured this exact program) and is cached."""
        if request.program != "lm":
            return cost
        ratio = self._calib_ratios.get(pbucket)
        if ratio is None:
            from .batcher import bucket_kv_bytes

            planner = bucket_kv_bytes(self.params, self.heads, pbucket,
                                      self.compute_dtype,
                                      batch=self.max_batch)
            programs = (("lm_prefill_paged", "lm_decode_paged")
                        if self.paged
                        else ("lm_prefill_slot", "lm_decode_rows"))
            ratio = memledger.admission_ratio(planner, programs,
                                              self._prog_key(pbucket))
            self._calib_ratios[pbucket] = ratio
        return int(cost * ratio) if ratio != 1.0 else cost

    def _ensure_kvpool(self) -> PagedKVPool:
        """The engine's one paged pool, built lazily (warmup or the first
        admission) and rebuilt zeroed after a recovery or slab loss."""
        pool = self._kvpool
        if pool is None:
            # analyze: single-writer — the pool pointer belongs to the live
            # scheduler generation; _recover/close swap it only after the
            # worker they superseded has stopped dispatching
            pool = self._kvpool = PagedKVPool(
                self.params, self.heads, self._num_pages, self._page_len,
                self.compute_dtype, self._prefix_cache)
            self.metrics.record_pages(pool.capacity, 0, 0)
            # account the slab in the process memory ledger: the free rides
            # every drop path (recovery, slab loss, terminal close), so a
            # rebuild re-registers the same name without double-counting
            led = memledger.get_ledger()
            led.free(f"kvpool:{self._name}", strict=False)
            led.register(f"kvpool:{self._name}",
                         self._num_pages * self._page_bytes, "kvpool",
                         owner=self._name)
        return pool

    def _record_pages(self, pool) -> None:
        st = pool.stats()
        self.metrics.record_pages(st["total"], st["used"], st["shared"])

    def _flight_dump(self, reason: str) -> None:
        """Dump the flight ring (never raises — rides failure paths)."""
        try:
            self.flight.dump(reason=reason)
        except Exception:
            pass

    def _finalize_obs(self) -> None:
        """Terminal observability flush (close/drain, idempotent): dump the
        flight ring and land the program-utilization snapshots
        (``kind="program"``/``ev="util"``) in the EventLog, then drop out
        of the /healthz registry — a terminated engine must not hold the
        process at 503."""
        if self._finalized:
            return
        self._finalized = True
        self._flight_dump("close")
        try:
            families = ["lm_decode_paged", "lm_prefill_paged",
                        "lm_decode_rows", "lm_prefill_slot"]
            families += [p.cost_program for n, p in self._programs.items()
                         if n != "lm" and p.cost_program]
            for prog in dict.fromkeys(families):
                perf.get_program_costs().emit(prog)
        except Exception:
            pass
        # a terminated engine must leave the memory ledger clean: sweep
        # everything it still owns (the KV slab, unconsumed migration
        # blobs) and land one attribution snapshot for the post-hoc report
        try:
            memledger.get_ledger().free_owner(self._name)
            memledger.emit_snapshot()
        except Exception:
            pass
        unregister_health_provider(self._name)
        unregister_kvpool_provider(self._name)
        unregister_slo_provider(self._name)
        if self._ts_collector is not None:
            get_registry().remove_collector(self._ts_collector)
            self._ts_collector = None
        self.metrics.attach_timeseries(None)

    def _join_worker(self) -> None:
        """Join until no worker generation will run again — a supervisor
        may swap in a fresh generation mid-join (crash during drain), or be
        a poll interval away from consuming a crash stash; returning after
        joining a dead predecessor would declare the engine closed with
        work still queued. Terminates because recovery is bounded: the
        supervisor's breaker (or the absence of a supervisor) guarantees a
        final generation."""
        if not self._started:
            return
        waited = 0.0
        while True:
            t = self._thread
            if t is self._abandoned:
                return  # a wedged generation the breaker gave up on: it
                # may never return from its device call, and everything it
                # held was already retired — joining would hang shutdown
            try:
                t.join()
            except RuntimeError:
                # a recovery publishes the fresh generation's thread under
                # the lock but starts it only after releasing it; joining
                # inside that window raises "cannot join thread before it
                # is started" — yield and re-join once the starter runs
                time.sleep(0.001)
                continue
            with self._cond:
                if self._thread is not t:
                    waited = 0.0
                    continue  # a recovery swapped in a new generation
                # stash pending + supervisor attached + a state it still
                # recovers in (check() skips closing/closed engines, so
                # waiting there would deadlock close())
                if (self._on_crash is not None and self._crash is not None
                        and self._state in ("running", "draining")):
                    recovery_pending = True  # stashed, not yet respawned
                else:
                    return
            if recovery_pending:
                if waited >= 5.0:
                    # an attached supervisor whose monitor never consumed
                    # the stash (e.g. Supervisor(start=False)): waiting
                    # forever would hang shutdown — return and let the
                    # caller's _fail_crash_stash / leftover paths resolve
                    # everything the dead worker held
                    return
                time.sleep(0.005)  # let the supervisor consume the stash
                waited += 0.005

    def _fail_crash_stash(self, reason: str) -> None:
        """Retire whatever a crashed, never-recovered worker was holding
        (drain/close with no supervisor attached, or a breaker-opened
        engine) — the shutdown path must strand nothing."""
        with self._cond:
            crash = self._crash
            self._crash = None
        if crash is None:
            return
        for e in crash[1]:
            if not e.handle.done():
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason=reason))

    def drain(self) -> None:
        """Graceful stop: no new admissions (post-drain submits resolve
        ``shutting_down``), but everything already accepted — queued and in
        flight — completes. Partial batches dispatch immediately. Terminal:
        the worker exits and is joined before this returns."""
        self._queue.close("engine draining (no new admissions)")
        self.start()  # a never-started engine still owes queued results
        with self._cond:
            if self._state == "running":
                self._state = "draining"
            self._cond.notify_all()
        self._join_worker()
        self._fail_mig_inbox("engine drained before servicing migration")
        self._fail_crash_stash("serving worker died while draining")
        with self._cond:
            self._state = "closed"
            leftovers = self._former.take_all()
        for e in leftovers:
            # only reachable when the last worker generation died with no
            # supervisor left to respawn one — queued work still resolves
            self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                   reason="serving worker lost while "
                                          "draining"))
        self._finalize_obs()

    def close(self) -> None:
        """Fast stop: no new admissions, the batch in flight completes, and
        every still-queued request is retired with a clean
        ``shutting_down`` Result (never silently dropped). Idempotent."""
        self._queue.close("engine shutting down")
        with self._cond:
            if self._state == "closed":
                return
            self._state = "closing"
            leftovers = self._former.take_all()
            self._cond.notify_all()
        for e in leftovers:
            self._retire(e, Result(
                e.request.rid, STATUS_SHUTTING_DOWN,
                reason="engine closed before this request was scheduled"))
        self._join_worker()
        self._fail_mig_inbox("engine closed before servicing migration")
        self._fail_crash_stash("serving worker died; engine closed before "
                               "recovery")
        with self._cond:
            self._state = "closed"
        self._finalize_obs()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- admission

    def submit(self, request: Request) -> ResultHandle:
        """Admit one request. Always returns a handle that will carry exactly
        one Result; overload / no-bucket / past-deadline submissions resolve
        immediately with ``rejected`` / ``expired`` status and a reason.

        Opens the request's span (a child of the caller's active span when
        there is one, else a fresh trace root), so every record the request
        ever produces — here and on the worker thread — shares one
        ``trace_id``."""
        ctx = obs_trace.child_of_current(f"serve.request.{request.rid}")
        with obs_trace.use(ctx):
            return self._submit(request, ctx)

    def _submit(self, request: Request, ctx) -> ResultHandle:
        faults.fire("serve.enqueue", path=str(request.rid))
        handle = ResultHandle(request)
        now = self._clock()
        prog = self._programs.get(request.program)
        if prog is None:
            return self._refuse(handle, STATUS_REJECTED, (
                f"unknown program {request.program!r} (this engine serves "
                f"{sorted(self._programs)})"))
        why = prog.validate(request)
        if why is not None:
            return self._refuse(handle, STATUS_REJECTED, why)
        pbucket = prog.pick_bucket(request)
        if pbucket is None:
            return self._refuse(handle, STATUS_REJECTED,
                                prog.refuse_no_bucket(request))
        # former/pool key: LM keeps its bare (prompt, steps) tuple (the
        # pre-refactor keys — events, pools, and migration manifests are
        # unchanged); other programs namespace theirs under their name
        bucket = (pbucket if request.program == "lm"
                  else (prog.name,) + tuple(pbucket))
        # resolve the relative/default deadline to an absolute engine-clock
        # one, ONCE — a router failover or worker restart must not hand the
        # request a fresh budget
        if request.deadline is None:
            rel = request.deadline_s
            if rel is None:
                rel = get_config().serve_default_deadline_s
            if rel is not None:
                request.deadline = now + float(rel)
                request.deadline_s = None
        if request.deadline is not None and request.deadline <= now:
            return self._refuse(handle, STATUS_EXPIRED, (
                f"deadline {request.deadline} already passed at submission "
                f"(now {now})"))
        # deadline-aware admission: with service history (EWMA of ok
        # per-request seconds), a request whose projected completion behind
        # the current queue already overshoots its deadline is refused NOW —
        # cheaper for everyone than decoding it into a guaranteed expiry
        if request.deadline is not None and self._service_ewma > 0.0:
            projected = now + self._service_ewma * (
                1.0 + self._queue.count / self.max_batch)
            if projected > request.deadline:
                return self._refuse(handle, STATUS_REJECTED, (
                    f"deadline unmeetable: projected completion {projected:.3f}"
                    f" > deadline {request.deadline:.3f} at queue depth "
                    f"{self._queue.count} (service est "
                    f"{self._service_ewma:.3f}s)"))
        # the program prices its own resource units (LM: actual KV pages or
        # the slab worst case; one-shot programs: their padded device row)
        # against the one shared HBM admission budget; a capacity refusal
        # (e.g. more pages than the pool holds) raises the reason
        try:
            cost = prog.admission_cost(request, pbucket)
        except ValueError as exc:
            return self._refuse(handle, STATUS_REJECTED, str(exc))
        if get_config().serve_admission_calibration:
            cost = self._calibrate_cost(request, pbucket, cost)
        reason = self._queue.try_admit(
            cost, priority=request.priority,
            deadline_slack_s=(request.deadline - now
                              if request.deadline is not None else None))
        if reason is not None:
            # a drain/close-shut gate is a deterministic shutting_down
            # Result (the caller can failover/retry elsewhere); overload
            # stays a rejection with the backpressure reason. Matching the
            # RETURNED reason (the close reason never changes once set)
            # keeps a "queue full" verdict that raced a concurrent drain
            # labeled as the backpressure it was
            if reason == self._queue.closed_reason:
                return self._refuse(handle, STATUS_SHUTTING_DOWN, reason)
            if (self._slo is not None
                    and reason.startswith(SHED_REASON_PREFIX)):
                self._slo.record_shed()
            return self._refuse(handle, STATUS_REJECTED, reason)
        entry = _Entry(request, handle, bucket, cost, now, trace=ctx)
        with self._cond:
            if self._state != "running":
                admitted = False
            else:
                self._former.add(entry)
                if self._idle:
                    # an IDLE worker's heartbeat is legitimately old (it
                    # blocks in cond.wait): restart the watchdog window at
                    # admission so the wakeup isn't a false positive. A
                    # busy (possibly wedged) worker is NOT idle — traffic
                    # must never keep refreshing a dead worker's pulse
                    self._heartbeat = time.monotonic()
                self._cond.notify_all()
                admitted = True
        if not admitted:  # raced with drain()/close(): resolve, don't strand
            self._queue.release(cost)
            return self._refuse(handle, STATUS_SHUTTING_DOWN,
                                "engine is shutting down")
        self.metrics.record_enqueue(request.rid, bucket, self._queue.count,
                                    program=request.program)
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    def _refuse(self, handle, status: str, reason: str) -> ResultHandle:
        handle._set(Result(handle.request.rid, status, reason=reason))
        if status == STATUS_REJECTED:
            self.metrics.record_reject(handle.request.rid, reason,
                                       program=handle.request.program)
        else:
            self.metrics.record_result(handle.request.rid, status,
                                       program=handle.request.program)
        return handle

    # ----------------------------------------------------------- worker loop

    def _run(self, gen: int = 0) -> None:
        if self.paged:
            self._run_paged(gen)
        else:
            self._run_rowlevel(gen)

    def _crash_handler(self, exc: BaseException, held: list,
                       gen: int) -> bool:
        """A worker generation is dying with ``held`` entries in hand.
        Supervised (``_on_crash`` installed, engine still serving): stash
        the undone entries for :meth:`_recover`, kick the supervisor, and
        return True — the worker exits quietly and the engine KEEPS
        accepting (requests queue up behind the restart). Unsupervised:
        the legacy contract — fail everything held plus the queued backlog
        with ``error`` Results so no submitter is ever stranded, and
        return False so the thread log still sees the exception. A
        SUPERSEDED generation dying late exits quietly without stashing —
        its entries were already requeued or failed by the recovery that
        superseded it, and a spurious stash would restart (and burn a
        retry attempt of) the healthy current generation."""
        cb = leftovers = None
        with self._cond:
            if self._gen != gen:
                return True  # stale straggler: recovery already ran
            undone = []
            seen = set()
            for e in held:
                if id(e) in seen or e.handle.done() or e.superseded:
                    continue
                seen.add(id(e))
                undone.append(e)
            # "freezing" counts as supervised even though the supervisor
            # idles there: freeze_rows() itself consumes the stash (the
            # crashed rows ride the migration fallback/retry path) — an
            # unsupervised fail-everything here would break exactly-once
            # for rows the freeze is about to hand to another replica
            supervised = ((self._on_crash is not None
                           and self._state in ("running", "draining"))
                          or self._state == "freezing")
            if supervised:
                self._crash = (exc, undone)
                cb = self._on_crash
            else:
                leftovers = self._former.take_all()
                self._state = "closing"
            self._claimed = []
        self._flight_dump("worker-died")
        if supervised:
            try:
                cb()
            except Exception:  # the supervisor's poll loop still catches it
                pass
            return True
        for e in leftovers + undone:
            if not e.handle.done():
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason="serving worker died"))
        return False

    def _retire(self, entry: _Entry, result: Result) -> None:
        if entry.superseded:
            return  # a retried twin owns this request (and its budget) now
        if entry.attempt > 1:
            result.metrics.setdefault("attempt", entry.attempt)
        try:
            entry.handle._set(result)
        except RuntimeError:
            # lost the exactly-once race to a stale worker generation's
            # twin — the winner released the budget and recorded the result
            return
        self._queue.release(entry.cost)
        if result.status == STATUS_OK:
            total = result.metrics.get("total_s")
            if total is not None:
                # EWMA of per-request SERVICE time — total minus queue wait
                # (the deadline-admission projection multiplies this by the
                # queue depth, so feeding end-to-end total_s would count
                # queueing twice and over-reject meetable deadlines, and a
                # single post-recovery straggler would poison the estimate)
                svc = max(total - (result.metrics.get("queue_s") or 0.0),
                          0.0)
                # analyze: single-writer — advisory latency estimate for
                # deadline admission; a lost EWMA update skews one sample,
                # never correctness, and taking the engine lock on the
                # retire path would order it against the submit path
                self._service_ewma = (svc if self._service_ewma == 0.0
                                      else 0.8 * self._service_ewma
                                      + 0.2 * svc)
        # re-activate the request's span on whichever thread retires it, so
        # the result record joins the request's trace
        with obs_trace.use(entry.trace):
            self.metrics.record_result(
                result.rid, result.status,
                bucket=result.metrics.get("bucket"),
                queue_s=result.metrics.get("queue_s"),
                total_s=result.metrics.get("total_s"),
                ttft_s=result.metrics.get("ttft_s"),
                attempt=entry.attempt,
                pages=result.metrics.get("pages"),
                shared_pages=result.metrics.get("shared_pages"),
                program=entry.request.program)
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)

    # ------------------------------------------------- row-level scheduler

    def _run_rowlevel(self, gen: int) -> None:
        """The slot-step loop: each iteration refills freed slots from the
        queue (prefill-on-admit), retires finished/expired rows, and runs
        one decode step per bucket with live rows. ``pools`` maps bucket ->
        SlotPool and persists across iterations — the KV slab never leaves
        the device between steps. ``self._pools``/``self._claimed`` mirror
        the worker's hands so a supervisor recovering a STUCK generation
        (watchdog timeout — the thread is alive but unreachable) can still
        find every in-flight entry to requeue."""
        pools: dict[tuple, object] = {}
        with self._cond:
            if self._gen != gen:
                return  # superseded before the first iteration: a late-
                # starting thread must not clobber its successor's mirrors
            self._pools = pools
        claimed: list[_Entry] = []
        try:
            while True:
                if self._gen == gen:  # a superseded straggler must never
                    # analyze: single-writer — generation-guarded monotonic
                    # stamp; floats assign atomically under the GIL and the
                    # watchdog tolerates any interleaving
                    self._heartbeat = time.monotonic()  # fake a live pulse
                if self._slo is not None:
                    # rate-limited internally (serve_slo_eval_interval_s):
                    # per-iteration cost is one float compare
                    self._slo.tick(self._clock())
                faults.fire("serve.worker_crash",
                            path=threading.current_thread().name)
                claimed = []
                with self._cond:
                    while True:
                        if self._gen != gen:
                            return  # superseded by a recovery
                        if self._state == "closing":
                            # the live slots are the work in flight: finish
                            # them (close() already emptied the former)
                            if not any(p.live_slots()
                                       for p in pools.values()):
                                return
                            break
                        draining = self._state == "draining"
                        claimed = self._claim_rowlevel(pools)
                        if claimed or any(p.live_slots()
                                          for p in pools.values()):
                            break
                        if draining:
                            return  # nothing queued, nothing live
                        # no max_wait ripening in row-level mode: wait for
                        # a submit/drain/close notify (poll-capped under an
                        # injected clock, as in the gang loop)
                        self._idle = True
                        self._cond.wait(None if self._real_clock
                                        else _POLL_CAP_S)
                        self._idle = False
                        if self._gen == gen:
                            self._heartbeat = time.monotonic()
                    self._claimed = claimed
                prog_claimed = [e for e in claimed
                                if self._is_program_bucket(e.bucket)]
                lm_claimed = [e for e in claimed
                              if not self._is_program_bucket(e.bucket)]
                self._admit_rowlevel(pools, lm_claimed)
                self._admit_program_rows(pools, prog_claimed)
                claimed = []
                with self._cond:
                    if self._gen == gen:  # never clobber a successor's
                        self._claimed = []  # claimed mirror
                self._step_rowlevel(pools)
                self._step_program_rows(pools)
        except BaseException as exc:  # worker death: recover or fail held
            live = [p.entries[i] for p in pools.values()
                    for i in p.live_slots()]
            if self._crash_handler(exc, claimed + live, gen):
                return
            raise

    @staticmethod
    def _is_program_bucket(bucket) -> bool:
        """True for a namespaced (name, *bucket) program key — the one
        type test that routes a former bucket to the program lane (LM
        buckets are bare (prompt, steps) int tuples)."""
        return (isinstance(bucket, tuple) and bool(bucket)
                and isinstance(bucket[0], str))

    def _claim_rowlevel(self, pools) -> list[_Entry]:
        """Claim queued entries for free slots, per bucket (called under the
        engine lock; prefill happens outside it). Program buckets claim up
        to their program's padded width instead of the LM max_batch."""
        claimed = []
        for bucket in self._former.pending_buckets():
            pool = pools.get(bucket)
            if pool is not None:
                free = len(pool.free_slots())
            elif self._is_program_bucket(bucket):
                prog = self._programs.get(bucket[0])
                # an unregistered program's entries (a misrouted adopt)
                # still claim: _admit_program_rows retires them cleanly
                free = prog.width if prog is not None else self.max_batch
            else:
                free = self.max_batch
            if free:
                claimed.extend(self._former.take_for_bucket(bucket, free))
        return claimed

    def _admit_program_rows(self, pools, claimed) -> None:
        """Bind claimed program entries to :class:`ProgramRowSet` slots —
        host-side only; a one-shot program's device work happens in
        :meth:`_step_program_rows`. Dispatch order matches the paged
        admit: priority first, then arrival."""
        if not claimed:
            return
        claimed = sorted(claimed,
                         key=lambda e: (-e.request.priority, e.request.rid))
        for e in claimed:
            with obs_trace.use(e.trace):
                now = self._clock()
                r = e.request
                prog = self._programs.get(e.bucket[0])
                if prog is None:
                    # a misrouted adopt: the target fleet lacks this
                    # program — resolve, never strand
                    self._retire(e, Result(
                        r.rid, STATUS_ERROR,
                        reason=f"program {e.bucket[0]!r} is not registered "
                               f"on this engine",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                if r.deadline is not None and r.deadline <= now:
                    self._retire(e, Result(
                        r.rid, STATUS_EXPIRED,
                        reason=f"deadline {r.deadline} passed before "
                               f"dispatch (dispatched at {now})",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                e.queue_s = now - e.enq_t
                rows = pools.get(e.bucket)
                if rows is None:
                    rows = pools[e.bucket] = ProgramRowSet(e.bucket,
                                                           prog.width)
                rows.assign(rows.free_slots()[0], e)
        self._live_rows = sum(len(g.live_slots()) for g in pools.values())

    def _step_program_rows(self, pools) -> None:
        """One batched compiled call per program bucket with live rows:
        expire stale rows, pad the rest to the program's smallest fitting
        width, execute, retire everything with its value — the one-shot
        analog of a decode step, interleaved with LM prefill chunks and
        decode steps in the same worker iteration."""
        for bucket, rows in list(pools.items()):
            if not isinstance(rows, ProgramRowSet):
                continue
            prog = self._programs[bucket[0]]
            now = self._clock()
            for i in rows.occupied_slots():
                dl = rows.entries[i].request.deadline
                if dl is not None and dl <= now:
                    self._retire_program_row(
                        rows, i, STATUS_EXPIRED, now,
                        reason=f"deadline {dl} passed before the program "
                               f"step (now {now})")
            live = rows.occupied_slots()
            if not live:
                continue
            entries = [rows.entries[i] for i in live]
            pkey = prog.program_key(bucket[1:],
                                    prog.step_width(len(entries)))
            try:
                faults.fire("serve.program_step",
                            path=f"{bucket[0]}-{len(entries)}")
                t0 = time.perf_counter()
                values = prog.step(bucket[1:],
                                   [e.request for e in entries])
            except Exception as exc:
                self._fail_program_rows(rows, exc)
                continue
            wall = time.perf_counter() - t0
            self.metrics.record_step(
                bucket, len(live), rows.width, wall, program_key=pkey,
                program=prog.cost_program, label=bucket[0])
            self.flight.record(
                "step", bucket=list(bucket), rows=len(live), seconds=wall,
                queue_depth=self._queue.count, compiles=_compile_count())
            now = self._clock()
            for i, val in zip(live, values):
                self._retire_program_row(rows, i, STATUS_OK, now, value=val)
        self._live_rows = sum(len(g.live_slots()) for g in pools.values())

    def _retire_program_row(self, rows, slot: int, status: str, now: float,
                            value=None, reason: str = "") -> None:
        """Retire one program row and free its slot — the only path a
        program row leaves its rowset by (the exactly-once release runs in
        :meth:`_retire` as for every other row). A one-shot answer IS the
        first output, so ``ttft_s`` equals ``total_s``."""
        e = rows.entries[slot]
        metrics = {"bucket": rows.bucket, "slot": slot, "queue_s": e.queue_s,
                   "ttft_s": now - e.enq_t, "total_s": now - e.enq_t}
        if status == STATUS_OK:
            result = Result(e.request.rid, STATUS_OK, value=value,
                            metrics=metrics)
        else:
            result = Result(e.request.rid, status, reason=reason,
                            metrics=metrics)
        rows.release(slot)
        self._retire(e, result)

    def _fail_program_rows(self, rows, exc: Exception) -> None:
        """A program step died: rows with attempt budget left requeue for
        a transparent retry; the rest fail with error Results — only this
        bucket's rows are touched (a program step holds no donated slab,
        so there is nothing to escalate)."""
        reason = f"program step failed: {type(exc).__name__}: {exc}"
        self.flight.record("program_fault", bucket=list(rows.bucket),
                           rows=len(rows.occupied_slots()), error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        now = self._clock()
        for i in rows.occupied_slots():
            e = rows.entries[i]
            if e.attempts_left():
                rows.release(i)
                self._requeue(e, reason)
            else:
                self._retire_program_row(rows, i, STATUS_ERROR, now,
                                         reason=reason)
        self._flight_dump("program-step-failed")

    def _admit_rowlevel(self, pools, claimed) -> None:
        """Prefill each claimed entry into a free slot of its bucket's pool
        (created lazily). The first token lands here — the row's TTFT."""
        from .batcher import SlotPool
        from ..models.transformer import lm_prefill_slot

        for e in claimed:
            # the worker runs every request's admission inside that
            # request's span: its prefill record — and any compile the
            # bridge observes during it — joins the request's trace
            with obs_trace.use(e.trace):
                now = self._clock()
                r = e.request
                dl = r.deadline
                p, s = e.bucket
                if dl is not None and dl <= now:
                    self._retire(e, Result(
                        r.rid, STATUS_EXPIRED,
                        reason=f"deadline {dl} passed before dispatch "
                               f"(dispatched at {now})",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                e.queue_s = now - e.enq_t
                try:
                    faults.fire("serve.step", path=f"bucket-{p}x{s}")
                    pool = pools.get(e.bucket)
                    if pool is None:
                        pool = pools[e.bucket] = SlotPool(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype)
                        # no-warmup path: the bucket's cost model still
                        # lands with its first (lazy) compile
                        capture_bucket_costs(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype, self.moe,
                            key=self._prog_key(e.bucket))
                    slot = pool.free_slots()[0]
                    prompt = np.zeros((p,), np.int32)
                    n = r.prompt.shape[0]
                    prompt[:n] = r.prompt
                    t0 = time.perf_counter()
                    caches, tokens, first = lm_prefill_slot(
                        self.params, pool.caches, pool.tokens, slot, prompt,
                        n, heads=self.heads, max_len=p + s, seed=r.seed,
                        temperature=r.temperature, top_p=r.top_p,
                        top_k=r.top_k, compute_dtype=self.compute_dtype,
                        moe=self.moe)
                    first = int(first)  # device sync: the first token exists
                    wall = time.perf_counter() - t0
                except Exception as exc:
                    self._admit_failure(pools, e, exc)
                    continue
                pool.caches, pool.tokens = caches, tokens
                pool.assign(slot, e)
                pool.ttft_s[slot] = self._clock() - e.enq_t
                self.metrics.record_prefill(
                    e.bucket, wall, rid=r.rid,
                    program_key=self._prog_key(e.bucket))
                self.flight.record(
                    "prefill", bucket=[p, s], slot=slot, rid=r.rid,
                    seconds=wall, queue_depth=self._queue.count,
                    compiles=_compile_count())
                if r.steps == 1 or (r.eos is not None and first == r.eos):
                    self._retire_row(pool, slot, STATUS_OK, self._clock())
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _step_rowlevel(self, pools) -> None:
        """Retire expired live rows, then run ONE decode step per bucket
        with live rows and retire rows that finished on it. All buckets'
        step programs are DISPATCHED before any result is awaited — JAX
        dispatch is async, so bucket B's device work overlaps the host
        round-trip for bucket A instead of serializing behind it."""
        from ..models.transformer import lm_decode_rows

        launched = []
        for bucket, pool in list(pools.items()):
            if isinstance(pool, ProgramRowSet):
                continue  # the program lane steps in _step_program_rows
            now = self._clock()
            for i in pool.live_slots():
                dl = pool.entries[i].request.deadline
                if dl is not None and dl <= now:
                    self._retire_row(
                        pool, i, STATUS_EXPIRED, now,
                        reason=f"deadline {dl} passed mid-decode "
                               f"(now {now})")
            live = pool.live_slots()
            if not live:
                continue
            p, s = bucket
            try:
                faults.fire("serve.decode_step", path=f"bucket-{p}x{s}")
                t0 = time.perf_counter()
                caches, tokens, nxt = lm_decode_rows(
                    self.params, pool.caches, pool.tokens, pool.positions,
                    pool.steps_done, pool.seeds, pool.temperature,
                    pool.top_p, pool.top_k, heads=self.heads,
                    max_len=pool.max_len, compute_dtype=self.compute_dtype,
                    moe=self.moe)
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            pool.caches, pool.tokens = caches, tokens
            launched.append((bucket, pool, live, t0, nxt))
        for bucket, pool, live, t0, nxt in launched:
            try:
                # analyze: ignore[host-sync] — THE one intentional sync per
                # decode step: the host must see the emitted tokens to
                # retire rows (all dispatches above launched async first)
                nxt = np.asarray(nxt)  # sync; the per-row emitted tokens
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            wall = time.perf_counter() - t0
            self.metrics.record_step(bucket, len(live), self.max_batch, wall,
                                     program_key=self._prog_key(bucket))
            self.flight.record(
                "step", bucket=list(bucket), rows=len(live),
                seconds=wall, queue_depth=self._queue.count,
                compiles=_compile_count())
            now = self._clock()
            host_tokens = None  # one slab fetch shared by this step's retirees
            for i in live:
                pool.positions[i] += 1
                pool.steps_done[i] += 1
                r = pool.entries[i].request
                if ((r.eos is not None and int(nxt[i]) == r.eos)
                        # analyze: ignore[host-sync] — host numpy bookkeeping
                        or int(pool.steps_done[i]) >= r.steps):
                    if host_tokens is None:
                        # analyze: ignore[host-sync] — one slab fetch
                        # amortized over every row this step retires
                        host_tokens = np.asarray(pool.tokens)
                    self._retire_row(pool, i, STATUS_OK, now,
                                     host_tokens=host_tokens)
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _retire_row(self, pool, slot: int, status: str, now: float,
                    reason: str = "", host_tokens=None) -> None:
        """Retire one slot's row and free the slot — the ONLY path a live
        slot leaves the pool by, so every terminal status releases the
        admission budget exactly once. ``host_tokens`` lets a step that
        retires several rows share ONE slab fetch (the transfer is whole-slab
        either way: a per-slot device gather would compile one tiny
        executable per static slot index and break the
        zero-compiles-under-traffic guarantee)."""
        e = pool.entries[slot]
        metrics = {"bucket": pool.bucket, "slot": slot,
                   "queue_s": e.queue_s, "ttft_s": pool.ttft_s[slot],
                   "total_s": now - e.enq_t}
        if status == STATUS_OK:
            n = int(pool.lengths[slot])
            emitted = int(pool.steps_done[slot])
            if host_tokens is None:
                host_tokens = np.asarray(pool.tokens)
            toks = host_tokens[slot, : n + emitted].copy()
            result = Result(e.request.rid, STATUS_OK, tokens=toks,
                            metrics=metrics)
        else:
            result = Result(e.request.rid, status, reason=reason,
                            metrics=metrics)
        pool.release(slot)
        self._retire(e, result)

    def _requeue(self, entry: _Entry, reason: str) -> None:
        """Park a failed attempt back in the former for its next attempt
        (the caller checked ``attempts_left``). The admission reservation
        is CARRIED — never released, never re-charged — so a parked retry
        holds exactly its one slot of the queue depth and KV HBM budget.
        On a shutting-down engine the retry would never be claimed, so it
        retires with the failure instead of stranding."""
        twin = entry.retry()
        with self._cond:
            requeued = self._state in ("running", "draining")
            if requeued:
                self._former.add(twin)
                self._cond.notify_all()
        if not requeued:
            self._retire(twin, Result(
                twin.request.rid, STATUS_ERROR,
                reason=f"{reason} (engine shutting down before retry)"))
            return
        with obs_trace.use(entry.trace):
            self.metrics.record_retry(entry.request.rid, twin.attempt,
                                      entry.request.max_attempts, reason)

    def _fail_pool(self, pools, bucket, exc: Exception) -> None:
        """A decode step died: rows with attempt budget left requeue for a
        transparent retry; the rest fail with error Results. Either way
        ONLY that step's live rows are touched and the slot pool stays
        consistent (slots freed, budget accounted exactly once). If the
        failed call consumed the donated slab (a genuine post-dispatch
        failure, not an injected fault raised before launch), drop the pool
        — it is rebuilt zeroed on the next admission."""
        pool = pools[bucket]
        reason = f"decode step failed: {type(exc).__name__}: {exc}"
        if memledger.is_oom_error(exc):
            memledger.dump_oom_forensics(reason)
        self.flight.record("decode_fault", bucket=list(bucket),
                           rows=len(pool.live_slots()), error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        now = self._clock()
        for i in pool.live_slots():
            e = pool.entries[i]
            if e.attempts_left():
                pool.release(i)
                self._requeue(e, reason)
            else:
                self._retire_row(pool, i, STATUS_ERROR, now, reason=reason)
        if self._slab_lost(pool):
            pools.pop(bucket)
        # the black box lands NOW, while the final iterations are still in
        # the ring — the post-mortem for exactly this failure class
        self._flight_dump("decode-step-failed")

    def _admit_failure(self, pools, entry: _Entry, exc: Exception) -> None:
        """A prefill died: the entry being admitted retries within its
        attempt budget, else gets an error Result; co-resident live rows
        survive unless the failed call consumed the donated slab, in which
        case they fail/retry too and the pool is dropped."""
        now = self._clock()
        reason = f"prefill failed: {type(exc).__name__}: {exc}"
        if memledger.is_oom_error(exc):
            memledger.dump_oom_forensics(reason)
        if entry.attempts_left():
            self._requeue(entry, reason)
        else:
            self._retire(entry, Result(
                entry.request.rid, STATUS_ERROR, reason=reason,
                metrics={"bucket": entry.bucket, "queue_s": entry.queue_s,
                         "total_s": now - entry.enq_t}))
        self.flight.record("prefill_fault", bucket=list(entry.bucket),
                           rid=entry.request.rid, error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        pool = pools.get(entry.bucket)
        if pool is not None and self._slab_lost(pool):
            lost = f"slab lost to a failed prefill: {reason}"
            for i in pool.live_slots():
                e = pool.entries[i]
                if e.attempts_left():
                    pool.release(i)
                    self._requeue(e, lost)
                else:
                    self._retire_row(pool, i, STATUS_ERROR, now, reason=lost)
            pools.pop(entry.bucket)
        self._flight_dump("prefill-failed")

    # ------------------------------------------------- supervised recovery

    def attach_supervisor(self, on_crash) -> None:
        """Install the supervisor's crash kick: while set, a dying worker
        stashes its undone entries for :meth:`_recover` instead of failing
        them, and calls ``on_crash()`` so recovery starts promptly."""
        self._on_crash = on_crash

    def detach_supervisor(self) -> None:
        self._on_crash = None

    def _recover(self, reason: str, respawn: bool = True) -> dict:
        """Recover from a dead or stuck worker generation: supersede it
        (``_gen`` bump — a stale thread exits at its next check and can
        never retire a superseded entry), requeue every undone in-flight
        entry within its attempt budget (the rest fail with ``error``),
        drop the slot pools — the slab state died with the worker; pools
        rebuild zeroed on the next admission, the PR 4 ``is_deleted``
        pool-rebuild path generalized — and spawn a fresh worker thread.
        Queued (former) entries are untouched: they were never in flight.
        ``respawn=False`` is the breaker's terminal path: supersede and
        fail everything held, mark the old thread abandoned (it may be
        wedged in a device call forever — shutdown must not join it), and
        spawn nothing. Returns counts for the supervisor's EventLog
        record."""
        failed, twins = [], []
        with self._cond:
            self._gen += 1
            gen = self._gen
            alive = respawn and self._state in ("running", "draining")
            if self._crash is not None:
                stash = list(self._crash[1])
                self._crash = None
            else:
                # stuck path: steal what the stale (still-alive) worker
                # holds — its pools/claimed mirrors. The straggler
                # mutates pool.entries WITHOUT this lock, so snapshot each
                # list and skip holes rather than indexing live_slots()
                # (an entry it retires concurrently shows up handle-done
                # below and is skipped; one it frees mid-scan must not
                # crash the recovery)
                stash = [e for p in self._pools.values()
                         for e in list(p.entries) if e is not None]
                stash += list(self._claimed)
            self._pools = {}
            self._claimed = []
            # the paged pool's slab/block-table/prefix-cache state died
            # with the worker: drop it wholesale; it rebuilds zeroed on
            # the fresh generation's first admission (page-unit admission
            # reservations ride the requeued twins, never re-charged)
            if self._kvpool is not None:
                memledger.get_ledger().free(f"kvpool:{self._name}",
                                            strict=False)
            self._kvpool = None
            seen = set()
            for e in stash:
                if id(e) in seen or e.handle.done() or e.superseded:
                    continue
                seen.add(id(e))
                if alive and e.attempts_left():
                    twin = e.retry()
                    self._former.add(twin)
                    twins.append(twin)
                else:
                    failed.append(e)
            if alive:
                self._thread = self._make_thread(gen)
            elif not respawn:
                self._abandoned = self._thread
            started = self._started
            # grant the fresh generation a full watchdog window: without
            # this the stale generation's last stamp re-trips the watchdog
            # before the new worker's first iteration, and repeated
            # recoveries burn the attempt budget on a worker that never got
            # to run
            self._heartbeat = time.monotonic()
            self._cond.notify_all()
        for e in failed:
            self._retire(e, Result(
                e.request.rid, STATUS_ERROR,
                reason=f"worker lost and attempt budget exhausted: "
                       f"{reason}"))
        for t in twins:
            with obs_trace.use(t.trace):
                self.metrics.record_retry(t.request.rid, t.attempt,
                                          t.request.max_attempts, reason)
        # analyze: single-writer — a progress gauge for the watchdog, owned
        # by the live scheduler generation; _recover zeroes it only after
        # the generation it superseded stopped (int stores are atomic)
        self._live_rows = 0
        if alive and started:
            self._thread.start()
        return {"gen": gen, "requeued": len(twins), "failed": len(failed)}

    @staticmethod
    def _slab_lost(pool) -> bool:
        """True when a failed donated call consumed the pool's arrays (the
        backends that implement donation delete the inputs on dispatch;
        injected faults raise before the call and never trip this)."""
        deleted = getattr(pool.tokens, "is_deleted", None)
        return bool(deleted and deleted())

    # ------------------------------------------------ cross-engine migration

    def freeze_rows(self) -> dict | None:
        """Pause this engine at a step boundary and take ownership of every
        resident row for migration: admission closes, the worker parks at
        its next iteration top (state ``freezing`` — pools left intact),
        and the caller thread exports each row's KV pages + cursors into a
        CRC-framed host blob (:meth:`PagedKVPool.export_rows`).

        Returns ``{"engine", "blob", "entries", "queued", "fallback"}``:
        ``entries`` maps rid → the live in-process :class:`_Entry` (handle
        + admission reservation — both travel with the row, the blob only
        carries device/cursor state); ``queued`` is the former backlog
        (never started — moved wholesale, no retry twin); ``fallback`` is
        rows that could not export (a ``serve.migrate`` export fault, or a
        worker crash mid-freeze — the pool is not trusted after one) and
        must ride the PR 7 retry path. Returns None when the engine cannot
        freeze (not paged, or already terminal) — the caller falls back to
        a plain drain. Terminal either way once it returns a dict: the
        worker has exited and the router closes the engine next."""
        if not self.paged:
            return None
        self._queue.close("engine freezing for migration")
        with self._cond:
            if self._state not in ("running", "draining"):
                return None
            self._state = "freezing"
            self._cond.notify_all()
        self._join_worker()
        self._fail_mig_inbox("engine froze for migration")
        with self._cond:
            crash = self._crash
            self._crash = None
            pools = dict(self._pools)
            pool = self._kvpool
            queued = self._former.take_all()
        entries: dict = {}
        rows: list[dict] = []
        fallback: list = []
        seen: set[int] = set()

        def _viable(e) -> bool:
            if (e is None or id(e) in seen or e.superseded
                    or e.handle.done()):
                return False
            seen.add(id(e))
            return True

        if crash is not None:
            # the worker died mid-freeze: the pool is not trusted —
            # export nothing, every stashed row rides the retry fallback.
            # This is also how a dead generation's in-flight export is
            # invalidated: its rows become fresh-attempt twins, and the
            # stale export's entries (superseded by those twins) are
            # skipped at adopt time
            for e in crash[1]:
                if _viable(e):
                    fallback.append(e)
        else:
            for bucket, group in pools.items():
                if isinstance(group, ProgramRowSet):
                    # one-shot program rows have no KV state to export: the
                    # program's freeze hook may veto, otherwise they ride
                    # the fallback lane and re-execute on the target
                    # (exactly-once is the handle's, not the row's)
                    prog = self._programs.get(bucket[0])
                    for slot in group.occupied_slots():
                        e = group.entries[slot]
                        if not _viable(e):
                            continue
                        if prog is not None:
                            prog.freeze(e)
                        fallback.append(e)
                    continue
                for slot in group.occupied_slots():
                    e = group.entries[slot]
                    if not _viable(e):
                        continue
                    try:
                        faults.fire(
                            "serve.migrate",
                            path=f"export:{e.request.rid}@{self._name}")
                        rows.append(self._export_row(group, bucket, slot))
                        entries[e.request.rid] = e
                    except Exception:
                        fallback.append(e)
        blob = None
        if rows and pool is not None:
            try:
                blob = pool.export_rows(rows)
                self.metrics.record_migration("export", len(rows))
            except Exception:
                # the blob never materialized: every exported row falls
                # back to the retry path (its source pages die with this
                # engine — nothing leaks into the blob's absence)
                fallback.extend(entries.values())
                entries = {}
        token = None
        if blob is not None:
            # the frozen blob is migration bytes in flight: credit it to
            # this engine until the adopt side consumes it (adopt_rows
            # transfers ownership to the target, then debits exactly once;
            # a never-adopted blob is swept by _finalize_obs's free_owner)
            token = f"migration:{self._name}:{next(_mig_tokens)}"
            memledger.get_ledger().register(token, len(blob), "migration",
                                            owner=self._name)
        with self._cond:
            self._state = "frozen"
        self._flight_dump("freeze")
        return {"engine": self, "blob": blob, "entries": entries,
                "queued": list(queued), "fallback": fallback,
                "ledger_token": token}

    def _export_row(self, group, bucket, slot: int) -> dict:
        """One row's migration manifest: block table (position order),
        cursors, host token stream, and sampling state — everything
        :meth:`PagedGroup.restore` needs for a bit-identical resume."""
        e = group.entries[slot]
        return {
            "rid": e.request.rid,
            "bucket": [int(b) for b in bucket],
            "prompt": np.asarray(e.request.prompt, np.int32).tolist(),
            "pages": [int(p) for p in (group.row_pages[slot] or [])],
            "length": int(group.lengths[slot]),
            "position": int(group.positions[slot]),
            "steps_done": int(group.steps_done[slot]),
            "cur_tok": int(group.cur_tok[slot]),
            "pf_next": int(group.pf_next[slot]),
            "n_shared": int(group.shared_pages[slot]),
            "emitted": [int(t) for t in (group.emitted[slot] or [])],
            "seed": int(e.request.seed),
            "temperature": float(group.temperature[slot]),
            "top_p": float(group.top_p[slot]),
            "top_k": int(group.top_k[slot]),
            "ttft_s": group.ttft_s[slot],
            # the request's span context rides the manifest so an adopting
            # engine — even in another process, where no live _Entry span
            # exists — continues the SAME trace instead of orphaning it
            "trace": (None if e.trace is None else {
                "trace_id": e.trace.trace_id, "span_id": e.trace.span_id,
                "parent_id": e.trace.parent_id, "name": e.trace.name}),
        }

    def adopt_rows(self, frozen: dict, timeout: float | None = None) -> dict:
        """Adopt a peer's frozen row set: import the blob's KV pages into
        this engine's pool (re-deduplicating through the prefix cache) and
        resume each row mid-stream. Runs on THIS engine's worker thread via
        the migration mailbox — the pool stays single-threaded. Each row
        binds under the engine lock: its admission reservation is adopted
        (:meth:`AdmissionQueue.adopt`) at bind time and released by the
        normal retirement path, so the reservation is carried exactly once
        end to end (the caller releases the source's charge for adopted
        rids). Rows whose entry was superseded or resolved while frozen
        (a source recovery invalidated the export) are dropped with their
        pages released. Returns ``{"adopted": [rids], "fallback":
        [entries]}``; on a worker timeout the rows bound so far count as
        adopted and the rest fall back — never both."""
        entries = dict(frozen["entries"])
        blob = frozen.get("blob")
        if blob is None or not entries:
            return {"adopted": [], "fallback": list(entries.values())}
        if not self.paged:
            raise MigrationError(
                f"adopt target {self._name} is not a paged engine")
        if timeout is None:
            timeout = get_config().serve_migrate_timeout_s
        box: dict = {"bound": [], "cancelled": False}
        ev = threading.Event()
        with self._cond:
            if self._state != "running" or not self._started:
                raise MigrationError(
                    f"adopt target {self._name} not accepting "
                    f"({self._state})")
            self._mig_inbox.append(
                ("adopt", {"blob": blob, "entries": entries}, ev, box))
            if self._idle:
                self._heartbeat = time.monotonic()
            self._cond.notify_all()
        # the handoff is committed: the blob's ledger entry moves to this
        # engine (source debited, target credited — one transfer, the
        # process total never moves) and is debited exactly once below,
        # whichever way the adopt resolves (bound, timeout, or error —
        # after the post the blob is consumed or dead either way). The
        # not-accepting raise above leaves the entry with the source, so
        # a retry against another replica still finds it.
        token = frozen.get("ledger_token")
        led = memledger.get_ledger()
        if token:
            led.transfer(token, owner=self._name)
        try:
            if not ev.wait(timeout):
                # cancel under the lock: rows not yet bound will be released
                # by the worker when it gets there; rows already bound are
                # this engine's responsibility now — report them adopted so
                # the caller neither twins nor re-places them
                with self._cond:
                    box["cancelled"] = True
                    bound = set(box["bound"])
                return {"adopted": sorted(bound),
                        "fallback": [e for rid, e in entries.items()
                                     if rid not in bound]}
            err = box.get("error")
            if err is not None:
                if isinstance(err, MigrationError):
                    raise err
                raise MigrationError(
                    f"adopt failed on {self._name}: {type(err).__name__}: "
                    f"{err}") from err
            return box["result"]
        finally:
            if token:
                led.free(token, strict=False)

    def adopt_entries(self, entries) -> bool:
        """Queue-only handoff for migrated work WITHOUT device state — the
        frozen backlog and retry-fallback twins. Each entry's reservation
        is force-admitted (the fleet already admitted this work; the gate
        bounds new admissions only) and the entry queues normally. Returns
        False when this engine is not accepting — the caller tries the
        next replica."""
        entries = list(entries)
        if not entries:
            return True
        with self._cond:
            if self._state != "running":
                return False
            for e in entries:
                self._queue.adopt(e.cost)
                self._former.add(e)
            if self._idle:
                self._heartbeat = time.monotonic()
            self._cond.notify_all()
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)
        return True

    def export_prefixes(self, n: int,
                        timeout: float | None = None) -> bytes | None:
        """The pool's N hottest prefix-cache chains as a migration blob
        (worker-mediated; best-effort — returns None instead of raising:
        cache warming must never fail a restart)."""
        if not self.paged or n <= 0:
            return None
        if timeout is None:
            timeout = get_config().serve_migrate_timeout_s
        try:
            return self._mig_post("export_prefixes", int(n), timeout)
        except MigrationError:
            return None

    def import_prefixes(self, blob: bytes | None,
                        timeout: float | None = None) -> int:
        """Warm this pool's prefix cache from a peer's exported chains
        (worker-mediated; best-effort). Returns entries inserted."""
        if not self.paged or not blob:
            return 0
        if timeout is None:
            timeout = get_config().serve_migrate_timeout_s
        try:
            return int(self._mig_post("import_prefixes", blob, timeout) or 0)
        except MigrationError:
            return 0

    def _mig_post(self, kind: str, payload, timeout: float):
        """Post one op to the worker's migration mailbox and wait."""
        box: dict = {"bound": [], "cancelled": False}
        ev = threading.Event()
        with self._cond:
            if self._state != "running" or not self._started:
                raise MigrationError(
                    f"{self._name} not accepting ({self._state})")
            self._mig_inbox.append((kind, payload, ev, box))
            if self._idle:
                self._heartbeat = time.monotonic()
            self._cond.notify_all()
        if not ev.wait(timeout):
            with self._cond:
                box["cancelled"] = True
            raise MigrationError(
                f"{kind} timed out after {timeout}s on {self._name}")
        err = box.get("error")
        if err is not None:
            raise MigrationError(
                f"{kind} failed on {self._name}: {type(err).__name__}: "
                f"{err}") from err
        return box.get("result")

    def _service_migrations(self, pool, pools, pf_queue) -> None:
        """Drain the migration mailbox on the worker thread (called once
        per iteration). Any failure lands in the requester's box — the
        worker survives every migration fault; mid-migration failure must
        degrade to the retry path, never kill the adoptive engine."""
        while True:
            with self._cond:
                if not self._mig_inbox:
                    return
                kind, payload, ev, box = self._mig_inbox.popleft()
                if box.get("cancelled"):
                    box["error"] = MigrationError("cancelled by requester")
                    ev.set()
                    continue
            try:
                if kind == "adopt":
                    box["result"] = self._mig_adopt(pool, pools, pf_queue,
                                                    payload, box)
                elif kind == "export_prefixes":
                    box["result"] = pool.export_prefixes(payload)
                elif kind == "import_prefixes":
                    faults.fire("serve.migrate", path=f"warm@{self._name}")
                    n = pool.import_prefixes(payload)
                    self._record_pages(pool)
                    box["result"] = n
                else:
                    box["error"] = MigrationError(
                        f"unknown migration op {kind!r}")
            except BaseException as exc:
                box["error"] = exc
            ev.set()

    def _mig_adopt(self, pool, pools, pf_queue, payload, box) -> dict:
        """Worker-side adopt: import the blob, then bind each row under
        the engine lock (atomic against the requester's timeout-cancel —
        a row is either bound here exactly once or reported back for the
        fallback path, never both)."""
        faults.fire("serve.migrate", path=f"import@{self._name}")
        entries = payload["entries"]
        rows = pool.import_rows(payload["blob"])
        adopted: list = []
        fallback: list = []
        for row in rows:
            rid = row["rid"]
            e = entries.get(rid)
            pages = row["pages"]
            bucket = tuple(row["bucket"])
            group = pools.get(bucket)
            if group is None and bucket in self.buckets:
                group = pools[bucket] = PagedGroup(
                    bucket, self.max_batch, self._page_len,
                    self._prefill_chunk)
                capture_paged_costs(
                    self.params, self.heads, bucket, self.max_batch,
                    pool, self._prefill_chunk, self.compute_dtype,
                    self.moe, key=self._prog_key(bucket),
                    kernel=self._decode_kernel)
            bound = False
            try:
                faults.fire("serve.migrate",
                            path=f"adopt:{rid}@{self._name}")
                with self._cond:
                    viable = (e is not None and not e.superseded
                              and not e.handle.done()
                              and not box.get("cancelled")
                              and self._state == "running")
                    free = group.free_slots() if group is not None else []
                    if viable and free:
                        slot = free[0]
                        self._queue.adopt(e.cost)
                        group.restore(slot, e, row, pages)
                        if int(row["pf_next"]) >= 0:
                            pf_queue.append((bucket, slot, rid))
                        box["bound"].append(rid)
                        bound = True
            except Exception:
                bound = False
            if bound:
                adopted.append(rid)
                # re-activate the request's trace across the hop: a cross-
                # process adopt has no live entry span, so rebuild it from
                # the manifest; either way the migration itself becomes a
                # child span, so freeze -> adopt -> result joins into one
                # trace_id in the JSONL (tests/test_migration.py asserts)
                base = e.trace
                t = row.get("trace")
                if base is None and t:
                    base = obs_trace.SpanContext(
                        t.get("trace_id"), t.get("span_id"),
                        t.get("parent_id"),
                        t.get("name") or f"serve.request.{rid}")
                if base is not None:
                    e.trace = base.child(f"serve.migrate.{rid}")
                with obs_trace.use(e.trace):
                    self.metrics.record_page_event(
                        "adopt", rid=rid, pages=len(pages),
                        shared=int(row["n_shared"]),
                        used=pool.used_count(), total=pool.capacity)
            else:
                pool.release(pages)
                if (e is not None and not e.superseded
                        and not e.handle.done()):
                    fallback.append(e)
        if adopted:
            self.metrics.record_migration("adopt", len(adopted))
        self._record_pages(pool)
        self._live_rows = sum(len(g.live_slots())
                              for g in pools.values())
        return {"adopted": adopted, "fallback": fallback}

    def _fail_mig_inbox(self, reason: str) -> None:
        """Resolve every pending mailbox op with an error (the worker is
        gone — a requester blocked on its event must not wait out the
        full timeout)."""
        while True:
            with self._cond:
                if not self._mig_inbox:
                    return
                kind, payload, ev, box = self._mig_inbox.popleft()
            box["error"] = MigrationError(reason)
            ev.set()

    def kvpool_audit(self) -> dict:
        """The pool invariant report (:meth:`PagedKVPool.audit`) over this
        engine's live groups — exact on a quiesced engine (closed, drained,
        frozen); advisory under a running worker (the probe snapshot races
        row transitions). Never raises — rides ``GET /debug/kvpool``."""
        if not self.paged:
            return {"ok": True, "errors": [], "note": "engine is not paged"}
        with self._cond:
            pool = self._kvpool
            groups = [g for g in self._pools.values()
                      if not isinstance(g, ProgramRowSet)]
        if pool is None:
            return {"ok": True, "errors": [], "note": "no pool built"}
        try:
            return pool.audit(groups)
        except Exception as exc:  # racing a live worker's row transition
            return {"ok": False,
                    "errors": [f"audit crashed: {type(exc).__name__}: "
                               f"{exc}"]}

    # --------------------------------------------------- paged scheduler

    def _run_paged(self, gen: int) -> None:
        """The paged slot-step loop: each iteration refills freed rows from
        the queue (page allocation + prefix match — host-side), runs
        prefill chunks up to the ``serve_prefill_chunk`` TOKEN budget
        (oldest rows first), then one decode step per bucket over its live
        rows — chunked prefill interleaves with decode, so a long prompt
        never monopolizes an iteration. ``pools`` maps bucket -> PagedGroup
        over the engine's
        one shared :class:`PagedKVPool`; ``pf_queue`` is the FIFO of rows
        mid-prefill ((bucket, slot, rid) — rid guards against a retired
        slot's re-occupant inheriting a stale cursor). Mirrors for
        supervisor recovery as in the slab loop."""
        pools: dict[tuple, PagedGroup] = {}
        with self._cond:
            if self._gen != gen:
                return  # superseded before the first iteration
            self._pools = pools
            # the GENERATION-LOCAL pool binding: every helper below takes
            # this pool, never self._kvpool — a stuck-but-alive superseded
            # worker resuming mid-iteration must mutate only its own dead
            # pool, not the replacement generation's (page ids are
            # meaningless across pools; a cross-generation release would
            # silently double-book pages under live rows). Bound UNDER the
            # lock with the generation re-checked, so a racing recovery
            # can never hand two generations one pool.
            pool = self._ensure_kvpool()
        pf_queue: collections.deque = collections.deque()
        claimed: list[_Entry] = []
        try:
            while True:
                if self._gen == gen:  # a superseded straggler must never
                    # analyze: single-writer — generation-guarded monotonic
                    # stamp; floats assign atomically under the GIL and the
                    # watchdog tolerates any interleaving
                    self._heartbeat = time.monotonic()  # fake a live pulse
                if self._slo is not None:
                    # rate-limited internally (serve_slo_eval_interval_s):
                    # per-iteration cost is one float compare
                    self._slo.tick(self._clock())
                faults.fire("serve.worker_crash",
                            path=threading.current_thread().name)
                claimed = []
                with self._cond:
                    while True:
                        if self._gen != gen:
                            return  # superseded by a recovery
                        if self._state == "freezing":
                            # migration pause: park WITHOUT touching the
                            # pools — freeze_rows() joins this thread and
                            # takes over every resident row
                            return
                        busy = any(p.occupied_slots()
                                   for p in pools.values())
                        if self._mig_inbox:
                            break  # service migration ops outside the lock
                        if self._state == "closing":
                            # resident rows (live AND mid-prefill) are the
                            # work in flight: finish them (close() already
                            # emptied the former)
                            if not busy:
                                return
                            break
                        draining = self._state == "draining"
                        claimed = self._claim_rowlevel(pools)
                        if claimed or busy:
                            break
                        if draining:
                            return  # nothing queued, nothing resident
                        self._idle = True
                        self._cond.wait(None if self._real_clock
                                        else _POLL_CAP_S)
                        self._idle = False
                        if self._gen == gen:
                            self._heartbeat = time.monotonic()
                    self._claimed = claimed
                with self._cond:
                    if self._gen == gen and pool is not self._kvpool:
                        # this generation dropped its pool (slab consumed
                        # by a failed donated call): rebind to the rebuilt
                        # one — the old object's arrays are deleted. Under
                        # the lock + gen check: a stale generation must
                        # never build (or adopt) the live generation's
                        # pool
                        pool = self._ensure_kvpool()
                self._service_migrations(pool, pools, pf_queue)
                prog_claimed = [e for e in claimed
                                if self._is_program_bucket(e.bucket)]
                lm_claimed = [e for e in claimed
                              if not self._is_program_bucket(e.bucket)]
                self._admit_paged(pool, pools, lm_claimed, pf_queue)
                self._admit_program_rows(pools, prog_claimed)
                claimed = []
                with self._cond:
                    if self._gen == gen:  # never clobber a successor's
                        self._claimed = []  # claimed mirror
                self._prefill_paged_chunk(pool, pools, pf_queue)
                self._step_paged(pool, pools)
                self._step_program_rows(pools)
        except BaseException as exc:  # worker death: recover or fail held
            held = [p.entries[i] for p in pools.values()
                    for i in p.occupied_slots()]
            if self._crash_handler(exc, claimed + held, gen):
                return
            raise

    def _admit_paged(self, pool, pools, claimed, pf_queue) -> None:
        """Bind each claimed entry to a free row of its bucket's group:
        prefix-cache match, page allocation (the admission charge was
        taken in page units at submit, so the alloc cannot fail under
        engine traffic — still guarded), block table build. Host-side
        only; the device work happens chunk by chunk in
        :meth:`_prefill_paged_chunk`."""
        if not claimed:
            return
        from ..models.planner import request_pages

        # dispatch order ACROSS buckets: _claim_rowlevel walks an unordered
        # bucket set, but the prefill queue is the TTFT ledger — higher
        # priority first, then arrival (rid is monotonic per process), so a
        # short early request never waits out a later long prompt's chunks
        claimed = sorted(claimed,
                         key=lambda e: (-e.request.priority, e.request.rid))
        for e in claimed:
            with obs_trace.use(e.trace):
                now = self._clock()
                r = e.request
                if r.deadline is not None and r.deadline <= now:
                    self._retire(e, Result(
                        r.rid, STATUS_EXPIRED,
                        reason=f"deadline {r.deadline} passed before "
                               f"dispatch (dispatched at {now})",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                e.queue_s = now - e.enq_t
                group = pools.get(e.bucket)
                if group is None:
                    group = pools[e.bucket] = PagedGroup(
                        e.bucket, self.max_batch, self._page_len,
                        self._prefill_chunk)
                    # no-warmup path: the bucket's cost model still lands
                    # with its first (lazy) compile
                    capture_paged_costs(
                        self.params, self.heads, e.bucket, self.max_batch,
                        pool, self._prefill_chunk, self.compute_dtype,
                        self.moe, key=self._prog_key(e.bucket),
                        kernel=self._decode_kernel)
                slot = group.free_slots()[0]
                n = r.prompt.shape[0]
                shared_len, spages = pool.match_prefix(r.prompt)
                need = request_pages(n, r.steps, self._page_len)
                try:
                    owned = pool.alloc(need - len(spages))
                except PagePoolExhausted as exc:
                    pool.release(spages)  # drop the refs the match took
                    # the OOM post-mortem lands BEFORE the retry path runs
                    # (the retry rebuilds state and destroys the evidence)
                    memledger.dump_oom_forensics(
                        f"page allocation failed for rid {r.rid}: {exc}")
                    reason = f"page allocation failed: {exc}"
                    if e.attempts_left():
                        self._requeue(e, reason)
                    else:
                        self._retire(e, Result(
                            r.rid, STATUS_ERROR, reason=reason,
                            metrics={"bucket": e.bucket,
                                     "queue_s": e.queue_s,
                                     "total_s": now - e.enq_t}))
                    continue
                group.assign(slot, e, spages + owned, shared_len,
                             len(spages))
                pf_queue.append((e.bucket, slot, r.rid))
                self.metrics.record_prefix(hit=bool(spages))
                self.metrics.record_page_event(
                    "alloc", rid=r.rid, pages=len(spages) + len(owned),
                    shared=len(spages), used=pool.used_count(),
                    total=pool.capacity)
        self._record_pages(pool)

    def _prefill_paged_chunk(self, pool, pools, pf_queue) -> None:
        """Run bounded prefill for this iteration — the chunked-prefill
        scheduling contract: at most ``serve_prefill_chunk`` prompt TOKENS
        of prefill per worker iteration (several short prompts may share
        the budget; one long prompt consumes it in a single chunk and
        resumes next iteration), decode steps interleaving in between so a
        long prompt never monopolizes the worker. Rows prefill oldest
        first — FIFO TTFT fairness. A row's final chunk (the one
        containing the prompt's last token) emits its first token — real
        TTFT — caches the completed prompt pages for prefix sharing, and
        flips the row decode-ready."""
        budget = self._prefill_chunk
        while budget > 0 and pf_queue:
            budget -= self._prefill_one_chunk(pool, pools, pf_queue)
        self._live_rows = sum(len(g.live_slots()) for g in pools.values())

    def _prefill_one_chunk(self, pool, pools, pf_queue) -> int:
        """One chunk for the head of the prefill queue; returns the real
        prompt tokens it consumed (0 ends the caller's budget loop —
        nothing left to prefill, or the head row just failed)."""
        while pf_queue:
            bucket, slot, rid = pf_queue[0]
            group = pools.get(bucket)
            e = group.entries[slot] if group is not None else None
            if (e is None or e.request.rid != rid
                    or group.pf_next[slot] < 0):
                pf_queue.popleft()  # stale: retired/expired/re-occupied
                continue
            break
        else:
            return 0
        with obs_trace.use(e.trace):
            r = e.request
            p, s = bucket
            # analyze: ignore[host-sync] — host numpy bookkeeping arrays
            cs = int(group.pf_next[slot])
            # analyze: ignore[host-sync] — host numpy bookkeeping arrays
            n = int(group.lengths[slot])
            C = group.chunk
            tokens = min(C, n - cs)
            final = cs + C >= n
            chunk = group.prompts[slot][cs:cs + C]
            if chunk.shape[0] < C:
                # a prefix hit whose shared_len is page- but not CHUNK-
                # aligned leaves a short tail slice; pad it back to the
                # compiled width — a narrower array would compile a fresh
                # program per width and break the <=3-per-bucket bound
                chunk = np.concatenate(
                    [chunk, np.zeros(C - chunk.shape[0], np.int32)])
            try:
                # copy-on-write gate on every page the chunk will scatter
                # into (a no-op in steady state: writes target owned pages
                # by construction — kvpool.PagedKVPool.ensure_writable)
                for j in range(cs // self._page_len,
                               min((cs + C) // self._page_len,
                                   group.pages_per_row)):
                    self._cow(pool, group, slot, j, rid=r.rid)
                from ..models.transformer import lm_prefill_paged

                faults.fire("serve.prefill", path=f"bucket-{p}x{s}")
                t0 = time.perf_counter()
                pages, first = lm_prefill_paged(
                    self.params, pool.pages, group.tables[slot], chunk, cs,
                    n, heads=self.heads, page_len=self._page_len,
                    seed=r.seed, temperature=r.temperature, top_p=r.top_p,
                    top_k=r.top_k, compute_dtype=self.compute_dtype,
                    moe=self.moe)
                first = int(first)  # device sync: the chunk landed
                wall = time.perf_counter() - t0
            except Exception as exc:
                pf_queue.popleft()
                self._paged_prefill_failure(pool, pools, bucket, slot, exc)
                return 0  # end this iteration's budget loop
            pool.pages = pages
            group.pf_next[slot] = cs + C
            self.metrics.record_prefill(
                e.bucket, wall, rid=r.rid,
                program_key=self._prog_key(e.bucket),
                program="lm_prefill_paged", chunk=[cs, tokens], final=final)
            self.flight.record(
                "prefill", bucket=[p, s], slot=slot, rid=r.rid,
                seconds=wall, chunk=[cs, tokens],
                queue_depth=self._queue.count, compiles=_compile_count(),
                pages_used=pool.used_count())
            if final:
                pf_queue.popleft()
                group.finish_prefill(slot, first)
                group.ttft_s[slot] = self._clock() - e.enq_t
                # the prompt's full pages are final now — publish them for
                # copy-on-write reuse by later identical prefixes
                pool.insert_prefix(r.prompt, group.row_pages[slot])
                self._record_pages(pool)
                if r.steps == 1 or (r.eos is not None and first == r.eos):
                    self._retire_row_paged(pool, pools, bucket, slot,
                                           STATUS_OK, self._clock())
        return tokens

    def _cow(self, pool, group, slot: int, table_idx: int,
             rid: int | None = None) -> None:
        """Engine-side copy-on-write: splits the page and keeps the group's
        release bookkeeping in step with the table (kvpool owns the device
        copy — ONE compiled program per slab shape)."""
        old = int(group.tables[slot, table_idx])
        if pool.ensure_writable(group.tables[slot], table_idx):
            rp = group.row_pages[slot]
            rp[table_idx] = int(group.tables[slot, table_idx])
            if group.shared_pages[slot] > 0:
                group.shared_pages[slot] -= 1
            self.metrics.record_page_event(
                "cow", rid=rid, pages=1, used=pool.used_count(),
                total=pool.capacity)
            self.flight.record("cow", slot=slot, page=old,
                               fresh=rp[table_idx],
                               pages_used=pool.used_count())

    def _step_paged(self, pool, pools) -> None:
        """Retire expired resident rows, then run ONE decode step per
        bucket over its live rows. All buckets' steps are dispatched before
        any result is awaited (async dispatch overlap, as in the slab
        loop); non-live rows run the masked-harmless dummy against page 0
        so a prefilling neighbor's pages are never scribbled."""
        from ..models.transformer import lm_decode_paged

        launched = []
        for bucket, group in list(pools.items()):
            if isinstance(group, ProgramRowSet):
                continue  # the program lane steps in _step_program_rows
            now = self._clock()
            for i in group.occupied_slots():
                dl = group.entries[i].request.deadline
                if dl is not None and dl <= now:
                    self._retire_row_paged(
                        pool, pools, bucket, i, STATUS_EXPIRED, now,
                        reason=f"deadline {dl} passed mid-decode "
                               f"(now {now})")
            live = group.live_slots()
            if not live:
                continue
            p, s = bucket
            try:
                for i in live:  # COW gate on each row's write page
                    self._cow(pool, group, slot=i,
                              # analyze: ignore[host-sync] — host numpy
                              # block-table bookkeeping, not device data
                              table_idx=int(group.positions[i])
                              // self._page_len,
                              rid=group.entries[i].request.rid)
                faults.fire("serve.decode_step", path=f"bucket-{p}x{s}")
                t0 = time.perf_counter()
                tables, positions, cur = group.decode_inputs()
                pages, nxt = lm_decode_paged(
                    self.params, pool.pages, tables, positions, cur,
                    group.steps_done, group.seeds, group.temperature,
                    group.top_p, group.top_k, heads=self.heads,
                    page_len=self._page_len,
                    compute_dtype=self.compute_dtype, moe=self.moe,
                    kernel=self._decode_kernel)
            except Exception as exc:
                self._fail_paged_bucket(pool, pools, bucket, exc)
                continue
            pool.pages = pages
            launched.append((bucket, group, live, t0, nxt))
        for bucket, group, live, t0, nxt in launched:
            try:
                # analyze: ignore[host-sync] — THE one intentional sync per
                # decode step: the host must see the emitted tokens to
                # retire rows (all dispatches above launched async first)
                nxt = np.asarray(nxt)  # sync; the per-row emitted tokens
            except Exception as exc:
                self._fail_paged_bucket(pool, pools, bucket, exc)
                continue
            wall = time.perf_counter() - t0
            self.metrics.record_step(bucket, len(live), self.max_batch,
                                     wall, program_key=self._prog_key(bucket),
                                     program="lm_decode_paged")
            self.flight.record(
                "step", bucket=list(bucket), rows=len(live),
                seconds=wall, queue_depth=self._queue.count,
                compiles=_compile_count(), pages_used=pool.used_count())
            now = self._clock()
            for i in live:
                if group.entries[i] is None:
                    continue  # expired between dispatch and landing
                group.positions[i] += 1
                group.steps_done[i] += 1
                tok = int(nxt[i])
                group.cur_tok[i] = tok
                group.emitted[i].append(tok)
                r = group.entries[i].request
                if ((r.eos is not None and tok == r.eos)
                        # analyze: ignore[host-sync] — host numpy bookkeeping
                        or int(group.steps_done[i]) >= r.steps):
                    self._retire_row_paged(pool, pools, bucket, i,
                                           STATUS_OK, now)
        self._live_rows = sum(len(g.live_slots()) for g in pools.values())

    def _retire_row_paged(self, pool, pools, bucket, slot: int,
                          status: str, now: float, reason: str = "") -> None:
        """Retire one paged row and free its slot — the ONLY path a
        resident row leaves a group by, so every terminal status releases
        the row's pages AND its page-unit admission reservation exactly
        once (pages here via the pool refcount, the reservation in
        :meth:`_retire` by whoever wins the handle)."""
        group = pools[bucket]
        e = group.entries[slot]
        n_pages = len(group.row_pages[slot] or [])
        metrics = {"bucket": bucket, "slot": slot, "queue_s": e.queue_s,
                   "ttft_s": group.ttft_s[slot],
                   "total_s": now - e.enq_t, "pages": n_pages,
                   "shared_pages": int(group.shared_pages[slot])}
        if status == STATUS_OK:
            toks = np.concatenate([
                np.asarray(e.request.prompt, np.int32),
                np.asarray(group.emitted[slot], np.int32)])
            result = Result(e.request.rid, STATUS_OK, tokens=toks,
                            metrics=metrics)
        else:
            result = Result(e.request.rid, status, reason=reason,
                            metrics=metrics)
        pages = group.release(slot)
        if pool is not None:
            pool.release(pages)
            # inside the request's span: the free record must join the
            # request's trace whichever step retires it
            with obs_trace.use(e.trace):
                self.metrics.record_page_event(
                    "free", rid=e.request.rid, pages=len(pages),
                    used=pool.used_count(), total=pool.capacity)
            self._record_pages(pool)
        self._retire(e, result)

    def _paged_pool_lost(self, pool) -> bool:
        """True when a failed donated call consumed the page slab (the
        paged analog of :meth:`_slab_lost`)."""
        if pool is None:
            return False
        leaf = pool.pages["l0"][0]
        deleted = getattr(leaf, "is_deleted", None)
        return bool(deleted and deleted())

    def _drop_paged_pool(self, pool, pools, reason: str) -> None:
        """The calling generation's slab died under a failed donated
        call: every resident row in its EVERY bucket lost its cache —
        requeue each within its attempt budget (the page-unit reservation
        is carried), fail the rest, and drop the pool; the live worker
        rebinds a zeroed rebuild at its next iteration (the same contract
        as worker-crash recovery). A STALE generation reaching here
        clears only its own (already superseded) map — the engine-level
        pool reference is cleared only when it still names this pool."""
        now = self._clock()
        for bucket, group in list(pools.items()):
            if isinstance(group, ProgramRowSet):
                continue  # program rows hold no pages: they ride out a
                # slab loss untouched and answer on this same iteration
            for i in group.occupied_slots():
                e = group.entries[i]
                group.release(i)  # page bookkeeping dies with the pool
                if e.attempts_left():
                    self._requeue(e, reason)
                else:
                    self._retire(e, Result(
                        e.request.rid, STATUS_ERROR, reason=reason,
                        metrics={"bucket": bucket, "queue_s": e.queue_s,
                                 "total_s": now - e.enq_t}))
            pools.pop(bucket)
        if self._kvpool is pool:
            memledger.get_ledger().free(f"kvpool:{self._name}",
                                        strict=False)
            self._kvpool = None
            self.metrics.record_page_event("lost", used=0,
                                           total=self._num_pages - 1)
            self.metrics.record_pages(self._num_pages - 1, 0, 0)

    def _fail_paged_bucket(self, pool, pools, bucket,
                           exc: Exception) -> None:
        """A paged decode step died: with the pool intact (an injected
        fault raised before launch) only that step's live rows
        fail/retry and their pages free; a consumed slab escalates to
        :meth:`_drop_paged_pool`."""
        group = pools.get(bucket)
        if group is None or pool is not self._kvpool:
            # an earlier bucket's failure in this same landing loop already
            # escalated to _drop_paged_pool: every resident row (including
            # this bucket's) was requeued/failed there — a second handling
            # pass would KeyError on the cleared pools map
            return
        reason = f"decode step failed: {type(exc).__name__}: {exc}"
        if memledger.is_oom_error(exc):
            memledger.dump_oom_forensics(reason)
        self.flight.record("decode_fault", bucket=list(bucket),
                           rows=len(group.live_slots()), error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count(),
                           pages_used=pool.used_count() if pool else 0)
        if self._paged_pool_lost(pool):
            self._drop_paged_pool(pool, pools, reason)
        else:
            now = self._clock()
            for i in group.live_slots():
                e = group.entries[i]
                if e.attempts_left():
                    pool.release(group.release(i))
                    self._requeue(e, reason)
                else:
                    self._retire_row_paged(pool, pools, bucket, i,
                                           STATUS_ERROR, now, reason=reason)
            self._record_pages(pool)
        self._flight_dump("decode-step-failed")

    def _paged_prefill_failure(self, pool, pools, bucket, slot: int,
                               exc: Exception) -> None:
        """A prefill chunk died: the row being prefilled retries within
        its attempt budget (the chunk cursor restarts from its shared
        prefix on the retry — resumability is host state) or errors;
        co-resident rows survive unless the slab was consumed."""
        group = pools[bucket]
        e = group.entries[slot]
        reason = f"prefill failed: {type(exc).__name__}: {exc}"
        if memledger.is_oom_error(exc):
            memledger.dump_oom_forensics(reason)
        self.flight.record("prefill_fault", bucket=list(bucket),
                           rid=e.request.rid, error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count(),
                           pages_used=pool.used_count() if pool else 0)
        if self._paged_pool_lost(pool):
            self._drop_paged_pool(pool, pools,
                                  f"pool lost to a failed prefill: {reason}")
        else:
            now = self._clock()
            pool.release(group.release(slot))
            if e.attempts_left():
                self._requeue(e, reason)
            else:
                self._retire(e, Result(
                    e.request.rid, STATUS_ERROR, reason=reason,
                    metrics={"bucket": bucket, "queue_s": e.queue_s,
                             "total_s": now - e.enq_t}))
            self.metrics.record_page_event(
                "free", rid=e.request.rid, used=pool.used_count(),
                total=pool.capacity)
            self._record_pages(pool)
        self._flight_dump("prefill-failed")
