"""The continuous-batching serving engine: one worker thread, compiled decode.

:class:`ServeEngine` is the front half of an inference stack over the
library's compiled decode programs: concurrent callers ``submit`` requests;
an admission gate (queue depth + in-flight KV-cache HBM budget, request.py)
rejects overload with a reason; a batch former (batcher.py) buckets prompts
onto a small static shape set so compiles stay bounded; and a single worker
thread keeps the device fed. Two schedulers share that skeleton:

**Row-level** (``serve_rowlevel``, the default) changes the unit of
scheduling from "batch" to "slot-step". Each bucket owns a persistent
device-resident KV slab of ``max_batch`` slots (:class:`~.batcher.SlotPool`)
and TWO compiled programs — slot-targeted prefill
(:func:`~marlin_tpu.models.transformer.lm_prefill_slot`) and a single-token
decode step over the whole slab
(:func:`~marlin_tpu.models.transformer.lm_decode_rows`, donated KV buffers,
per-row positions and sampling knobs). Every worker iteration:

    refill freed slots from the queue (prefill-on-admit; the prompt's
    first token lands here — real TTFT)  →  retire rows that emitted
    their ``eos``, hit their step budget, or expired  →  run ONE decode
    step for all live rows  →  repeat

A finished row's slot refills on the very next step instead of riding out
its batch as a dummy, and a newly admitted request waits one step, not one
whole batch — the tokens/s and TTFT win at high offered load. Per-row
greedy output stays bit-identical to :func:`~marlin_tpu.models.transformer
.lm_generate` on the same prompt (greedy decode is composition-independent)
and the compile count is ≤ 2 programs per bucket, for ANY per-row mix of
sampling knobs (they are traced vectors).

**Gang** (``serve_rowlevel=False``, the fallback) runs one fused
``lm_generate_batch`` program per bucket to completion: all ``max_batch``
slot rows launch and land together (free slots carry inert dummy rows).
Simpler — one program per bucket, no per-step host sync — but a finished
row holds its slot as a dummy until the whole batch lands, and admissions
wait out the entire in-flight batch.

Lifecycle (both schedulers): ``drain()`` stops admission and completes
everything already accepted; ``close()`` stops admission, finishes the work
in flight (the gang batch / the live slots), and retires everything still
queued with a clean ``shutting_down`` Result. Both are terminal and
idempotent; the worker thread (named ``marlin-serve-*`` — the conftest leak
fixture watches the prefix) is joined before either returns. Chaos hooks
(utils/faults.py): ``serve.enqueue`` fires in ``submit``; ``serve.step``
fires before each gang batch launch / each row-level prefill — a fault
fails those requests with ``error`` Results; ``serve.decode_step`` fires
before each row-level decode step — a fault there fails only that step's
live rows and leaves the slot pool consistent. The engine keeps serving
after any of them.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref

import numpy as np

from ..config import get_config
from ..obs import perf, trace as obs_trace
from ..obs.collectors import compile_count as _compile_count
from ..obs.exposition import (register_health_provider,
                              unregister_health_provider)
from ..utils import faults
from .batcher import (BatchFormer, bucket_kv_bytes, bucket_program_key,
                      capture_bucket_costs, normalize_buckets, pick_bucket,
                      warmup_buckets)
from .metrics import ServeMetrics
from .request import (STATUS_ERROR, STATUS_EXPIRED, STATUS_OK,
                      STATUS_REJECTED, STATUS_SHUTTING_DOWN, AdmissionQueue,
                      Request, Result, ResultHandle)

__all__ = ["ServeEngine"]

_engine_ids = itertools.count()

# real-seconds cap on one condition wait under an INJECTED clock: bounds how
# stale the worker's view of a fake clock can get (tests advance it between
# polls). Real-clock engines never poll — they wait on the condition until
# notified or the exact max_wait hint elapses.
_POLL_CAP_S = 0.02


class _Entry:
    """One admitted request riding through the former to a batch slot.
    ``queue_s`` is stamped when the row-level scheduler claims the entry
    for a slot (the gang path derives it at dispatch instead). ``trace``
    is the request's span context (obs/trace.py), captured at submit and
    re-activated by the worker thread around every record the request
    produces — that cross-thread handoff is what joins one request's
    enqueue/prefill/result records into one trace in the JSONL.

    ``attempt`` counts executions of this request (1-based); a retry
    re-queues a FRESH entry via :meth:`retry` — same request, handle,
    admission cost (the HBM reservation is carried, never re-charged), and
    original ``enq_t`` (latency is honest: it includes the failed
    attempts) — and marks this one ``superseded`` so a stale worker
    generation that still holds it can never retire it. The exactly-once
    Result is enforced twice over: superseded entries no-op in ``_retire``,
    and the admission budget is released only by whoever wins the handle's
    single ``_set``."""

    __slots__ = ("request", "handle", "bucket", "cost", "enq_t", "queue_s",
                 "trace", "attempt", "superseded")

    def __init__(self, request, handle, bucket, cost, enq_t, trace=None,
                 attempt=1):
        self.request = request
        self.handle = handle
        self.bucket = bucket
        self.cost = cost
        self.enq_t = enq_t
        self.queue_s = None
        self.trace = trace
        self.attempt = attempt
        self.superseded = False

    def retry(self) -> "_Entry":
        """The next-attempt twin (this entry becomes superseded)."""
        self.superseded = True
        return _Entry(self.request, self.handle, self.bucket, self.cost,
                      self.enq_t, trace=self.trace, attempt=self.attempt + 1)

    def attempts_left(self) -> bool:
        return self.attempt < self.request.max_attempts


class ServeEngine:
    """Continuous-batching inference engine over a trained LM.

    ``params``/``heads``/``compute_dtype``/``moe`` describe the model exactly
    as :func:`lm_generate_batch` takes them. Knobs default from the global
    config: ``buckets`` (``serve_buckets``), ``max_batch``
    (``serve_max_batch``), ``max_wait_ms`` (``serve_max_wait_ms``),
    ``queue_depth`` (``serve_queue_depth``); ``hbm_budget_bytes`` defaults to
    the planner's :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (0
    disables the byte gate). ``clock`` is the engine's *policy* clock
    (deadlines, max_wait, latency metrics) — injectable for deterministic
    tests; wall throughput is always measured on the real clock. ``log``
    overrides the default EventLog for ``serve`` records.

    ``rowlevel`` picks the scheduler (``serve_rowlevel`` by default): True =
    slot-step scheduling over persistent per-bucket KV slabs (prefill +
    decode-step programs, per-row retirement/refill); False = the gang
    fallback (one fused program per bucket runs a batch to completion).

    Usable as a context manager (``close()`` on exit); ``start=False`` defers
    the worker thread so tests can stage a queue before any dispatch."""

    def __init__(self, params: dict, heads: int, *, buckets=None,
                 max_batch: int | None = None,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compute_dtype: str | None = None, moe: tuple | None = None,
                 rowlevel: bool | None = None,
                 clock=time.monotonic, log=None, start: bool = True):
        cfg = get_config()
        self.params = params
        self.heads = heads
        self.compute_dtype = compute_dtype
        self.moe = moe
        self.rowlevel = bool(cfg.serve_rowlevel if rowlevel is None
                             else rowlevel)
        self.buckets = normalize_buckets(
            cfg.serve_buckets if buckets is None else buckets)
        self.max_batch = int(cfg.serve_max_batch if max_batch is None
                             else max_batch)
        wait_ms = cfg.serve_max_wait_ms if max_wait_ms is None else max_wait_ms
        depth = int(cfg.serve_queue_depth if queue_depth is None
                    else queue_depth)
        if hbm_budget_bytes is None:
            from ..models.planner import usable_hbm_bytes

            hbm_budget_bytes = usable_hbm_bytes()
        self._clock = clock
        self._real_clock = clock is time.monotonic
        self.metrics = ServeMetrics(log=log)
        self._queue = AdmissionQueue(depth, hbm_budget_bytes)
        self._cond = threading.Condition()
        self._former = BatchFormer(self.buckets, self.max_batch,
                                   max_wait=float(wait_ms) / 1e3)
        self._state = "running"  # running | draining | closing | closed
        self._started = False
        eid = next(_engine_ids)
        self._name = f"marlin-serve-{eid}"
        # --- supervised recovery (serving/supervisor.py) -------------------
        # the worker generation: a recovery bumps it, spawns a fresh thread,
        # and any stale worker still unwinding exits at its next gen check
        # without touching shared state (its entries are superseded)
        self._gen = 0
        self._pools: dict[tuple, object] = {}   # current worker's slot pools
        self._inflight: list = []               # current gang batch entries
        self._claimed: list = []                # claimed-but-unslotted rows
        self._crash: tuple | None = None        # (exc, undone entries)
        self._on_crash = None                   # supervisor's prompt-wake cb
        self._abandoned = None                  # superseded wedged thread:
        # never joined (breaker opened on a stuck worker — close() must not
        # block on a thread that may never return from its device call)
        self._idle = False                      # worker parked in cond.wait
        # EWMA of per-request service seconds (ok results, engine clock) —
        # the deadline-admission estimate's only input
        self._service_ewma = 0.0
        self._thread = self._make_thread(0)
        # --- performance introspection (obs/perf.py) -----------------------
        # the step-time black box: per-iteration records from the worker
        # loop, dumped on worker faults, on close, and via GET /debug/flight
        self.flight = perf.FlightRecorder(name=self._name)
        self._heartbeat: float | None = None  # real clock; worker stamps it
        self._live_rows = 0                   # worker-written, healthz-read
        self._prog_keys: dict[tuple, str] = {}
        self._finalized = False
        # readiness: /healthz reports this engine's lifecycle and 503s once
        # it leaves "accepting" (weakref — the provider must never pin a
        # dead engine; terminal close/drain unregister explicitly)
        ref = weakref.ref(self)
        name = self._name

        def _health():
            eng = ref()
            if eng is None:
                # abandoned without close(): drop out silently — a dead
                # entry must not 503 an otherwise healthy process for one
                # probe (health_payload skips None)
                unregister_health_provider(name)
                return None
            return eng._health_info()

        register_health_provider(name, _health)
        if start:
            self.start()

    # ------------------------------------------------------------- lifecycle

    def _make_thread(self, gen: int) -> threading.Thread:
        """A worker thread for one generation. Restarted generations keep
        the ``marlin-serve`` prefix (the conftest leak fixture and the
        flight recorder key on it) with a ``-r<gen>`` suffix."""
        name = self._name if gen == 0 else f"{self._name}-r{gen}"
        return threading.Thread(target=self._run, args=(gen,), daemon=True,
                                name=name)

    def start(self) -> None:
        """Start the worker thread (idempotent; no-op once shutting down)."""
        with self._cond:
            if self._started or self._state != "running":
                return
            self._started = True
        self._thread.start()

    def warmup(self) -> int:
        """Compile every bucket's program(s) before traffic — the fused
        batch program per bucket in gang mode, the prefill + decode-step
        pair per bucket in row-level mode (batcher.warmup_buckets)."""
        return warmup_buckets(self.params, self.heads, self.buckets,
                              self.max_batch, self.compute_dtype, self.moe,
                              rowlevel=self.rowlevel)

    def pending(self) -> int:
        """Requests admitted but not yet retired (queued + in flight)."""
        return self._queue.count

    # ------------------------------------------------------- introspection

    def _health_info(self) -> dict:
        """The /healthz readiness payload for this engine: lifecycle state
        (``accepting`` while running), live slot rows, queue depth, and the
        worker heartbeat age (None until the worker's first iteration).
        Lock-free reads of GIL-atomic fields — the probe must never contend
        with the worker."""
        state = {"running": "accepting", "draining": "draining",
                 "closing": "closed", "closed": "closed"}[self._state]
        hb = self._heartbeat
        return {
            "state": state,
            "live_slots": self._live_rows,
            "queue_depth": self._queue.count,
            "worker_started": self._started,
            "heartbeat_age_s": (round(time.monotonic() - hb, 3)
                                if hb is not None else None),
        }

    def _prog_key(self, bucket) -> str:
        """The roofline-accounting key for this engine's programs at one
        bucket (cached — it sits on the per-step path)."""
        key = self._prog_keys.get(bucket)
        if key is None:
            key = self._prog_keys[bucket] = bucket_program_key(
                self.params, bucket, self.max_batch, self.compute_dtype)
        return key

    def _flight_dump(self, reason: str) -> None:
        """Dump the flight ring (never raises — rides failure paths)."""
        try:
            self.flight.dump(reason=reason)
        except Exception:
            pass

    def _finalize_obs(self) -> None:
        """Terminal observability flush (close/drain, idempotent): dump the
        flight ring and land the program-utilization snapshots
        (``kind="program"``/``ev="util"``) in the EventLog, then drop out
        of the /healthz registry — a terminated engine must not hold the
        process at 503."""
        if self._finalized:
            return
        self._finalized = True
        self._flight_dump("close")
        try:
            for prog in ("lm_decode_rows", "lm_prefill_slot",
                         "lm_generate_batch"):
                perf.get_program_costs().emit(prog)
        except Exception:
            pass
        unregister_health_provider(self._name)

    def _join_worker(self) -> None:
        """Join until no worker generation will run again — a supervisor
        may swap in a fresh generation mid-join (crash during drain), or be
        a poll interval away from consuming a crash stash; returning after
        joining a dead predecessor would declare the engine closed with
        work still queued. Terminates because recovery is bounded: the
        supervisor's breaker (or the absence of a supervisor) guarantees a
        final generation."""
        if not self._started:
            return
        waited = 0.0
        while True:
            t = self._thread
            if t is self._abandoned:
                return  # a wedged generation the breaker gave up on: it
                # may never return from its device call, and everything it
                # held was already retired — joining would hang shutdown
            t.join()
            with self._cond:
                if self._thread is not t:
                    waited = 0.0
                    continue  # a recovery swapped in a new generation
                # stash pending + supervisor attached + a state it still
                # recovers in (check() skips closing/closed engines, so
                # waiting there would deadlock close())
                if (self._on_crash is not None and self._crash is not None
                        and self._state in ("running", "draining")):
                    recovery_pending = True  # stashed, not yet respawned
                else:
                    return
            if recovery_pending:
                if waited >= 5.0:
                    # an attached supervisor whose monitor never consumed
                    # the stash (e.g. Supervisor(start=False)): waiting
                    # forever would hang shutdown — return and let the
                    # caller's _fail_crash_stash / leftover paths resolve
                    # everything the dead worker held
                    return
                time.sleep(0.005)  # let the supervisor consume the stash
                waited += 0.005

    def _fail_crash_stash(self, reason: str) -> None:
        """Retire whatever a crashed, never-recovered worker was holding
        (drain/close with no supervisor attached, or a breaker-opened
        engine) — the shutdown path must strand nothing."""
        with self._cond:
            crash = self._crash
            self._crash = None
        if crash is None:
            return
        for e in crash[1]:
            if not e.handle.done():
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason=reason))

    def drain(self) -> None:
        """Graceful stop: no new admissions (post-drain submits resolve
        ``shutting_down``), but everything already accepted — queued and in
        flight — completes. Partial batches dispatch immediately. Terminal:
        the worker exits and is joined before this returns."""
        self._queue.close("engine draining (no new admissions)")
        self.start()  # a never-started engine still owes queued results
        with self._cond:
            if self._state == "running":
                self._state = "draining"
            self._cond.notify_all()
        self._join_worker()
        self._fail_crash_stash("serving worker died while draining")
        with self._cond:
            self._state = "closed"
            leftovers = self._former.take_all()
        for e in leftovers:
            # only reachable when the last worker generation died with no
            # supervisor left to respawn one — queued work still resolves
            self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                   reason="serving worker lost while "
                                          "draining"))
        self._finalize_obs()

    def close(self) -> None:
        """Fast stop: no new admissions, the batch in flight completes, and
        every still-queued request is retired with a clean
        ``shutting_down`` Result (never silently dropped). Idempotent."""
        self._queue.close("engine shutting down")
        with self._cond:
            if self._state == "closed":
                return
            self._state = "closing"
            leftovers = self._former.take_all()
            self._cond.notify_all()
        for e in leftovers:
            self._retire(e, Result(
                e.request.rid, STATUS_SHUTTING_DOWN,
                reason="engine closed before this request was scheduled"))
        self._join_worker()
        self._fail_crash_stash("serving worker died; engine closed before "
                               "recovery")
        with self._cond:
            self._state = "closed"
        self._finalize_obs()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- admission

    def submit(self, request: Request) -> ResultHandle:
        """Admit one request. Always returns a handle that will carry exactly
        one Result; overload / no-bucket / past-deadline submissions resolve
        immediately with ``rejected`` / ``expired`` status and a reason.

        Opens the request's span (a child of the caller's active span when
        there is one, else a fresh trace root), so every record the request
        ever produces — here and on the worker thread — shares one
        ``trace_id``."""
        ctx = obs_trace.child_of_current(f"serve.request.{request.rid}")
        with obs_trace.use(ctx):
            return self._submit(request, ctx)

    def _submit(self, request: Request, ctx) -> ResultHandle:
        faults.fire("serve.enqueue", path=str(request.rid))
        handle = ResultHandle(request)
        now = self._clock()
        bucket = pick_bucket(request.prompt.shape[0], request.steps,
                             self.buckets)
        if bucket is None:
            return self._refuse(handle, STATUS_REJECTED, (
                f"no bucket fits prompt_len={request.prompt.shape[0]} "
                f"steps={request.steps} (buckets {list(self.buckets)})"))
        # resolve the relative/default deadline to an absolute engine-clock
        # one, ONCE — a router failover or worker restart must not hand the
        # request a fresh budget
        if request.deadline is None:
            rel = request.deadline_s
            if rel is None:
                rel = get_config().serve_default_deadline_s
            if rel is not None:
                request.deadline = now + float(rel)
                request.deadline_s = None
        if request.deadline is not None and request.deadline <= now:
            return self._refuse(handle, STATUS_EXPIRED, (
                f"deadline {request.deadline} already passed at submission "
                f"(now {now})"))
        # deadline-aware admission: with service history (EWMA of ok
        # per-request seconds), a request whose projected completion behind
        # the current queue already overshoots its deadline is refused NOW —
        # cheaper for everyone than decoding it into a guaranteed expiry
        if request.deadline is not None and self._service_ewma > 0.0:
            projected = now + self._service_ewma * (
                1.0 + self._queue.count / self.max_batch)
            if projected > request.deadline:
                return self._refuse(handle, STATUS_REJECTED, (
                    f"deadline unmeetable: projected completion {projected:.3f}"
                    f" > deadline {request.deadline:.3f} at queue depth "
                    f"{self._queue.count} (service est "
                    f"{self._service_ewma:.3f}s)"))
        cost = bucket_kv_bytes(self.params, self.heads, bucket,
                               self.compute_dtype)
        reason = self._queue.try_admit(cost)
        if reason is not None:
            # a drain/close-shut gate is a deterministic shutting_down
            # Result (the caller can failover/retry elsewhere); overload
            # stays a rejection with the backpressure reason. Matching the
            # RETURNED reason (the close reason never changes once set)
            # keeps a "queue full" verdict that raced a concurrent drain
            # labeled as the backpressure it was
            if reason == self._queue.closed_reason:
                return self._refuse(handle, STATUS_SHUTTING_DOWN, reason)
            return self._refuse(handle, STATUS_REJECTED, reason)
        entry = _Entry(request, handle, bucket, cost, now, trace=ctx)
        with self._cond:
            if self._state != "running":
                admitted = False
            else:
                self._former.add(entry)
                if self._idle:
                    # an IDLE worker's heartbeat is legitimately old (it
                    # blocks in cond.wait): restart the watchdog window at
                    # admission so the wakeup isn't a false positive. A
                    # busy (possibly wedged) worker is NOT idle — traffic
                    # must never keep refreshing a dead worker's pulse
                    self._heartbeat = time.monotonic()
                self._cond.notify_all()
                admitted = True
        if not admitted:  # raced with drain()/close(): resolve, don't strand
            self._queue.release(cost)
            return self._refuse(handle, STATUS_SHUTTING_DOWN,
                                "engine is shutting down")
        self.metrics.record_enqueue(request.rid, bucket, self._queue.count)
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    def _refuse(self, handle, status: str, reason: str) -> ResultHandle:
        handle._set(Result(handle.request.rid, status, reason=reason))
        if status == STATUS_REJECTED:
            self.metrics.record_reject(handle.request.rid, reason)
        else:
            self.metrics.record_result(handle.request.rid, status)
        return handle

    # ----------------------------------------------------------- worker loop

    def _run(self, gen: int = 0) -> None:
        if self.rowlevel:
            self._run_rowlevel(gen)
        else:
            self._run_gang(gen)

    def _crash_handler(self, exc: BaseException, held: list,
                       gen: int) -> bool:
        """A worker generation is dying with ``held`` entries in hand.
        Supervised (``_on_crash`` installed, engine still serving): stash
        the undone entries for :meth:`_recover`, kick the supervisor, and
        return True — the worker exits quietly and the engine KEEPS
        accepting (requests queue up behind the restart). Unsupervised:
        the legacy contract — fail everything held plus the queued backlog
        with ``error`` Results so no submitter is ever stranded, and
        return False so the thread log still sees the exception. A
        SUPERSEDED generation dying late exits quietly without stashing —
        its entries were already requeued or failed by the recovery that
        superseded it, and a spurious stash would restart (and burn a
        retry attempt of) the healthy current generation."""
        cb = leftovers = None
        with self._cond:
            if self._gen != gen:
                return True  # stale straggler: recovery already ran
            undone = []
            seen = set()
            for e in held:
                if id(e) in seen or e.handle.done() or e.superseded:
                    continue
                seen.add(id(e))
                undone.append(e)
            supervised = (self._on_crash is not None
                          and self._state in ("running", "draining"))
            if supervised:
                self._crash = (exc, undone)
                cb = self._on_crash
            else:
                leftovers = self._former.take_all()
                self._state = "closing"
            self._inflight = []
            self._claimed = []
        self._flight_dump("worker-died")
        if supervised:
            try:
                cb()
            except Exception:  # the supervisor's poll loop still catches it
                pass
            return True
        for e in leftovers + undone:
            if not e.handle.done():
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason="serving worker died"))
        return False

    def _run_gang(self, gen: int) -> None:
        inflight = []
        try:
            while True:
                if self._gen == gen:  # a superseded straggler must never
                    self._heartbeat = time.monotonic()  # fake a live pulse
                faults.fire("serve.worker_crash",
                            path=threading.current_thread().name)
                batch = None
                with self._cond:
                    while True:
                        if self._gen != gen:
                            return  # superseded by a recovery
                        if self._state == "closing":
                            return
                        draining = self._state == "draining"
                        batch = self._former.next_batch(self._clock(),
                                                        force=draining)
                        if batch[0] is not None:
                            break
                        if draining:
                            return  # nothing pending; in-flight is us
                        hint = batch[1]
                        self._idle = True
                        if self._real_clock:
                            # submit/drain/close all notify — idle waits
                            # need no polling on the real clock
                            self._cond.wait(hint)
                        else:
                            # injected clock: cap the real wait so advances
                            # between polls are observed promptly
                            self._cond.wait(
                                _POLL_CAP_S if hint is None
                                else min(max(hint, 1e-4), _POLL_CAP_S))
                        self._idle = False
                        if self._gen == gen:
                            self._heartbeat = time.monotonic()
                    inflight = batch[1]
                    self._inflight = inflight
                self._execute(*batch)
                inflight = []
                with self._cond:
                    if self._gen == gen:  # never clobber a successor's
                        self._inflight = []  # in-flight mirror
        except BaseException as exc:  # worker death: recover or fail held
            if self._crash_handler(exc, inflight, gen):
                return
            raise

    def _retire(self, entry: _Entry, result: Result) -> None:
        if entry.superseded:
            return  # a retried twin owns this request (and its budget) now
        if entry.attempt > 1:
            result.metrics.setdefault("attempt", entry.attempt)
        try:
            entry.handle._set(result)
        except RuntimeError:
            # lost the exactly-once race to a stale worker generation's
            # twin — the winner released the budget and recorded the result
            return
        self._queue.release(entry.cost)
        if result.status == STATUS_OK:
            total = result.metrics.get("total_s")
            if total is not None:
                # EWMA of per-request SERVICE time — total minus queue wait
                # (the deadline-admission projection multiplies this by the
                # queue depth, so feeding end-to-end total_s would count
                # queueing twice and over-reject meetable deadlines, and a
                # single post-recovery straggler would poison the estimate)
                svc = max(total - (result.metrics.get("queue_s") or 0.0),
                          0.0)
                self._service_ewma = (svc if self._service_ewma == 0.0
                                      else 0.8 * self._service_ewma
                                      + 0.2 * svc)
        # re-activate the request's span on whichever thread retires it, so
        # the result record joins the request's trace
        with obs_trace.use(entry.trace):
            self.metrics.record_result(
                result.rid, result.status,
                bucket=result.metrics.get("bucket"),
                queue_s=result.metrics.get("queue_s"),
                total_s=result.metrics.get("total_s"),
                ttft_s=result.metrics.get("ttft_s"),
                attempt=entry.attempt)
        self.metrics.record_queue(self._queue.count,
                                  self._queue.bytes_in_flight)

    # ------------------------------------------------- row-level scheduler

    def _run_rowlevel(self, gen: int) -> None:
        """The slot-step loop: each iteration refills freed slots from the
        queue (prefill-on-admit), retires finished/expired rows, and runs
        one decode step per bucket with live rows. ``pools`` maps bucket ->
        SlotPool and persists across iterations — the KV slab never leaves
        the device between steps. ``self._pools``/``self._claimed`` mirror
        the worker's hands so a supervisor recovering a STUCK generation
        (watchdog timeout — the thread is alive but unreachable) can still
        find every in-flight entry to requeue."""
        pools: dict[tuple, object] = {}
        with self._cond:
            if self._gen != gen:
                return  # superseded before the first iteration: a late-
                # starting thread must not clobber its successor's mirrors
            self._pools = pools
        claimed: list[_Entry] = []
        try:
            while True:
                if self._gen == gen:  # a superseded straggler must never
                    self._heartbeat = time.monotonic()  # fake a live pulse
                faults.fire("serve.worker_crash",
                            path=threading.current_thread().name)
                claimed = []
                with self._cond:
                    while True:
                        if self._gen != gen:
                            return  # superseded by a recovery
                        if self._state == "closing":
                            # the live slots are the work in flight: finish
                            # them (close() already emptied the former)
                            if not any(p.live_slots()
                                       for p in pools.values()):
                                return
                            break
                        draining = self._state == "draining"
                        claimed = self._claim_rowlevel(pools)
                        if claimed or any(p.live_slots()
                                          for p in pools.values()):
                            break
                        if draining:
                            return  # nothing queued, nothing live
                        # no max_wait ripening in row-level mode: wait for
                        # a submit/drain/close notify (poll-capped under an
                        # injected clock, as in the gang loop)
                        self._idle = True
                        self._cond.wait(None if self._real_clock
                                        else _POLL_CAP_S)
                        self._idle = False
                        if self._gen == gen:
                            self._heartbeat = time.monotonic()
                    self._claimed = claimed
                self._admit_rowlevel(pools, claimed)
                claimed = []
                with self._cond:
                    if self._gen == gen:  # never clobber a successor's
                        self._claimed = []  # claimed mirror
                self._step_rowlevel(pools)
        except BaseException as exc:  # worker death: recover or fail held
            live = [p.entries[i] for p in pools.values()
                    for i in p.live_slots()]
            if self._crash_handler(exc, claimed + live, gen):
                return
            raise

    def _claim_rowlevel(self, pools) -> list[_Entry]:
        """Claim queued entries for free slots, per bucket (called under the
        engine lock; prefill happens outside it)."""
        claimed = []
        for bucket in self._former.pending_buckets():
            pool = pools.get(bucket)
            free = self.max_batch if pool is None \
                else len(pool.free_slots())
            if free:
                claimed.extend(self._former.take_for_bucket(bucket, free))
        return claimed

    def _admit_rowlevel(self, pools, claimed) -> None:
        """Prefill each claimed entry into a free slot of its bucket's pool
        (created lazily). The first token lands here — the row's TTFT."""
        from .batcher import SlotPool
        from ..models.transformer import lm_prefill_slot

        for e in claimed:
            # the worker runs every request's admission inside that
            # request's span: its prefill record — and any compile the
            # bridge observes during it — joins the request's trace
            with obs_trace.use(e.trace):
                now = self._clock()
                r = e.request
                dl = r.deadline
                p, s = e.bucket
                if dl is not None and dl <= now:
                    self._retire(e, Result(
                        r.rid, STATUS_EXPIRED,
                        reason=f"deadline {dl} passed before dispatch "
                               f"(dispatched at {now})",
                        metrics={"bucket": e.bucket,
                                 "queue_s": now - e.enq_t,
                                 "total_s": now - e.enq_t}))
                    continue
                e.queue_s = now - e.enq_t
                try:
                    faults.fire("serve.step", path=f"bucket-{p}x{s}")
                    pool = pools.get(e.bucket)
                    if pool is None:
                        pool = pools[e.bucket] = SlotPool(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype)
                        # no-warmup path: the bucket's cost model still
                        # lands with its first (lazy) compile
                        capture_bucket_costs(
                            self.params, self.heads, e.bucket,
                            self.max_batch, self.compute_dtype, self.moe,
                            rowlevel=True, key=self._prog_key(e.bucket))
                    slot = pool.free_slots()[0]
                    prompt = np.zeros((p,), np.int32)
                    n = r.prompt.shape[0]
                    prompt[:n] = r.prompt
                    t0 = time.perf_counter()
                    caches, tokens, first = lm_prefill_slot(
                        self.params, pool.caches, pool.tokens, slot, prompt,
                        n, heads=self.heads, max_len=p + s, seed=r.seed,
                        temperature=r.temperature, top_p=r.top_p,
                        top_k=r.top_k, compute_dtype=self.compute_dtype,
                        moe=self.moe)
                    first = int(first)  # device sync: the first token exists
                    wall = time.perf_counter() - t0
                except Exception as exc:
                    self._admit_failure(pools, e, exc)
                    continue
                pool.caches, pool.tokens = caches, tokens
                pool.assign(slot, e)
                pool.ttft_s[slot] = self._clock() - e.enq_t
                self.metrics.record_prefill(
                    e.bucket, wall, rid=r.rid,
                    program_key=self._prog_key(e.bucket))
                self.flight.record(
                    "prefill", bucket=[p, s], slot=slot, rid=r.rid,
                    seconds=wall, queue_depth=self._queue.count,
                    compiles=_compile_count())
                if r.steps == 1 or (r.eos is not None and first == r.eos):
                    self._retire_row(pool, slot, STATUS_OK, self._clock())
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _step_rowlevel(self, pools) -> None:
        """Retire expired live rows, then run ONE decode step per bucket
        with live rows and retire rows that finished on it. All buckets'
        step programs are DISPATCHED before any result is awaited — JAX
        dispatch is async, so bucket B's device work overlaps the host
        round-trip for bucket A instead of serializing behind it."""
        from ..models.transformer import lm_decode_rows

        launched = []
        for bucket, pool in list(pools.items()):
            now = self._clock()
            for i in pool.live_slots():
                dl = pool.entries[i].request.deadline
                if dl is not None and dl <= now:
                    self._retire_row(
                        pool, i, STATUS_EXPIRED, now,
                        reason=f"deadline {dl} passed mid-decode "
                               f"(now {now})")
            live = pool.live_slots()
            if not live:
                continue
            p, s = bucket
            try:
                faults.fire("serve.decode_step", path=f"bucket-{p}x{s}")
                t0 = time.perf_counter()
                caches, tokens, nxt = lm_decode_rows(
                    self.params, pool.caches, pool.tokens, pool.positions,
                    pool.steps_done, pool.seeds, pool.temperature,
                    pool.top_p, pool.top_k, heads=self.heads,
                    max_len=pool.max_len, compute_dtype=self.compute_dtype,
                    moe=self.moe)
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            pool.caches, pool.tokens = caches, tokens
            launched.append((bucket, pool, live, t0, nxt))
        for bucket, pool, live, t0, nxt in launched:
            try:
                nxt = np.asarray(nxt)  # sync; the per-row emitted tokens
            except Exception as exc:
                self._fail_pool(pools, bucket, exc)
                continue
            wall = time.perf_counter() - t0
            self.metrics.record_step(bucket, len(live), self.max_batch, wall,
                                     program_key=self._prog_key(bucket))
            self.flight.record(
                "step", bucket=list(bucket), rows=len(live),
                seconds=wall, queue_depth=self._queue.count,
                compiles=_compile_count())
            now = self._clock()
            host_tokens = None  # one slab fetch shared by this step's retirees
            for i in live:
                pool.positions[i] += 1
                pool.steps_done[i] += 1
                r = pool.entries[i].request
                if ((r.eos is not None and int(nxt[i]) == r.eos)
                        or int(pool.steps_done[i]) >= r.steps):
                    if host_tokens is None:
                        host_tokens = np.asarray(pool.tokens)
                    self._retire_row(pool, i, STATUS_OK, now,
                                     host_tokens=host_tokens)
        self._live_rows = sum(len(p.live_slots()) for p in pools.values())

    def _retire_row(self, pool, slot: int, status: str, now: float,
                    reason: str = "", host_tokens=None) -> None:
        """Retire one slot's row and free the slot — the ONLY path a live
        slot leaves the pool by, so every terminal status releases the
        admission budget exactly once. ``host_tokens`` lets a step that
        retires several rows share ONE slab fetch (the transfer is whole-slab
        either way: a per-slot device gather would compile one tiny
        executable per static slot index and break the
        zero-compiles-under-traffic guarantee)."""
        e = pool.entries[slot]
        metrics = {"bucket": pool.bucket, "slot": slot,
                   "queue_s": e.queue_s, "ttft_s": pool.ttft_s[slot],
                   "total_s": now - e.enq_t}
        if status == STATUS_OK:
            n = int(pool.lengths[slot])
            emitted = int(pool.steps_done[slot])
            if host_tokens is None:
                host_tokens = np.asarray(pool.tokens)
            toks = host_tokens[slot, : n + emitted].copy()
            result = Result(e.request.rid, STATUS_OK, tokens=toks,
                            metrics=metrics)
        else:
            result = Result(e.request.rid, status, reason=reason,
                            metrics=metrics)
        pool.release(slot)
        self._retire(e, result)

    def _requeue(self, entry: _Entry, reason: str) -> None:
        """Park a failed attempt back in the former for its next attempt
        (the caller checked ``attempts_left``). The admission reservation
        is CARRIED — never released, never re-charged — so a parked retry
        holds exactly its one slot of the queue depth and KV HBM budget.
        On a shutting-down engine the retry would never be claimed, so it
        retires with the failure instead of stranding."""
        twin = entry.retry()
        with self._cond:
            requeued = self._state in ("running", "draining")
            if requeued:
                self._former.add(twin)
                self._cond.notify_all()
        if not requeued:
            self._retire(twin, Result(
                twin.request.rid, STATUS_ERROR,
                reason=f"{reason} (engine shutting down before retry)"))
            return
        with obs_trace.use(entry.trace):
            self.metrics.record_retry(entry.request.rid, twin.attempt,
                                      entry.request.max_attempts, reason)

    def _fail_pool(self, pools, bucket, exc: Exception) -> None:
        """A decode step died: rows with attempt budget left requeue for a
        transparent retry; the rest fail with error Results. Either way
        ONLY that step's live rows are touched and the slot pool stays
        consistent (slots freed, budget accounted exactly once). If the
        failed call consumed the donated slab (a genuine post-dispatch
        failure, not an injected fault raised before launch), drop the pool
        — it is rebuilt zeroed on the next admission."""
        pool = pools[bucket]
        reason = f"decode step failed: {type(exc).__name__}: {exc}"
        self.flight.record("decode_fault", bucket=list(bucket),
                           rows=len(pool.live_slots()), error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        now = self._clock()
        for i in pool.live_slots():
            e = pool.entries[i]
            if e.attempts_left():
                pool.release(i)
                self._requeue(e, reason)
            else:
                self._retire_row(pool, i, STATUS_ERROR, now, reason=reason)
        if self._slab_lost(pool):
            pools.pop(bucket)
        # the black box lands NOW, while the final iterations are still in
        # the ring — the post-mortem for exactly this failure class
        self._flight_dump("decode-step-failed")

    def _admit_failure(self, pools, entry: _Entry, exc: Exception) -> None:
        """A prefill died: the entry being admitted retries within its
        attempt budget, else gets an error Result; co-resident live rows
        survive unless the failed call consumed the donated slab, in which
        case they fail/retry too and the pool is dropped."""
        now = self._clock()
        reason = f"prefill failed: {type(exc).__name__}: {exc}"
        if entry.attempts_left():
            self._requeue(entry, reason)
        else:
            self._retire(entry, Result(
                entry.request.rid, STATUS_ERROR, reason=reason,
                metrics={"bucket": entry.bucket, "queue_s": entry.queue_s,
                         "total_s": now - entry.enq_t}))
        self.flight.record("prefill_fault", bucket=list(entry.bucket),
                           rid=entry.request.rid, error=reason,
                           queue_depth=self._queue.count,
                           compiles=_compile_count())
        pool = pools.get(entry.bucket)
        if pool is not None and self._slab_lost(pool):
            lost = f"slab lost to a failed prefill: {reason}"
            for i in pool.live_slots():
                e = pool.entries[i]
                if e.attempts_left():
                    pool.release(i)
                    self._requeue(e, lost)
                else:
                    self._retire_row(pool, i, STATUS_ERROR, now, reason=lost)
            pools.pop(entry.bucket)
        self._flight_dump("prefill-failed")

    # ------------------------------------------------- supervised recovery

    def attach_supervisor(self, on_crash) -> None:
        """Install the supervisor's crash kick: while set, a dying worker
        stashes its undone entries for :meth:`_recover` instead of failing
        them, and calls ``on_crash()`` so recovery starts promptly."""
        self._on_crash = on_crash

    def detach_supervisor(self) -> None:
        self._on_crash = None

    def _recover(self, reason: str, respawn: bool = True) -> dict:
        """Recover from a dead or stuck worker generation: supersede it
        (``_gen`` bump — a stale thread exits at its next check and can
        never retire a superseded entry), requeue every undone in-flight
        entry within its attempt budget (the rest fail with ``error``),
        drop the slot pools — the slab state died with the worker; pools
        rebuild zeroed on the next admission, the PR 4 ``is_deleted``
        pool-rebuild path generalized — and spawn a fresh worker thread.
        Queued (former) entries are untouched: they were never in flight.
        ``respawn=False`` is the breaker's terminal path: supersede and
        fail everything held, mark the old thread abandoned (it may be
        wedged in a device call forever — shutdown must not join it), and
        spawn nothing. Returns counts for the supervisor's EventLog
        record."""
        failed, twins = [], []
        with self._cond:
            self._gen += 1
            gen = self._gen
            alive = respawn and self._state in ("running", "draining")
            if self._crash is not None:
                stash = list(self._crash[1])
                self._crash = None
            else:
                # stuck path: steal what the stale (still-alive) worker
                # holds — its pools/claimed/inflight mirrors. The straggler
                # mutates pool.entries WITHOUT this lock, so snapshot each
                # list and skip holes rather than indexing live_slots()
                # (an entry it retires concurrently shows up handle-done
                # below and is skipped; one it frees mid-scan must not
                # crash the recovery)
                stash = [e for p in self._pools.values()
                         for e in list(p.entries) if e is not None]
                stash += list(self._claimed) + list(self._inflight)
            self._pools = {}
            self._inflight = []
            self._claimed = []
            seen = set()
            for e in stash:
                if id(e) in seen or e.handle.done() or e.superseded:
                    continue
                seen.add(id(e))
                if alive and e.attempts_left():
                    twin = e.retry()
                    self._former.add(twin)
                    twins.append(twin)
                else:
                    failed.append(e)
            if alive:
                self._thread = self._make_thread(gen)
            elif not respawn:
                self._abandoned = self._thread
            started = self._started
            # grant the fresh generation a full watchdog window: without
            # this the stale generation's last stamp re-trips the watchdog
            # before the new worker's first iteration, and repeated
            # recoveries burn the attempt budget on a worker that never got
            # to run
            self._heartbeat = time.monotonic()
            self._cond.notify_all()
        for e in failed:
            self._retire(e, Result(
                e.request.rid, STATUS_ERROR,
                reason=f"worker lost and attempt budget exhausted: "
                       f"{reason}"))
        for t in twins:
            with obs_trace.use(t.trace):
                self.metrics.record_retry(t.request.rid, t.attempt,
                                          t.request.max_attempts, reason)
        self._live_rows = 0
        if alive and started:
            self._thread.start()
        return {"gen": gen, "requeued": len(twins), "failed": len(failed)}

    @staticmethod
    def _slab_lost(pool) -> bool:
        """True when a failed donated call consumed the pool's arrays (the
        backends that implement donation delete the inputs on dispatch;
        injected faults raise before the call and never trip this)."""
        deleted = getattr(pool.tokens, "is_deleted", None)
        return bool(deleted and deleted())

    # ---------------------------------------------------- gang scheduler

    def _execute(self, group_key, entries) -> None:
        """One engine cycle: expire stale rows, prefill live rows into the
        bucket's fixed-width slot batch, run the compiled program, retire."""
        import jax

        from ..models.transformer import lm_generate_batch

        bucket, temperature, top_p, top_k, _ = group_key
        # sampled groups share one seed (the former keys on it); greedy
        # groups ignore the key entirely, so any member's seed serves
        p, s = bucket
        dispatch_t = self._clock()
        live = []
        for e in entries:
            dl = e.request.deadline
            if dl is not None and dl <= dispatch_t:
                self._retire(e, Result(
                    e.request.rid, STATUS_EXPIRED,
                    reason=f"deadline {dl} passed before dispatch "
                           f"(dispatched at {dispatch_t})",
                    metrics={"bucket": bucket,
                             "queue_s": dispatch_t - e.enq_t,
                             "total_s": dispatch_t - e.enq_t}))
            else:
                live.append(e)
        if not live:
            return
        self._live_rows = len(live)
        capture_bucket_costs(self.params, self.heads, bucket, self.max_batch,
                             self.compute_dtype, self.moe, rowlevel=False,
                             key=self._prog_key(bucket))
        try:
            faults.fire("serve.step", path=f"bucket-{p}x{s}")
            # prefill the claimed slots; free slots carry inert dummy rows so
            # the batch shape (and the compiled program) never varies
            prompts = np.zeros((self.max_batch, p), np.int32)
            lengths = np.ones((self.max_batch,), np.int32)
            for i, e in enumerate(live):
                n = e.request.prompt.shape[0]
                prompts[i, :n] = e.request.prompt
                lengths[i] = n
            key = jax.random.key(live[0].request.seed)
            t0 = time.perf_counter()
            out = np.asarray(lm_generate_batch(
                self.params, prompts, lengths, key, heads=self.heads,
                max_len=p + s, steps=s, temperature=temperature, top_p=top_p,
                top_k=top_k, compute_dtype=self.compute_dtype, moe=self.moe))
            wall = time.perf_counter() - t0
        except Exception as exc:
            reason = f"batch failed: {type(exc).__name__}: {exc}"
            self.flight.record("batch_fault", bucket=[p, s], rows=len(live),
                               error=reason, queue_depth=self._queue.count,
                               compiles=_compile_count())
            done_t = self._clock()
            for e in live:
                if e.attempts_left():
                    self._requeue(e, reason)
                else:
                    self._retire(e, Result(
                        e.request.rid, STATUS_ERROR, reason=reason,
                        metrics={"bucket": bucket,
                                 "queue_s": dispatch_t - e.enq_t,
                                 "total_s": done_t - e.enq_t}))
            self._live_rows = 0
            self._flight_dump("batch-failed")
            return
        done_t = self._clock()
        for i, e in enumerate(live):
            n = e.request.prompt.shape[0]
            self._retire(e, Result(
                e.request.rid, STATUS_OK,
                tokens=out[i, : n + e.request.steps].copy(),
                metrics={"bucket": bucket, "queue_s": dispatch_t - e.enq_t,
                         "ttft_s": done_t - e.enq_t,
                         "total_s": done_t - e.enq_t}))
        self.metrics.record_batch(bucket, len(live), self.max_batch,
                                  len(live) * s, wall,
                                  program_key=self._prog_key(bucket))
        self.flight.record("batch", bucket=[p, s], rows=len(live),
                           seconds=wall, queue_depth=self._queue.count,
                           compiles=_compile_count())
        self._live_rows = 0
