"""The continuous-batching serving engine: one worker thread, compiled decode.

:class:`ServeEngine` is the front half of an inference stack over the
library's batched decode (:func:`~marlin_tpu.models.transformer
.lm_generate_batch`, "the serving shape"): concurrent callers ``submit``
requests; an admission gate (queue depth + in-flight KV-cache HBM budget,
request.py) rejects overload with a reason; a batch former (batcher.py)
buckets prompts onto a small static shape set so each bucket compiles ONCE;
and a single worker thread runs the continuous loop —

    claim a batch of slots  →  retire deadline-expired rows  →  prefill the
    live rows + run the bucket's compiled decode program (one fused XLA
    program per bucket)  →  retire finished rows with Results  →  repeat

Scheduling is gang-style: the ``max_batch`` slot rows of one bucket launch
and land together (free slots carry inert dummy rows so the batch shape —
and therefore the compiled program — never varies). That trades some
tail-row latency for two hard guarantees the acceptance tests assert: a
bounded compile count (≤ one program per bucket for default sampling) and
bit-identical outputs to calling ``lm_generate_batch`` directly on the same
bucket shape. Row-level continuous batching (admitting into a running
batch's free slots mid-decode) is the documented next step
(docs/serving.md).

Lifecycle: ``drain()`` stops admission and completes everything already
accepted (partial batches dispatch immediately rather than waiting out
``max_wait``); ``close()`` stops admission, finishes the batch in flight,
and retires everything still queued with a clean ``shutting_down`` Result.
Both are terminal and idempotent; the worker thread (named
``marlin-serve-*`` — the conftest leak fixture watches the prefix) is joined
before either returns. Chaos hooks: ``serve.enqueue`` fires in ``submit``,
``serve.step`` fires before each batch launch (utils/faults.py) — a fault
there fails that batch's requests with ``error`` Results and the engine
keeps serving.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from ..config import get_config
from ..utils import faults
from .batcher import (BatchFormer, bucket_kv_bytes, normalize_buckets,
                      pick_bucket, warmup_buckets)
from .metrics import ServeMetrics
from .request import (STATUS_ERROR, STATUS_EXPIRED, STATUS_OK,
                      STATUS_REJECTED, STATUS_SHUTTING_DOWN, AdmissionQueue,
                      Request, Result, ResultHandle)

__all__ = ["ServeEngine"]

_engine_ids = itertools.count()

# real-seconds cap on one condition wait under an INJECTED clock: bounds how
# stale the worker's view of a fake clock can get (tests advance it between
# polls). Real-clock engines never poll — they wait on the condition until
# notified or the exact max_wait hint elapses.
_POLL_CAP_S = 0.02


class _Entry:
    """One admitted request riding through the former to a batch slot."""

    __slots__ = ("request", "handle", "bucket", "cost", "enq_t")

    def __init__(self, request, handle, bucket, cost, enq_t):
        self.request = request
        self.handle = handle
        self.bucket = bucket
        self.cost = cost
        self.enq_t = enq_t


class ServeEngine:
    """Continuous-batching inference engine over a trained LM.

    ``params``/``heads``/``compute_dtype``/``moe`` describe the model exactly
    as :func:`lm_generate_batch` takes them. Knobs default from the global
    config: ``buckets`` (``serve_buckets``), ``max_batch``
    (``serve_max_batch``), ``max_wait_ms`` (``serve_max_wait_ms``),
    ``queue_depth`` (``serve_queue_depth``); ``hbm_budget_bytes`` defaults to
    the planner's :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (0
    disables the byte gate). ``clock`` is the engine's *policy* clock
    (deadlines, max_wait, latency metrics) — injectable for deterministic
    tests; wall throughput is always measured on the real clock. ``log``
    overrides the default EventLog for ``serve`` records.

    Usable as a context manager (``close()`` on exit); ``start=False`` defers
    the worker thread so tests can stage a queue before any dispatch."""

    def __init__(self, params: dict, heads: int, *, buckets=None,
                 max_batch: int | None = None,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 hbm_budget_bytes: int | None = None,
                 compute_dtype: str | None = None, moe: tuple | None = None,
                 clock=time.monotonic, log=None, start: bool = True):
        cfg = get_config()
        self.params = params
        self.heads = heads
        self.compute_dtype = compute_dtype
        self.moe = moe
        self.buckets = normalize_buckets(
            cfg.serve_buckets if buckets is None else buckets)
        self.max_batch = int(cfg.serve_max_batch if max_batch is None
                             else max_batch)
        wait_ms = cfg.serve_max_wait_ms if max_wait_ms is None else max_wait_ms
        depth = int(cfg.serve_queue_depth if queue_depth is None
                    else queue_depth)
        if hbm_budget_bytes is None:
            from ..models.planner import usable_hbm_bytes

            hbm_budget_bytes = usable_hbm_bytes()
        self._clock = clock
        self._real_clock = clock is time.monotonic
        self.metrics = ServeMetrics(log=log)
        self._queue = AdmissionQueue(depth, hbm_budget_bytes)
        self._cond = threading.Condition()
        self._former = BatchFormer(self.buckets, self.max_batch,
                                   max_wait=float(wait_ms) / 1e3)
        self._state = "running"  # running | draining | closing | closed
        self._started = False
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"marlin-serve-{next(_engine_ids)}")
        if start:
            self.start()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the worker thread (idempotent; no-op once shutting down)."""
        with self._cond:
            if self._started or self._state != "running":
                return
            self._started = True
        self._thread.start()

    def warmup(self) -> int:
        """Compile every bucket's full-width batch program before traffic
        (one dummy execution per bucket; see batcher.warmup_buckets)."""
        return warmup_buckets(self.params, self.heads, self.buckets,
                              self.max_batch, self.compute_dtype, self.moe)

    def pending(self) -> int:
        """Requests admitted but not yet retired (queued + in flight)."""
        return self._queue.count

    def drain(self) -> None:
        """Graceful stop: no new admissions (rejections say "draining"), but
        everything already accepted — queued and in flight — completes.
        Partial batches dispatch immediately. Terminal: the worker exits and
        is joined before this returns."""
        self._queue.close("engine draining (no new admissions)")
        self.start()  # a never-started engine still owes queued results
        with self._cond:
            if self._state == "running":
                self._state = "draining"
            self._cond.notify_all()
        if self._started:
            self._thread.join()
        with self._cond:
            self._state = "closed"

    def close(self) -> None:
        """Fast stop: no new admissions, the batch in flight completes, and
        every still-queued request is retired with a clean
        ``shutting_down`` Result (never silently dropped). Idempotent."""
        self._queue.close("engine shutting down")
        with self._cond:
            if self._state == "closed":
                return
            self._state = "closing"
            leftovers = self._former.take_all()
            self._cond.notify_all()
        for e in leftovers:
            self._retire(e, Result(
                e.request.rid, STATUS_SHUTTING_DOWN,
                reason="engine closed before this request was scheduled"))
        if self._started:
            self._thread.join()
        with self._cond:
            self._state = "closed"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- admission

    def submit(self, request: Request) -> ResultHandle:
        """Admit one request. Always returns a handle that will carry exactly
        one Result; overload / no-bucket / past-deadline submissions resolve
        immediately with ``rejected`` / ``expired`` status and a reason."""
        faults.fire("serve.enqueue", path=str(request.rid))
        handle = ResultHandle(request)
        now = self._clock()
        bucket = pick_bucket(request.prompt.shape[0], request.steps,
                             self.buckets)
        if bucket is None:
            return self._refuse(handle, STATUS_REJECTED, (
                f"no bucket fits prompt_len={request.prompt.shape[0]} "
                f"steps={request.steps} (buckets {list(self.buckets)})"))
        if request.deadline is not None and request.deadline <= now:
            return self._refuse(handle, STATUS_EXPIRED, (
                f"deadline {request.deadline} already passed at submission "
                f"(now {now})"))
        cost = bucket_kv_bytes(self.params, self.heads, bucket,
                               self.compute_dtype)
        reason = self._queue.try_admit(cost)
        if reason is not None:
            return self._refuse(handle, STATUS_REJECTED, reason)
        entry = _Entry(request, handle, bucket, cost, now)
        with self._cond:
            if self._state != "running":
                admitted = False
            else:
                self._former.add(entry)
                self._cond.notify_all()
                admitted = True
        if not admitted:  # raced with close(): resolve, don't strand
            self._queue.release(cost)
            return self._refuse(handle, STATUS_REJECTED,
                                "engine is shutting down")
        self.metrics.record_enqueue(request.rid, bucket, self._queue.count)
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    def _refuse(self, handle, status: str, reason: str) -> ResultHandle:
        handle._set(Result(handle.request.rid, status, reason=reason))
        if status == STATUS_REJECTED:
            self.metrics.record_reject(handle.request.rid, reason)
        else:
            self.metrics.record_result(handle.request.rid, status)
        return handle

    # ----------------------------------------------------------- worker loop

    def _run(self) -> None:
        inflight = []
        try:
            while True:
                batch = None
                with self._cond:
                    while True:
                        if self._state == "closing":
                            return
                        draining = self._state == "draining"
                        batch = self._former.next_batch(self._clock(),
                                                        force=draining)
                        if batch[0] is not None:
                            break
                        if draining:
                            return  # nothing pending; in-flight is us
                        hint = batch[1]
                        if self._real_clock:
                            # submit/drain/close all notify — idle waits
                            # need no polling on the real clock
                            self._cond.wait(hint)
                        else:
                            # injected clock: cap the real wait so advances
                            # between polls are observed promptly
                            self._cond.wait(
                                _POLL_CAP_S if hint is None
                                else min(max(hint, 1e-4), _POLL_CAP_S))
                inflight = batch[1]
                self._execute(*batch)
                inflight = []
        except BaseException:  # pragma: no cover - scheduler invariant
            # a dying worker must not strand submitters on .result(): fail
            # the batch it was holding plus everything still queued, then
            # re-raise for the thread log (_execute absorbs ordinary
            # Exceptions itself; this path is KeyboardInterrupt-class)
            with self._cond:
                leftovers = self._former.take_all()
                self._state = "closing"
            for e in leftovers + [e for e in inflight
                                  if not e.handle.done()]:
                self._retire(e, Result(e.request.rid, STATUS_ERROR,
                                       reason="serving worker died"))
            raise

    def _retire(self, entry: _Entry, result: Result) -> None:
        entry.handle._set(result)
        self._queue.release(entry.cost)
        self.metrics.record_result(
            result.rid, result.status, bucket=result.metrics.get("bucket"),
            queue_s=result.metrics.get("queue_s"),
            total_s=result.metrics.get("total_s"))

    def _execute(self, group_key, entries) -> None:
        """One engine cycle: expire stale rows, prefill live rows into the
        bucket's fixed-width slot batch, run the compiled program, retire."""
        import jax

        from ..models.transformer import lm_generate_batch

        bucket, temperature, top_p, top_k, _ = group_key
        # sampled groups share one seed (the former keys on it); greedy
        # groups ignore the key entirely, so any member's seed serves
        p, s = bucket
        dispatch_t = self._clock()
        live = []
        for e in entries:
            dl = e.request.deadline
            if dl is not None and dl <= dispatch_t:
                self._retire(e, Result(
                    e.request.rid, STATUS_EXPIRED,
                    reason=f"deadline {dl} passed before dispatch "
                           f"(dispatched at {dispatch_t})",
                    metrics={"bucket": bucket,
                             "queue_s": dispatch_t - e.enq_t,
                             "total_s": dispatch_t - e.enq_t}))
            else:
                live.append(e)
        if not live:
            return
        try:
            faults.fire("serve.step", path=f"bucket-{p}x{s}")
            # prefill the claimed slots; free slots carry inert dummy rows so
            # the batch shape (and the compiled program) never varies
            prompts = np.zeros((self.max_batch, p), np.int32)
            lengths = np.ones((self.max_batch,), np.int32)
            for i, e in enumerate(live):
                n = e.request.prompt.shape[0]
                prompts[i, :n] = e.request.prompt
                lengths[i] = n
            key = jax.random.key(live[0].request.seed)
            t0 = time.perf_counter()
            out = np.asarray(lm_generate_batch(
                self.params, prompts, lengths, key, heads=self.heads,
                max_len=p + s, steps=s, temperature=temperature, top_p=top_p,
                top_k=top_k, compute_dtype=self.compute_dtype, moe=self.moe))
            wall = time.perf_counter() - t0
        except Exception as exc:
            reason = f"batch failed: {type(exc).__name__}: {exc}"
            done_t = self._clock()
            for e in live:
                self._retire(e, Result(
                    e.request.rid, STATUS_ERROR, reason=reason,
                    metrics={"bucket": bucket,
                             "queue_s": dispatch_t - e.enq_t,
                             "total_s": done_t - e.enq_t}))
            return
        done_t = self._clock()
        for i, e in enumerate(live):
            n = e.request.prompt.shape[0]
            self._retire(e, Result(
                e.request.rid, STATUS_OK,
                tokens=out[i, : n + e.request.steps].copy(),
                metrics={"bucket": bucket, "queue_s": dispatch_t - e.enq_t,
                         "ttft_s": done_t - e.enq_t,
                         "total_s": done_t - e.enq_t}))
        self.metrics.record_batch(bucket, len(live), self.max_batch,
                                  len(live) * s, wall)
