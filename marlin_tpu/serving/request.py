"""Request/Result contracts and bounded admission for the serving engine.

The reference delegated all request scheduling to Spark (SURVEY.md §0); the
TPU-native rebuild supplies its own front half, and this module is its wire
format: a :class:`Request` carries one prompt plus its serving policy
(deadline, priority, sampling knobs), a :class:`Result` is the exactly-once
answer every submitted request eventually receives — completed, rejected,
expired, errored, or shut down, but never silently dropped — and
:class:`AdmissionQueue` is the backpressure gate in front of the batch
former: a submission is admitted only while both the queue-depth bound and
the in-flight KV-cache HBM budget (defaulting to the planner's measured
:func:`~marlin_tpu.models.planner.usable_hbm_bytes`) have room, and a full
queue rejects with a reason instead of blocking the caller.

Everything here is stdlib + numpy; the engine (engine.py) owns the JAX side.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any

import numpy as np

__all__ = ["Request", "Result", "ResultHandle", "AdmissionQueue",
           "SHED_REASON_PREFIX",
           "STATUS_OK", "STATUS_REJECTED", "STATUS_EXPIRED", "STATUS_ERROR",
           "STATUS_SHUTTING_DOWN"]

#: rejection reasons produced by SLO-driven load shedding start with this —
#: the engine keys its marlin_slo_shed_total accounting off the prefix and
#: callers can distinguish "shed under breach, retry elsewhere/later" from
#: a structurally full queue
SHED_REASON_PREFIX = "shedding load"

#: terminal statuses a :class:`Result` can carry
STATUS_OK = "ok"                          # decoded; ``tokens`` is set
STATUS_REJECTED = "rejected"              # refused at admission (see reason)
STATUS_EXPIRED = "expired"                # deadline passed before decode
STATUS_ERROR = "error"                    # the batch it rode in failed
STATUS_SHUTTING_DOWN = "shutting_down"    # queued at close(); never decoded

_rid_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One serving request.

    ``program`` names the :class:`~marlin_tpu.serving.programs
    .BucketProgram` that answers it — ``"lm"`` (the default, token
    generation) or any program the engine was constructed with (``"als"``,
    ``"pagerank"``, ``"classify"``, ...). Non-LM programs take their input
    through ``payload`` (a small host-side dict, e.g. ``{"user": 7,
    "k": 10}``) and need no ``prompt``; every request, whatever its
    program, shares the same deadline/priority/retry policy surface and
    the same exactly-once :class:`Result` contract.

    ``prompt`` is a 1-D int32 token array (required for ``program="lm"``,
    ignored elsewhere); ``steps`` how many tokens to generate (the bucket
    rounds it up for execution, the :class:`Result` slices back down). ``deadline`` is an *absolute* time on the engine's
    clock (``None`` = no deadline): a request whose deadline has passed when
    its batch forms is retired with :data:`STATUS_EXPIRED` rather than
    decoded late. ``priority`` orders dispatch within a bucket (higher
    first; FIFO among equals). Sampling knobs mirror
    :func:`~marlin_tpu.models.transformer.lm_generate_batch`.

    ``seed`` feeds the sampling PRNG: each row draws its own
    ``fold_in(key(seed), step)`` stream, so a sampled output replays from
    (seed, prompt) alone — composition-independent across batch makeup,
    bucket padding, page boundaries, and prefix sharing — and any knob mix
    shares a decode step (the knobs are per-row traced). Greedy decode,
    the default, ignores the key entirely (docs/serving.md).

    ``eos`` names a stop token: a row retires the step it EMITS that token
    (its slot refills from the queue on the next step), so
    ``Result.tokens`` may carry fewer than ``steps`` generated tokens,
    ending with the eos. Detection looks only at GENERATED tokens — an
    eos-valued token inside the prompt or its pad region never stops a
    row.

    ``deadline_s`` is the *relative* form of ``deadline``: seconds from
    submission, resolved to an absolute engine-clock deadline inside
    ``submit()`` (at most one of the two may be set; with neither set,
    ``config.serve_default_deadline_s`` applies when configured). The
    resolved deadline survives router failover and worker restarts — a
    retried attempt does not get a fresh budget.

    ``max_attempts`` is the request's total execution budget: rows failed
    by a decode-step/prefill fault or lost to a worker crash are
    transparently re-queued until they have consumed ``max_attempts``
    attempts, then retired with an ``error`` Result. The default (1) keeps
    the pre-resilience semantics — first failure is final. Replays are
    attempt-independent: greedy retries are bit-identical to an
    uninterrupted run, sampled retries re-derive the same per-row
    ``fold_in(key(seed), step)`` stream (docs/robustness.md)."""

    prompt: Any = None
    steps: int = 1
    deadline: float | None = None
    deadline_s: float | None = None
    max_attempts: int = 1
    priority: int = 0
    temperature: float = 0.0
    top_p: float | None = None
    top_k: int | None = None
    seed: int = 0
    eos: int | None = None
    program: str = "lm"
    payload: Any = None
    rid: int = dataclasses.field(default_factory=lambda: next(_rid_counter))

    def __post_init__(self):
        if self.prompt is None:
            if self.program == "lm":
                raise ValueError("program 'lm' needs a token prompt")
        else:
            self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
            if self.prompt.size < 1:
                raise ValueError("empty prompt")
        if self.steps < 1:
            raise ValueError(f"steps must be >= 1, got {self.steps}")
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.deadline is not None and self.deadline_s is not None:
            raise ValueError("set deadline (absolute) or deadline_s "
                             "(relative to submit), not both")


@dataclasses.dataclass
class Result:
    """The exactly-once answer to one :class:`Request`. ``tokens`` (status
    :data:`STATUS_OK` only) is prompt + the generated tokens — exactly the
    requested ``steps`` of them, or fewer ending in the stop token when
    ``Request.eos`` fired under the row-level scheduler. Non-LM programs
    answer through ``value`` instead (the program-shaped payload, e.g.
    ALS's ``{"items": ..., "scores": ...}``). ``metrics``
    carries the per-request timings on the engine clock (``queue_s``,
    ``ttft_s`` — time to the first generated token, which row-level prefill
    makes genuinely earlier than ``total_s``), the ``bucket`` that executed
    it, and under row-level scheduling the ``slot`` it occupied."""

    rid: int
    status: str
    tokens: np.ndarray | None = None
    reason: str = ""
    metrics: dict = dataclasses.field(default_factory=dict)
    value: Any = None

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class ResultHandle:
    """Caller-side future for one request: ``result(timeout)`` blocks until
    the engine retires the request. The engine sets each handle exactly once
    — a second ``_set`` is a scheduler bug and raises."""

    def __init__(self, request: Request):
        self.request = request
        self._event = threading.Event()
        self._result: Result | None = None

    def _set(self, result: Result) -> None:
        if self._event.is_set():  # pragma: no cover - guards engine bugs
            raise RuntimeError(
                f"request {self.request.rid} retired twice "
                f"(had {self._result.status}, got {result.status})")
        self._result = result
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> Result:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request.rid} not done within {timeout}s")
        return self._result

    def __repr__(self):
        state = self._result.status if self.done() else "pending"
        return f"ResultHandle(rid={self.request.rid}, {state})"


class AdmissionQueue:
    """Depth + HBM-byte admission gate with reject-with-reason backpressure.

    Tracks every admitted-but-not-retired request: ``depth`` bounds how many
    may be pending or in flight at once, ``budget_bytes`` bounds the summed
    KV-cache cost the engine would hold if everything admitted ran (cost per
    request = its bucket row's cache bytes, :func:`..serving.batcher
    .bucket_kv_bytes`). ``try_admit`` returns ``None`` on admission or the
    rejection reason string; ``release`` returns the request's capacity when
    the engine retires it. ``close(reason)`` flips the gate shut (drain /
    shutdown) — everything after is rejected with that reason.

    **Graceful degradation** — :meth:`set_shed` arms an SLO-breach shed
    level: while armed, ``try_admit`` additionally rejects the *least
    protected* new arrivals (reason prefixed :data:`SHED_REASON_PREFIX`).
    A request's protection score is its ``priority`` plus 1 when its
    deadline is imminent (slack ≤ ``protect_slack_s`` — work the fleet is
    about to owe an answer for is never the first shed); a request is shed
    iff score < level, so level 1 drops only priority-0 slack-rich
    traffic and each further level reaches one priority tier higher.
    In-flight work is untouched — shedding gates admission only, so
    exactly-once delivery is preserved: every shed request still gets its
    clean ``rejected`` Result. :meth:`clear_shed` disarms on SLO clear."""

    def __init__(self, depth: int, budget_bytes: int):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        self.depth = depth
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._count = 0
        self._bytes = 0
        self._closed_reason: str | None = None
        self._shed_level = 0
        self._shed_reason = ""
        self._shed_slack_s = 0.0
        self._shed_count = 0

    def set_shed(self, level: int, reason: str = "",
                 protect_slack_s: float = 0.0) -> None:
        """Arm (level ≥ 1) or disarm (level 0) SLO-driven shedding.
        ``reason`` names the breached objective(s) for the rejection
        string; ``protect_slack_s`` is the deadline-slack bound under
        which a request counts as imminent and gains a protection point."""
        with self._lock:
            self._shed_level = max(0, int(level))
            self._shed_reason = str(reason)
            self._shed_slack_s = float(protect_slack_s)

    def clear_shed(self) -> None:
        self.set_shed(0)

    @property
    def shed_level(self) -> int:
        with self._lock:
            return self._shed_level

    @property
    def shed_count(self) -> int:
        """Total requests rejected by shedding since construction."""
        with self._lock:
            return self._shed_count

    def try_admit(self, cost_bytes: int, priority: int = 0,
                  deadline_slack_s: float | None = None) -> str | None:
        with self._lock:
            if self._closed_reason is not None:
                return self._closed_reason
            if self._shed_level > 0:
                score = int(priority)
                if (deadline_slack_s is not None
                        and deadline_slack_s <= self._shed_slack_s):
                    score += 1
                if score < self._shed_level:
                    self._shed_count += 1
                    why = (f" ({self._shed_reason})" if self._shed_reason
                           else "")
                    return (f"{SHED_REASON_PREFIX}: SLO error budget "
                            f"burning{why}; retry later or raise priority")
            if self._count >= self.depth:
                return (f"queue full ({self._count}/{self.depth} requests "
                        f"pending or in flight)")
            # at least one request is always admissible, else an oversized
            # budgetless config would deadlock the whole engine
            if (self._count and self.budget_bytes
                    and self._bytes + cost_bytes > self.budget_bytes):
                return (f"HBM admission budget exhausted ({self._bytes} + "
                        f"{cost_bytes} > {self.budget_bytes} bytes of "
                        f"in-flight KV cache)")
            self._count += 1
            self._bytes += cost_bytes
            return None

    def release(self, cost_bytes: int) -> None:
        with self._lock:
            self._count -= 1
            self._bytes -= cost_bytes
            assert self._count >= 0 and self._bytes >= 0, \
                "admission release without admit"

    def adopt(self, cost_bytes: int) -> None:
        """Force-admit a MIGRATED request's reservation (cross-engine
        handoff): the fleet already admitted this work on the source
        engine, whose queue is released by the migration caller — the
        reservation moves, it is never re-judged, so depth/budget/closed
        do not gate it (a frozen row must land even on a briefly-over-
        budget target; the normal ``release`` path drains the charge)."""
        with self._lock:
            self._count += 1
            self._bytes += cost_bytes

    def close(self, reason: str) -> None:
        with self._lock:
            if self._closed_reason is None:
                self._closed_reason = reason

    @property
    def closed_reason(self) -> str | None:
        """The drain/shutdown reason once the gate is shut, else None —
        submit() turns post-drain arrivals into deterministic
        ``shutting_down`` Results instead of generic rejections."""
        with self._lock:
            return self._closed_reason

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def bytes_in_flight(self) -> int:
        with self._lock:
            return self._bytes
