"""Supervised worker recovery for :class:`~marlin_tpu.serving.engine
.ServeEngine` — the serving half of the repo's fault-tolerance story.

A bare engine dies with its worker thread: before this module, a single
uncaught exception in the ``marlin-serve`` loop (or a wedged device call)
permanently killed the engine — healthz flipped 503, the flight recorder
dumped, and every live and queued request was stranded or failed. A
:class:`Supervisor` turns that one-shot failure into a supervised restart
loop:

- **Crash detection** is prompt: the engine's crash handler stashes the
  undone in-flight entries and kicks the supervisor's monitor thread (no
  poll latency); a worker that dies without reaching its handler is caught
  by the thread-liveness poll.
- **Stuck detection** is the watchdog: a worker whose ``_heartbeat`` stamp
  (stamped once per loop iteration, real clock) is older than
  ``watchdog_s`` while work is pending is declared stuck — the engine's
  worker *generation* is superseded (the stale thread exits at its next
  check and can never retire a superseded entry) and a fresh generation
  takes over. A ``warmup()`` in progress is exempt (``engine._warming``):
  first-compile latencies routinely outlast any sane watchdog, and a
  freshly scaled-out replica must not be "recovered" mid-warmup — crash
  detection stays on throughout.
- **Recovery** (``ServeEngine._recover``) rebuilds from the admission
  contract outward: slot pools are dropped (the KV slab state died with
  the worker; pools rebuild zeroed on the next admission — the PR 4
  ``is_deleted``→pool-rebuild path generalized), live rows that never
  emitted a Result re-queue within their per-request ``max_attempts``
  budget (exactly-once is preserved by attempt accounting: a superseded
  entry can never set the handle, and the admission reservation is carried
  — never released, never re-charged), and a fresh worker thread spawns.
  Greedy retries are bit-identical to an uninterrupted run; sampled
  retries re-derive the same per-row ``fold_in(key(seed), step)`` stream.
- **The restart budget** is a circuit breaker: restarts are timestamped
  into a sliding ``restart_window_s`` window and each restart backs off
  exponentially (``backoff_s * 2^k``, capped); more than ``restart_max``
  restarts in the window OPENS the breaker — the engine is failed
  permanently (closed; queued work gets clean terminal Results) instead of
  crash-looping against a deterministic bug.

Every transition lands in the EventLog (``kind="serve"``,
``ev="restart"`` / ``ev="breaker"``) and the process metrics registry:
``marlin_serve_restarts_total{engine=...}`` and
``marlin_serve_breaker_state{engine=...}`` (0 closed / 1 open). The
monitor thread is named ``marlin-serve-sup-*`` — the conftest leak fixture
watches the prefix; :meth:`Supervisor.close` joins it.

Knobs default from the config: ``serve_watchdog_s``,
``serve_restart_max``, ``serve_restart_window_s``,
``serve_restart_backoff_s`` (docs/robustness.md has the table).
"""

from __future__ import annotations

import collections
import threading
import time

from ..config import get_config
from ..obs.metrics import get_registry
from ..utils.tracing import get_default_event_log

__all__ = ["Supervisor"]


def _emit(log, **fields) -> None:
    log = log or get_default_event_log()
    if log is not None:
        log.event("serve", **fields)


class Supervisor:
    """Watch one engine's worker; restart it under a bounded budget.

    ``Supervisor(engine)`` attaches immediately: the engine's crash handler
    now stashes-and-kicks instead of failing its held requests, and a
    ``marlin-serve-sup-*`` monitor thread polls thread liveness plus the
    heartbeat watchdog every ``poll_s`` (the crash kick wakes it early).
    ``watchdog_s=0`` disables the stuck check; crash detection stays on.
    ``sleep`` is injectable so tests drive backoff deterministically.

    Usable as a context manager; :meth:`close` detaches, joins the monitor,
    and leaves the engine running (closing the engine is the owner's call —
    except after the breaker opened, when the engine is already closed)."""

    def __init__(self, engine, *, watchdog_s: float | None = None,
                 restart_max: int | None = None,
                 restart_window_s: float | None = None,
                 backoff_s: float | None = None,
                 poll_s: float = 0.05, log=None, start: bool = True,
                 sleep=time.sleep):
        cfg = get_config()
        self.engine = engine
        self.watchdog_s = float(cfg.serve_watchdog_s if watchdog_s is None
                                else watchdog_s)
        self.restart_max = int(cfg.serve_restart_max if restart_max is None
                               else restart_max)
        self.restart_window_s = float(
            cfg.serve_restart_window_s if restart_window_s is None
            else restart_window_s)
        self.backoff_s = float(cfg.serve_restart_backoff_s if backoff_s is
                               None else backoff_s)
        self.poll_s = float(poll_s)
        self._log = log
        self._sleep = sleep
        self._lock = threading.Lock()
        self._restarts: collections.deque = collections.deque()
        self.restart_count = 0
        self.breaker_open = False
        self._kick = threading.Event()
        self._stop = threading.Event()
        reg = get_registry()
        self._m_restarts = reg.counter(
            "marlin_serve_restarts_total",
            "Supervised serving-worker restarts", labelnames=("engine",)
        ).labels(engine=engine._name)
        self._m_breaker = reg.gauge(
            "marlin_serve_breaker_state",
            "Restart circuit breaker (0 closed / 1 open = engine failed "
            "permanently)", labelnames=("engine",)
        ).labels(engine=engine._name)
        self._m_breaker.set(0)
        engine.attach_supervisor(self._kick.set)
        self._thread = threading.Thread(
            target=self._monitor, daemon=True,
            name=f"marlin-serve-sup-{engine._name}")
        if start:
            self._thread.start()

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        """Detach from the engine and join the monitor. Idempotent."""
        self.engine.detach_supervisor()
        self._stop.set()
        self._kick.set()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ the watch

    def _monitor(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.poll_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                if not self.check():
                    return  # engine terminal (closed or breaker-opened)
            except Exception:
                # supervision must never die of its own bug; next poll
                # retries (the engine's own failure paths still resolve
                # every handle)
                pass

    def check(self) -> bool:
        """One inspection cycle (unit-testable without the thread): detect
        a crashed, dead, or stuck worker and recover. Returns False once
        the engine is terminal — the monitor loop exits."""
        eng = self.engine
        if self.breaker_open or eng._state in ("closing", "closed"):
            return False
        if eng._state in ("freezing", "frozen"):
            # migration pause: the worker parks (or has parked) on purpose
            # and freeze_rows() owns every resident row — a recovery here
            # would respawn a generation under the migration's feet and
            # double-deliver rows. A crash mid-freeze is stashed by the
            # crash handler and consumed by freeze_rows() itself (those
            # rows ride the retry fallback); keep polling — the router
            # closes the engine when the handoff ends
            return True
        crash = eng._crash  # read once: close()'s _fail_crash_stash may
        if crash is not None:  # null the attribute between our reads
            self._recover("worker crashed: "
                          f"{type(crash[0]).__name__}: {crash[0]}")
            return not self.breaker_open
        thread = eng._thread
        if eng._started and not thread.is_alive() \
                and eng._state in ("running", "draining"):
            # died without reaching the crash handler (SystemExit-class);
            # nothing stashed — _recover steals the pools/inflight mirrors
            self._recover("worker thread died")
            return not self.breaker_open
        hb = eng._heartbeat
        if (self.watchdog_s > 0 and eng._started and hb is not None
                and not eng._warming
                and time.monotonic() - hb > self.watchdog_s
                and eng._state in ("running", "draining")
                and eng.pending() > 0):
            self._recover(f"worker stuck: heartbeat "
                          f"{time.monotonic() - hb:.1f}s old "
                          f"(watchdog {self.watchdog_s}s)")
            return not self.breaker_open
        return True

    # ------------------------------------------------------------- recovery

    def _recover(self, reason: str) -> None:
        with self._lock:
            now = time.monotonic()
            self._restarts.append(now)
            while self._restarts and \
                    self._restarts[0] < now - self.restart_window_s:
                self._restarts.popleft()
            in_window = len(self._restarts)
            if in_window > self.restart_max:
                self._open_breaker(reason, in_window)
                return
            # exponential backoff within the window, capped at 16x — a
            # tight crash loop must not spin the device
            delay = self.backoff_s * min(2 ** (in_window - 1), 16)
        if delay > 0:
            self._sleep(delay)
        info = self.engine._recover(reason)
        with self._lock:
            self.restart_count += 1
        self._m_restarts.inc()
        _emit(self._log, ev="restart", engine=self.engine._name,
              reason=reason, gen=info["gen"], requeued=info["requeued"],
              failed=info["failed"], backoff_s=delay,
              restarts_in_window=in_window)

    def _open_breaker(self, reason: str, in_window: int) -> None:
        """Too many restarts in the window: fail the engine permanently.
        The current generation is superseded WITHOUT a respawn (a wedged
        thread is abandoned, never joined — it may sit in a device call
        forever, and close() must not hang on it), everything it held
        fails with ``error``, queued requests retire with clean
        ``shutting_down`` Results — nothing is stranded, and nothing
        restarts again."""
        # analyze: single-writer — a monotonic one-way latch (never reset);
        # readers tolerate a stale False for one poll interval
        self.breaker_open = True
        self._m_breaker.set(1)
        _emit(self._log, ev="breaker", engine=self.engine._name,
              state="open", reason=reason, restarts_in_window=in_window,
              window_s=self.restart_window_s)
        eng = self.engine
        eng.detach_supervisor()
        try:
            eng._recover(f"breaker open: {reason}", respawn=False)
            eng.close()
        except Exception:
            pass

    def info(self) -> dict:
        """Supervisor state for health aggregation (router / tests)."""
        with self._lock:
            return {"restarts": self.restart_count,
                    "restarts_in_window": len(self._restarts),
                    "breaker": "open" if self.breaker_open else "closed",
                    "watchdog_s": self.watchdog_s}
