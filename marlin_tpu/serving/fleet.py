"""Elastic fleet controller: SLO-burn-driven scale-out/in/rebalance over a
:class:`~marlin_tpu.serving.router.Router`'s replica set.

The reference delegated elasticity to Spark (SURVEY.md §0: a lost executor's
work is rescheduled, a busy cluster grows); the TPU-native rebuild closes
that loop itself. PR 15 computes multi-window error-budget burn per replica
and fleet-merged; PR 12 made replicas disposable (lossless freeze→adopt
migration, warm-from-peer prefix caches, consistent rendezvous
re-placement). A :class:`FleetController` sits on top of both and turns the
fleet-merged burn signal into topology:

- **scale OUT** — fast-window burn at/above ``serve_fleet_out_burn`` for
  ``serve_fleet_hysteresis`` consecutive evaluations: factory-spawn a
  replica (:meth:`~.router.Router.add_replica` — warm prefix cache from the
  warmest peer, fresh supervisor/breaker window, atomic rendezvous-ring
  join), bounded by ``serve_fleet_max_replicas``.
- **scale IN** — burn at/below ``serve_fleet_in_burn`` (budget slack) past
  the same hysteresis: retire the least-loaded replica
  (:meth:`~.router.Router.retire_replica` — out of every rendezvous list
  first, live rows + queued backlog migrated losslessly, then closed),
  floored at ``serve_fleet_min_replicas``.
- **REBALANCE** — one replica's queue depth exceeds the fleet mean by
  ``serve_fleet_rebalance_ratio`` past hysteresis (prefix affinity
  hot-spotting): shed ``serve_fleet_shed_frac`` of its rendezvous weight
  (:meth:`~.router.Router.shed_weight` — weighted HRW re-places exactly
  that share of its seen-prefix keys, nobody else's move).

**Robustness is the point, not a rider.** Actions are single-flight (a
second decision while one runs is a no-op); each runs on its own
``marlin-fleet-act-*`` thread and is recorded as ``timeout`` if it outlives
``serve_fleet_action_timeout_s`` — the controller then *degrades to doing
nothing* until the leg actually finishes (the underlying migration paths
own their own timeouts and are lossless by construction, so a stuck action
can delay elasticity but never drop work). ``serve_fleet_cooldown_s``
after any completed action lets its effect reach the burn windows;
opposite-direction actions inside ``serve_fleet_flap_window_s`` are
suppressed (flap damping — oscillating burn thrashes streak counters,
never the fleet). The controller keeps NO durable state of its own:
topology, loads, and weights live in the Router
(:meth:`~.router.Router.replica_view` / ``snapshot()``), so killing and
rebuilding the controller mid-action loses nothing but the transient
streak counters — the next evaluations re-derive the decision. The
``serve.fleet`` fault point fires inside each action leg
(``spawn-*``/``join-*``/``retire-*``/``shed-*``) so the chaos suite can
kill any leg mid-flight.

Observability: ``marlin_fleet_*`` gauges/counters (docs/observability.md),
``kind="fleet"`` EventLog records per decision and outcome, a
``GET /debug/fleet`` payload (:meth:`FleetController.payload`, registered
via :func:`~marlin_tpu.obs.exposition.register_fleet_provider`), and a
fleet panel in the ops console. The evaluation clock is injectable; call
:meth:`tick` from any loop, or :meth:`start` a ``marlin-fleet-ctl-*``
poll thread (the conftest leak fixture watches the prefix;
:meth:`close` joins it).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time

from ..config import get_config
from ..obs.exposition import (register_fleet_provider,
                              unregister_fleet_provider)
from ..obs.metrics import get_registry
from ..utils.tracing import get_default_event_log

__all__ = ["FleetController"]

_ctl_ids = itertools.count()

#: scale direction per action — flap damping suppresses an action whose
#: direction OPPOSES the previous one inside the flap window; rebalance is
#: direction-neutral (never damped, only cooled down)
_DIRECTION = {"scale_out": 1, "scale_in": -1, "rebalance": 0}


class FleetController:
    """Close the loop from fleet-merged SLO burn to fleet topology.

    ``FleetController(router)`` reads every knob from the config
    (``serve_fleet_*``; keyword overrides win) and registers its
    ``/debug/fleet`` provider. Nothing evaluates until :meth:`tick` is
    called (or :meth:`start` spawns the poll thread) — construction is
    passive, so tests drive the controller deterministically on an
    injectable ``clock``. ``threaded=False`` runs actions inline on the
    ticking thread (deterministic tests); the default runs each on its own
    ``marlin-fleet-act-*`` thread so a slow migration never blocks the
    evaluation loop.

    The controller is restart-safe by design: its only durable state is
    the Router's own replica set. Rebuilding a controller on the same
    router (e.g. after a crash mid-action) resumes correct control —
    streak counters restart empty and re-derive from the live burn
    signal."""

    def __init__(self, router, *, clock=time.monotonic, log=None,
                 min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 eval_interval_s: float | None = None,
                 out_burn: float | None = None,
                 in_burn: float | None = None,
                 hysteresis: int | None = None,
                 cooldown_s: float | None = None,
                 flap_window_s: float | None = None,
                 rebalance_ratio: float | None = None,
                 shed_frac: float | None = None,
                 action_timeout_s: float | None = None,
                 threaded: bool = True):
        cfg = get_config()
        self.router = router
        self._clock = clock
        self._log = log
        self.min_replicas = int(cfg.serve_fleet_min_replicas
                                if min_replicas is None else min_replicas)
        self.max_replicas = int(cfg.serve_fleet_max_replicas
                                if max_replicas is None else max_replicas)
        self.eval_interval_s = float(
            cfg.serve_fleet_eval_interval_s if eval_interval_s is None
            else eval_interval_s)
        self.out_burn = float(cfg.serve_fleet_out_burn if out_burn is None
                              else out_burn)
        self.in_burn = float(cfg.serve_fleet_in_burn if in_burn is None
                             else in_burn)
        self.hysteresis = int(cfg.serve_fleet_hysteresis if hysteresis is
                              None else hysteresis)
        self.cooldown_s = float(cfg.serve_fleet_cooldown_s if cooldown_s is
                                None else cooldown_s)
        self.flap_window_s = float(
            cfg.serve_fleet_flap_window_s if flap_window_s is None
            else flap_window_s)
        self.rebalance_ratio = float(
            cfg.serve_fleet_rebalance_ratio if rebalance_ratio is None
            else rebalance_ratio)
        self.shed_frac = float(cfg.serve_fleet_shed_frac if shed_frac is
                               None else shed_frac)
        self.action_timeout_s = float(
            cfg.serve_fleet_action_timeout_s if action_timeout_s is None
            else action_timeout_s)
        self._threaded = bool(threaded)
        # re-entrant: tick() holds it across _decide/_reset_streak, which
        # take it again at their own write sites (lock-discipline wants
        # every cross-thread write lexically under the lock)
        self._lock = threading.RLock()
        self._closed = False
        self._hot = 0          # consecutive evaluations at/above out_burn
        self._slack = 0        # consecutive evaluations at/below in_burn
        self._imbalance = 0    # consecutive hot-spotted evaluations
        self._last_eval: float | None = None
        self._last_burn = 0.0
        self._action: dict | None = None      # the single in-flight action
        self._last_action: dict | None = None  # most recent COMPLETED one
        self._history: collections.deque = collections.deque(maxlen=16)
        self._rs_mark: float | None = None    # replica-seconds accumulator
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._name = f"marlin-fleet-{next(_ctl_ids)}"
        reg = get_registry()
        self._m_replicas = reg.gauge(
            "marlin_fleet_replicas",
            "Live replicas behind the router the fleet controller drives",
            labelnames=("router",)).labels(router=router._name)
        self._m_burn = reg.gauge(
            "marlin_fleet_burn",
            "Fleet-merged worst-objective fast-window error-budget burn "
            "rate the controller last evaluated", labelnames=("router",)
        ).labels(router=router._name)
        self._m_weight = reg.gauge(
            "marlin_fleet_weight",
            "Per-replica rendezvous routing weight (1.0 = classic HRW; "
            "rebalance sheds by shrinking it)",
            labelnames=("router", "replica"))
        self._m_actions = reg.counter(
            "marlin_fleet_actions_total",
            "Fleet controller actions by outcome (ok / error / timeout / "
            "damped)", labelnames=("router", "action", "outcome"))
        self._m_replica_seconds = reg.counter(
            "marlin_fleet_replica_seconds_total",
            "Accumulated replica-seconds of fleet capacity (replicas x "
            "wall time between controller evaluations) — the bench's "
            "replica-hours denominator", labelnames=("router",)
        ).labels(router=router._name)
        register_fleet_provider(self._name, self.payload)

    # ------------------------------------------------------------ lifecycle

    def start(self, poll_s: float = 1.0) -> None:
        """Spawn the ``marlin-fleet-ctl-*`` poll thread: ``tick()`` every
        ``poll_s`` real seconds (the eval-interval rate limit still
        applies on the controller's own clock). Idempotent."""
        with self._lock:
            if self._closed or self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._poll, args=(float(poll_s),), daemon=True,
                name=f"{self._name}-ctl")
        self._thread.start()

    def _poll(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.tick()
            except Exception:
                # the control loop must never die of its own bug; the
                # next poll re-evaluates from the router's live state
                pass

    def close(self) -> None:
        """Stop evaluating and unregister the ``/debug/fleet`` provider.
        Joins the poll thread and any in-flight action thread (bounded —
        the action's own migration timeouts make it finite). The router
        is untouched: closing the controller freezes the fleet at its
        current size, it does not shrink it. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            action = self._action
            thread = self._thread
        self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=30.0)
        t = (action or {}).get("thread")
        if t is not None and t is not threading.current_thread() \
                and t.is_alive():
            t.join(timeout=30.0)
        unregister_fleet_provider(self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ----------------------------------------------------------- evaluation

    def _emit(self, **fields) -> None:
        log = self._log or get_default_event_log()
        if log is not None:
            log.event("fleet", controller=self._name,
                      router=self.router._name, **fields)

    def _burn_signal(self) -> float:
        """Worst fast-window burn across the fleet-merged objectives, 0.0
        when no SLOs are configured (a burn-less fleet never scales out
        and always counts as slack — min_replicas floors the shrink)."""
        try:
            merged = self.router._fleet_slo()
        except Exception:
            return 0.0
        if not merged:
            return 0.0
        burns = [o.get("burn_rate") or 0.0
                 for o in merged.get("objectives", ())]
        return max(burns, default=0.0)

    def _hot_spot(self, view: list[dict]) -> int | None:
        """The hot-spotted replica's index, or None. Hot-spotted = the
        most loaded ready replica's queue depth is nontrivial (>= 4) and
        exceeds ``rebalance_ratio`` times its peers' mean depth."""
        ready = [r for r in view if r["state"] == "accepting"]
        if len(ready) < 2:
            return None
        top = max(ready, key=lambda r: r["load"])
        if top["load"] < 4:
            return None
        others = [r["load"] for r in ready if r is not top]
        mean = sum(others) / len(others)
        if top["load"] >= self.rebalance_ratio * max(mean, 1.0):
            return top["replica"]
        return None

    def tick(self, now: float | None = None) -> dict:
        """One evaluation on the controller's clock: accumulate
        replica-seconds, rate-limit to ``eval_interval_s``, update the
        burn/imbalance streaks, and start at most one action. Returns a
        small decision record (``{"evaluated": bool, "action": ...}``) —
        the tests' window into the state machine. Never raises from a
        signal-read failure; action failures are recorded, not thrown."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._closed:
                return {"evaluated": False, "reason": "closed"}
            n = self.router.replica_count()
            if self._rs_mark is not None and now > self._rs_mark:
                self._m_replica_seconds.inc((now - self._rs_mark) * n)
            self._rs_mark = now
            if self._last_eval is not None \
                    and now - self._last_eval < self.eval_interval_s:
                return {"evaluated": False, "reason": "interval"}
            self._last_eval = now
        burn = self._burn_signal()
        view = self.router.replica_view()
        hot_idx = self._hot_spot(view)
        self._m_replicas.set(len(view))
        self._m_burn.set(burn)
        for r in view:
            self._m_weight.labels(router=self.router._name,
                                  replica=r["replica"]).set(r["weight"])
        with self._lock:
            self._last_burn = burn
            if burn >= self.out_burn:
                self._hot += 1
                self._slack = 0
            elif burn <= self.in_burn:
                self._slack += 1
                self._hot = 0
            else:
                self._hot = self._slack = 0
            self._imbalance = self._imbalance + 1 if hot_idx is not None \
                else 0
            decision = self._decide(now, len(view), hot_idx)
        if decision.get("action") and decision.get("outcome") is None:
            self._launch(decision["action"], decision.get("replica"))
        return decision

    def _decide(self, now: float, n: int, hot_idx: int | None) -> dict:
        """Pick at most one action (caller holds the lock). Ordering:
        an in-flight action wins (single-flight), then cooldown, then
        scale-out (capacity protects the SLO) over scale-in over
        rebalance."""
        base = {"evaluated": True, "replicas": n,
                "burn": round(self._last_burn, 4), "action": None,
                "outcome": None}
        act = self._action
        if act is not None:
            if not act["timed_out"] \
                    and now - act["started"] > self.action_timeout_s:
                act["timed_out"] = True
                self._emit(action=act["action"], outcome="timeout",
                           seconds=round(now - act["started"], 3))
            return dict(base, reason="busy", action=None)
        last = self._last_action
        if last is not None and now - last["finished"] < self.cooldown_s:
            return dict(base, reason="cooldown")
        want = None
        if self._hot >= self.hysteresis:
            want = "scale_out" if n < self.max_replicas else None
            if want is None:
                return dict(base, reason="at-max")
        elif self._slack >= self.hysteresis:
            want = "scale_in" if n > self.min_replicas else None
            if want is None:
                return dict(base, reason="at-min")
        elif self._imbalance >= self.hysteresis:
            want = "rebalance"
        if want is None:
            return dict(base, reason="steady")
        if last is not None and _DIRECTION[want] \
                and _DIRECTION[want] == -_DIRECTION.get(last["action"], 0) \
                and now - last["finished"] < self.flap_window_s:
            # flap damping: reversing the previous action this soon means
            # the signal is oscillating, not trending — suppress, reset
            # the streak, and record the refusal
            self._reset_streak(want)
            self._m_actions.labels(router=self.router._name, action=want,
                                   outcome="damped").inc()
            self._emit(action=want, outcome="damped",
                       previous=last["action"],
                       age_s=round(now - last["finished"], 3))
            return dict(base, action=want, outcome="damped")
        self._reset_streak(want)
        with self._lock:  # re-entrant (tick holds it)
            self._action = {"action": want, "started": now,
                            "replica": hot_idx if want == "rebalance"
                            else None,
                            "timed_out": False, "thread": None}
        return dict(base, action=want,
                    replica=hot_idx if want == "rebalance" else None)

    def _reset_streak(self, action: str) -> None:
        with self._lock:  # re-entrant (tick holds it)
            if action == "scale_out":
                self._hot = 0
            elif action == "scale_in":
                self._slack = 0
            else:
                self._imbalance = 0

    # -------------------------------------------------------------- actions

    def _launch(self, action: str, replica: int | None) -> None:
        if not self._threaded:
            self._run_action(action, replica)
            return
        t = threading.Thread(target=self._run_action,
                             args=(action, replica), daemon=True,
                             name=f"{self._name}-act-{action}")
        with self._lock:
            if self._action is not None:
                self._action["thread"] = t
        t.start()

    def _run_action(self, action: str, replica: int | None) -> None:
        """Execute one action against the router. Every failure mode —
        exception, fault injection, a peer dying mid-migration — degrades
        to 'did nothing' or 'did it losslessly'; the router's own paths
        guarantee no work is dropped either way."""
        outcome, detail = "ok", {}
        try:
            if action == "scale_out":
                detail["replica"] = self.router.add_replica()
            elif action == "scale_in":
                detail["replica"] = self.router.retire_replica()
            else:
                idx, w = self.router.shed_weight(idx=replica,
                                                 frac=self.shed_frac)
                detail["replica"] = idx
                detail["weight"] = round(w, 4)
        except Exception as exc:
            outcome = "error"
            detail["error"] = f"{type(exc).__name__}: {exc}"
        now = self._clock()
        with self._lock:
            act = self._action
            self._action = None
            timed_out = bool(act and act["timed_out"])
            record = {"action": action, "outcome":
                      "timeout" if timed_out and outcome == "ok"
                      else outcome, "finished": now, **detail}
            self._last_action = record
            self._history.append(record)
        self._m_actions.labels(router=self.router._name, action=action,
                               outcome=record["outcome"]).inc()
        self._m_replicas.set(self.router.replica_count())
        self._emit(action=action, outcome=record["outcome"],
                   replicas=self.router.replica_count(), **detail)

    # -------------------------------------------------------- introspection

    def replica_seconds(self) -> float:
        """Replica-seconds accumulated so far (the bench's replica-hours
        source) — read off the process counter."""
        return float(self._m_replica_seconds.value)

    def payload(self) -> dict:
        """The ``GET /debug/fleet`` scope: bounds, streaks, burn, the
        in-flight action (if any), recent completed actions, and the
        router's live per-replica view — everything an operator needs to
        see why the fleet is (not) moving."""
        with self._lock:
            act = dict(self._action) if self._action else None
            if act is not None:
                act.pop("thread", None)
            body = {
                "controller": self._name,
                "router": self.router._name,
                "closed": self._closed,
                "replicas": self.router.replica_count(),
                "bounds": {"min": self.min_replicas,
                           "max": self.max_replicas},
                "burn": round(self._last_burn, 4),
                "thresholds": {"out": self.out_burn, "in": self.in_burn,
                               "hysteresis": self.hysteresis},
                "streaks": {"hot": self._hot, "slack": self._slack,
                            "imbalance": self._imbalance},
                "action": act,
                "history": list(self._history),
            }
        body["view"] = self.router.replica_view()
        body["replica_seconds"] = round(self.replica_seconds(), 3)
        return body
