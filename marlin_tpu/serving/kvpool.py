"""Paged KV-cache pool: block-table paging + copy-on-write prefix sharing.

The dense-slab scheduler (PR 4, ``serve_paged=False``) gives every slot a
``(max_len, kvh, dh)`` cache row sized for its bucket's worst case — a
short request in a long bucket wastes HBM linearly and admission must
budget by bucket. This module replaces the slab with the classic paged
design: ONE device-resident page slab per engine
(:func:`~marlin_tpu.models.transformer.init_kv_pages` — ``(num_pages,
page_len, kvh, dh)`` per layer, shared by every bucket) plus host-side
bookkeeping per row:

- **Block tables** — each live row holds an ordered list of page ids
  covering its positions; the decode program gathers by table, the chunked
  prefill program scatters by table
  (:func:`~marlin_tpu.models.transformer.lm_decode_paged` /
  :func:`~marlin_tpu.models.transformer.lm_prefill_paged`).
- **Free-list allocation + refcounts** — a request allocates exactly
  :func:`~marlin_tpu.models.planner.request_pages` pages (what it can ever
  write); every retirement path releases them exactly once; page 0 is a
  permanently-pinned dummy that absorbs out-of-extent gathers/scatters.
- **Copy-on-write prefix sharing** — completed FULL pages of prompt tokens
  are cached under a rolling hash (page k's key folds page k-1's key, so a
  key names an entire prefix, not one page's content): a later request
  whose prompt starts with the same pages takes a reference instead of
  re-prefilling — the dominant real-traffic shape, a common system prompt
  prefilled once. The page holding the prompt's LAST token is never shared
  (it is re-prefilled so the first-token logits exist, and decode writes
  continue into it), so in steady state shared pages are read-only by
  construction; :meth:`PagedKVPool.ensure_writable` still implements the
  full COW contract — a writer to a page with other referents gets a fresh
  page and a device :func:`~marlin_tpu.models.transformer.kv_page_copy` —
  as the safety net the engine runs before every write. Cached pages are
  LRU-evicted (leaf-first — an entry with cached children or live readers
  is not evictable) when allocation needs room.

Allocation invariant (why :meth:`alloc` cannot fail under the auto-sized
pool): pages are allocated only when a request claims a ROW, rows are
bounded by the slot set (``max_batch`` per bucket), each row allocates at
most its bucket's page extent, and cache-only pages are LRU-evictable —
so the :func:`auto_num_pages` default (every bucket at full width, plus
slack) always has room, whatever the queue depth. A hand-set smaller
``serve_num_pages`` can run out under full occupancy; the engine guards
the call either way (a failed alloc retries/errors one request, never the
worker).

Everything here is host-side numpy/stdlib except the three compiled
programs it drives; single-threaded by contract (only the engine worker
touches a pool, like :class:`~.batcher.SlotPool`).
"""

from __future__ import annotations

import hashlib
import json
import struct
import zlib
from collections import OrderedDict

import numpy as np

__all__ = ["PagedKVPool", "PagedGroup", "PagePoolExhausted",
           "MigrationCorruptError", "auto_num_pages", "paged_program_key",
           "warmup_paged", "capture_paged_costs"]


class PagePoolExhausted(RuntimeError):
    """alloc() found fewer free+evictable pages than requested."""


class MigrationCorruptError(RuntimeError):
    """A migration blob failed structural or CRC validation on import —
    truncation, bit flips, or a geometry mismatch between pools. Import
    never partially applies a corrupt blob."""


# Migration wire format (PR 12): the MarlinChunk idiom — a flat sequence of
# 32-byte-header chunks, each body independently CRC32-framed so a torn or
# bit-flipped blob ALWAYS raises on import instead of resurrecting garbage
# KV state on the target replica.
#   header: magic "MGRT" | crc32(body) | kind | body_len | 12 pad bytes
_MIG_MAGIC = b"MGRT"
_MIG_HDR = struct.Struct("<4sIIQ12x")  # 32 bytes
_MIG_META = 1      # JSON metadata (geometry + per-row/per-entry manifest)
_MIG_ROW = 2       # one row's page contents, layers in order, k then v
_MIG_PREFIX = 3    # prefix-cache pages (one body for the whole entry set)


def _mig_frame(kind: int, body: bytes) -> bytes:
    return _MIG_HDR.pack(_MIG_MAGIC, zlib.crc32(body) & 0xFFFFFFFF, kind,
                         len(body)) + body


def _mig_chunks(blob: bytes) -> list[tuple[int, bytes]]:
    """Split and validate a migration blob; raises on any corruption."""
    out = []
    off = 0
    n = len(blob)
    while off < n:
        if n - off < _MIG_HDR.size:
            raise MigrationCorruptError(
                f"truncated chunk header at offset {off}")
        magic, crc, kind, length = _MIG_HDR.unpack_from(blob, off)
        if magic != _MIG_MAGIC:
            raise MigrationCorruptError(
                f"bad chunk magic {magic!r} at offset {off}")
        off += _MIG_HDR.size
        body = blob[off:off + length]
        if len(body) != length:
            raise MigrationCorruptError(
                f"truncated chunk body at offset {off}: "
                f"need {length} bytes, have {len(body)}")
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise MigrationCorruptError(
                f"chunk CRC mismatch at offset {off}")
        out.append((kind, body))
        off += length
    return out


def _mig_default(o):
    """json.dumps default: numpy scalars/arrays from group vectors."""
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def auto_num_pages(buckets, max_batch: int, page_len: int) -> int:
    """The default pool size (``serve_num_pages=0``): every bucket's full
    slot width at its full extent — the dense-slab steady state, so a
    paged-vs-slab A/B holds device capacity equal — plus one slack page
    per slot (chunk scatter spill) and the dummy page 0. Short requests
    use fewer pages than this budget assumes; the surplus is what the
    prefix cache lives in."""
    pages = 1  # the dummy
    for p, s in buckets:
        pages += max_batch * (-(-(p + s) // page_len) + 1)
    return pages


class _CacheEntry:
    __slots__ = ("page", "parent", "children")

    def __init__(self, page: int, parent: bytes | None):
        self.page = page
        self.parent = parent
        self.children = 0


class PagedKVPool:
    """Host-side owner of one engine's page slab (see module docstring).

    ``pages`` is the device slab dict; the engine replaces it after every
    donated program call. Counters (``hits``/``misses``/``cow_copies``/
    ``evictions``) feed the serving metrics."""

    def __init__(self, params: dict, heads: int, num_pages: int,
                 page_len: int, compute_dtype: str | None = None,
                 prefix_cache: bool = True):
        from ..models.transformer import init_kv_pages

        self.page_len = int(page_len)
        self.num_pages = int(num_pages)
        self.compute_dtype = compute_dtype
        self.pages = init_kv_pages(params, num_pages, page_len, heads,
                                   compute_dtype)
        # pop() hands out ascending ids; page 0 never enters the list
        self._free = list(range(num_pages - 1, 0, -1))
        self._ref = np.zeros(num_pages, np.int32)
        self._ref[0] = 1  # the dummy page is pinned forever
        self._cache: OrderedDict[bytes, _CacheEntry] = OrderedDict()
        self.prefix_cache_enabled = bool(prefix_cache)
        self.hits = 0
        self.misses = 0
        self.cow_copies = 0
        self.evictions = 0

    # ------------------------------------------------------------- capacity

    @property
    def capacity(self) -> int:
        """Allocatable pages (everything but the dummy)."""
        return self.num_pages - 1

    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        """Pages held by rows and/or the prefix cache."""
        return self.capacity - len(self._free)

    def shared_count(self) -> int:
        """Pages with more than one referent (cache + row, or row + row)."""
        return int((self._ref[1:] > 1).sum())

    def cached_count(self) -> int:
        return len(self._cache)

    def stats(self) -> dict:
        return {"total": self.capacity, "used": self.used_count(),
                "shared": self.shared_count(),
                "cached": self.cached_count(), "hits": self.hits,
                "misses": self.misses, "cow_copies": self.cow_copies,
                "evictions": self.evictions}

    # ----------------------------------------------------- alloc / refcount

    def alloc(self, n: int) -> list[int]:
        """``n`` fresh pages (refcount 1 each), evicting cache-only pages
        LRU as needed. Raises :class:`PagePoolExhausted` when free +
        evictable < n — unreachable under the auto-sized pool (module
        docstring: allocation is row-bounded), guarded anyway."""
        while len(self._free) < n and self._evict_one():
            pass
        if len(self._free) < n:
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free and nothing "
                f"evictable ({self.used_count()}/{self.capacity} used, "
                f"{self.cached_count()} cached)")
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def retain(self, pages) -> None:
        """One more referent per page (prefix-share acquisition)."""
        for p in pages:
            assert self._ref[p] > 0, f"retain of unowned page {p}"
            self._ref[p] += 1

    def release(self, pages) -> None:
        """Drop one referent per page; pages at zero return to the free
        list. Every retirement path funnels here exactly once per row
        (PagedGroup.release returns the row's distinct real pages)."""
        for p in pages:
            if p == 0:
                continue  # dummy padding in a table slice — never counted
            self._ref[p] -= 1
            assert self._ref[p] >= 0, f"page {p} released below zero"
            if self._ref[p] == 0:
                self._free.append(int(p))

    # -------------------------------------------------------- prefix cache

    @staticmethod
    def _page_key(prev: bytes, tokens: np.ndarray) -> bytes:
        """Rolling hash: page k's key digests (page k-1's key || page k's
        tokens), so one key identifies the whole prefix through page k."""
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.digest()

    def _share_limit(self, prompt_len: int) -> int:
        """Positions eligible for sharing: whole pages strictly before the
        prompt's last token — that token's page is always re-prefilled (its
        logits seed the first sample) and then written by decode, so it can
        never be a shared page."""
        return ((prompt_len - 1) // self.page_len) * self.page_len

    def match_prefix(self, prompt: np.ndarray) -> tuple[int, list[int]]:
        """Longest cached prefix of ``prompt`` in whole pages:
        ``(shared_len, pages)``, with one reference taken per matched page
        (the caller's row now co-owns them read-only). Counts a hit when
        at least one page matched, else a miss."""
        if not self.prefix_cache_enabled:
            return 0, []
        prompt = np.asarray(prompt, np.int32)
        limit = self._share_limit(len(prompt))
        pages: list[int] = []
        key = b""
        k = 0
        while (k + 1) * self.page_len <= limit:
            key = self._page_key(
                key, prompt[k * self.page_len:(k + 1) * self.page_len])
            e = self._cache.get(key)
            if e is None:
                break
            self._cache.move_to_end(key)  # LRU touch
            pages.append(e.page)
            k += 1
        if pages:
            self.retain(pages)
            self.hits += 1
        else:
            self.misses += 1
        return len(pages) * self.page_len, pages

    def insert_prefix(self, prompt: np.ndarray, row_pages) -> int:
        """Cache the row's completed full prompt pages (called once, when
        the row's prefill finishes — the pages' contents are final from
        then on). ``row_pages`` is the row's block table in position order.
        Already-cached prefixes are skipped (no double reference); each
        newly cached page gains one cache-owned reference that outlives
        the row. Returns pages inserted."""
        if not self.prefix_cache_enabled:
            return 0
        prompt = np.asarray(prompt, np.int32)
        limit = self._share_limit(len(prompt))
        key = b""
        inserted = 0
        for k in range(limit // self.page_len):
            parent = key if k else None
            key = self._page_key(
                key, prompt[k * self.page_len:(k + 1) * self.page_len])
            e = self._cache.get(key)
            if e is not None:
                self._cache.move_to_end(key)
                continue
            page = int(row_pages[k])
            self._cache[key] = entry = _CacheEntry(page, parent)
            if parent is not None:
                self._cache[parent].children += 1
            del entry
            self.retain([page])
            inserted += 1
        return inserted

    def _evict_one(self) -> bool:
        """Evict the LRU cache entry that is a chain leaf (no cached
        children — evicting mid-chain would orphan unreachable deeper
        entries) and has no live readers (refcount is the cache's own).
        Returns False when nothing qualifies."""
        for key, e in self._cache.items():  # OrderedDict: oldest first
            if e.children == 0 and self._ref[e.page] == 1:
                del self._cache[key]
                if e.parent is not None:
                    self._cache[e.parent].children -= 1
                self.release([e.page])
                self.evictions += 1
                return True
        return False

    # ------------------------------------------------------- copy-on-write

    def ensure_writable(self, table: np.ndarray, idx: int) -> bool:
        """Copy-on-write gate for one block-table slot: if the page has
        other referents (shared prefix, cache), allocate a fresh page,
        device-copy the contents (:func:`kv_page_copy` — ONE compiled
        program per slab shape), move this row's reference, and point the
        table at the copy. Returns True when a copy happened. The engine
        calls this before every page it is about to write; in steady state
        writes only ever target exclusively-owned pages (see
        :meth:`_share_limit`), so this is a cheap refcount check — but it
        is the contract that makes sharing safe against any future
        scheduler change, and the unit tests drive it directly."""
        from ..models.transformer import kv_page_copy

        page = int(table[idx])
        if page == 0 or self._ref[page] <= 1:
            return False
        fresh = self.alloc(1)[0]
        self.pages = kv_page_copy(self.pages, page, fresh)
        self.release([page])
        table[idx] = fresh
        self.cow_copies += 1
        return True

    # ------------------------------------------------- cross-pool migration

    def _layer_names(self) -> list[str]:
        return sorted(self.pages, key=lambda s: int(s[1:]))

    def _host_pages(self) -> dict:
        """One whole-slab device→host fetch (migration is a restart-path
        operation; a per-row device gather would compile one program per
        row-set size and break the bounded-compiles guarantee)."""
        return {name: [np.array(t) for t in self.pages[name]]
                for name in self._layer_names()}

    def _flush_host(self, host) -> None:
        """Push a host slab copy back to the device wholesale."""
        if host is None:
            return
        import jax.numpy as jnp

        self.pages = {name: tuple(jnp.asarray(a) for a in kv)
                      for name, kv in host.items()}

    def _geometry(self) -> dict:
        names = self._layer_names()
        leaf = self.pages[names[0]][0]
        return {"page_len": self.page_len, "layers": names,
                "dtype": str(np.dtype(leaf.dtype)),
                "shapes": [list(np.shape(self.pages[nm][0])[1:])
                           for nm in names]}

    def _check_geometry(self, meta: dict) -> None:
        geo = self._geometry()
        for field in ("page_len", "layers", "dtype", "shapes"):
            if meta.get(field) != geo[field]:
                raise MigrationCorruptError(
                    f"pool geometry mismatch on {field!r}: blob has "
                    f"{meta.get(field)!r}, target pool has {geo[field]!r}")

    def _row_nbytes(self, n_pages: int) -> int:
        geo = self._geometry()
        item = np.dtype(geo["dtype"]).itemsize
        per_page = sum(int(np.prod([self.page_len] + shape[1:]))
                       for shape in geo["shapes"])
        # shapes[i] is (page_len, kvh, dh); k and v slabs per layer
        return 2 * n_pages * per_page * item

    def export_rows(self, rows) -> bytes:
        """Serialize a row set into a CRC-framed host blob. Each element of
        ``rows`` is a dict carrying the row's block table in position order
        (``pages``), its prompt/cursor/sampling manifest (engine-provided;
        travels verbatim in the meta chunk), and ``rid``. Page contents are
        gathered device→host once for the whole set. The blob is
        self-contained: :meth:`import_rows` on any pool with matching
        geometry rebuilds the rows without reference to this pool."""
        host = self._host_pages()
        names = self._layer_names()
        meta = {"version": 1, "kind": "rows", **self._geometry(),
                "rows": [dict(r, pages=[int(p) for p in r["pages"]])
                         for r in rows]}
        blob = [_mig_frame(
            _MIG_META, json.dumps(meta, default=_mig_default).encode())]
        for r in meta["rows"]:
            pids = np.asarray(r["pages"], np.int64)
            body = b"".join(
                np.ascontiguousarray(host[name][half][pids]).tobytes()
                for name in names for half in (0, 1))
            blob.append(_mig_frame(_MIG_ROW, body))
        return b"".join(blob)

    def import_rows(self, blob: bytes) -> list[dict]:
        """Rebuild an exported row set in THIS pool: validate every chunk
        (corruption always raises :class:`MigrationCorruptError`), then per
        row run the NORMAL allocation path — :meth:`match_prefix` first, so
        a migrated shared prefix re-deduplicates against the target's cache
        (and against earlier rows of this same blob, whose completed prompt
        pages are re-inserted as they land), then :meth:`alloc` for the
        remainder — and scatter the imported page contents into the slab.
        Returns the row manifests with target-space ``pages``/``n_shared``/
        ``shared_len`` rebound; the caller binds them to entries. On any
        failure every page this call allocated is released (pages already
        content-written stay valid for the cache entries that reference
        them), so a failed import leaks nothing."""
        chunks = _mig_chunks(blob)
        if not chunks or chunks[0][0] != _MIG_META:
            raise MigrationCorruptError("blob does not start with a meta "
                                        "chunk")
        try:
            meta = json.loads(chunks[0][1].decode())
        except ValueError as exc:
            raise MigrationCorruptError(f"meta chunk not JSON: {exc}")
        if meta.get("version") != 1 or meta.get("kind") != "rows":
            raise MigrationCorruptError(
                f"unsupported blob version/kind: {meta.get('version')}/"
                f"{meta.get('kind')}")
        self._check_geometry(meta)
        bodies = [b for kind, b in chunks[1:] if kind == _MIG_ROW]
        if len(bodies) != len(meta["rows"]):
            raise MigrationCorruptError(
                f"row count mismatch: meta lists {len(meta['rows'])} rows, "
                f"blob carries {len(bodies)} page chunks")
        for row, body in zip(meta["rows"], bodies):
            if len(body) != self._row_nbytes(len(row["pages"])):
                raise MigrationCorruptError(
                    f"row {row.get('rid')}: page payload is {len(body)} "
                    f"bytes, expected "
                    f"{self._row_nbytes(len(row['pages']))}")
        names = self._layer_names()
        dtype = np.dtype(meta["dtype"])
        out: list[dict] = []
        taken: list[list[int]] = []
        host = None
        try:
            for row, body in zip(meta["rows"], bodies):
                prompt = np.asarray(row["prompt"], np.int32)
                n_pages = len(row["pages"])
                shared_len, spages = self.match_prefix(prompt)
                owned = self.alloc(n_pages - len(spages))
                pages = list(spages) + owned
                taken.append(pages)
                if owned:
                    if host is None:
                        host = self._host_pages()
                    off = 0
                    for name, shape in zip(names, meta["shapes"]):
                        cnt = n_pages * int(np.prod(shape))
                        nb = cnt * dtype.itemsize
                        for half in (0, 1):
                            arr = np.frombuffer(
                                body, dtype, cnt, off).reshape(
                                    [n_pages] + shape)
                            host[name][half][owned] = arr[len(spages):]
                            off += nb
                row = dict(row, pages=pages, n_shared=len(spages),
                           shared_len=shared_len)
                out.append(row)
                if int(row.get("pf_next", -1)) < 0:
                    # prefill completed on the source: publish the prompt's
                    # full pages so later arrivals — including later rows
                    # of this same blob — share instead of re-importing
                    self.insert_prefix(prompt, pages)
        except BaseException:
            # pages already written hold valid content — flush them so any
            # cache entry inserted above stays safe, then drop row refs
            self._flush_host(host)
            for pages in taken:
                self.release(pages)
            raise
        self._flush_host(host)
        return out

    def export_prefixes(self, n: int) -> bytes | None:
        """The N hottest prefix-cache entries (MRU end of the LRU order),
        closed over their parent chains (a child without its ancestors can
        never be matched), as a CRC-framed blob for warming a peer's cache.
        Keys are the content hashes themselves — no prompt tokens travel.
        Returns None when there is nothing to export."""
        if not self.prefix_cache_enabled or not self._cache:
            return None
        selected: set[bytes] = set()
        for key in list(self._cache)[-max(1, int(n)):]:
            while key is not None and key not in selected:
                selected.add(key)
                key = self._cache[key].parent

        def depth(k: bytes) -> int:
            d = 0
            e = self._cache[k]
            while e.parent is not None:
                d += 1
                e = self._cache[e.parent]
            return d

        ordered = sorted(selected, key=depth)  # parents import first
        host = self._host_pages()
        names = self._layer_names()
        entries = []
        body = []
        for key in ordered:
            e = self._cache[key]
            entries.append({
                "key": key.hex(),
                "parent": None if e.parent is None else e.parent.hex()})
            pid = np.asarray([e.page], np.int64)
            body.append(b"".join(
                np.ascontiguousarray(host[name][half][pid]).tobytes()
                for name in names for half in (0, 1)))
        meta = {"version": 1, "kind": "prefixes", **self._geometry(),
                "entries": entries}
        return (_mig_frame(_MIG_META, json.dumps(meta).encode())
                + _mig_frame(_MIG_PREFIX, b"".join(body)))

    def import_prefixes(self, blob: bytes) -> int:
        """Warm this pool's prefix cache from a peer's
        :meth:`export_prefixes` blob: each entry allocates one page (LRU
        eviction may make room; exhaustion stops the warm early rather than
        failing it), takes the cache-owned reference, and links into the
        parent chain. Entries already cached (or whose parent did not make
        the cut) are skipped. Returns entries inserted."""
        if not self.prefix_cache_enabled:
            return 0
        chunks = _mig_chunks(blob)
        if not chunks or chunks[0][0] != _MIG_META:
            raise MigrationCorruptError("blob does not start with a meta "
                                        "chunk")
        try:
            meta = json.loads(chunks[0][1].decode())
        except ValueError as exc:
            raise MigrationCorruptError(f"meta chunk not JSON: {exc}")
        if meta.get("version") != 1 or meta.get("kind") != "prefixes":
            raise MigrationCorruptError(
                f"unsupported blob version/kind: {meta.get('version')}/"
                f"{meta.get('kind')}")
        self._check_geometry(meta)
        bodies = [b for kind, b in chunks[1:] if kind == _MIG_PREFIX]
        body = bodies[0] if bodies else b""
        per_entry = self._row_nbytes(1)
        if len(body) != per_entry * len(meta["entries"]):
            raise MigrationCorruptError(
                f"prefix payload is {len(body)} bytes, expected "
                f"{per_entry * len(meta['entries'])}")
        names = self._layer_names()
        dtype = np.dtype(meta["dtype"])
        host = None
        inserted = 0
        for i, ent in enumerate(meta["entries"]):
            key = bytes.fromhex(ent["key"])
            parent = None if ent["parent"] is None \
                else bytes.fromhex(ent["parent"])
            if key in self._cache:
                self._cache.move_to_end(key)
                continue
            if parent is not None and parent not in self._cache:
                continue  # chain broken (parent evicted/skipped)
            try:
                page = self.alloc(1)[0]
            except PagePoolExhausted:
                break  # a partial warm is still a warm
            if host is None:
                host = self._host_pages()
            off = i * per_entry
            for name, shape in zip(names, meta["shapes"]):
                cnt = int(np.prod(shape))
                nb = cnt * dtype.itemsize
                for half in (0, 1):
                    host[name][half][page] = np.frombuffer(
                        body, dtype, cnt, off).reshape(shape)
                    off += nb
            self._cache[key] = _CacheEntry(page, parent)
            if parent is not None:
                self._cache[parent].children += 1
            inserted += 1
        self._flush_host(host)
        return inserted

    # --------------------------------------------------------------- audit

    def audit(self, groups=()) -> dict:
        """Cross-check every pool invariant: refcounts vs block-table
        references vs the free list vs prefix-cache ownership, the pinned
        dummy page, and cache parent/children chain consistency. ``groups``
        is the engine's live :class:`PagedGroup` set — row-side references
        are only checkable when the caller passes them (chaos tests and
        ``GET /debug/kvpool`` do). Returns ``{"ok": bool, "errors": [...],
        **stats}``; read-only, never raises."""
        errors: list[str] = []
        expect = np.zeros(self.num_pages, np.int64)
        expect[0] = 1  # the dummy pin
        for g in groups:
            for slot in g.occupied_slots():
                for p in (g.row_pages[slot] or []):
                    p = int(p)
                    if not 0 < p < self.num_pages:
                        errors.append(f"row table references out-of-range "
                                      f"page {p}")
                        continue
                    expect[p] += 1
        children: dict[bytes, int] = {}
        for key, e in self._cache.items():
            if not 0 < e.page < self.num_pages:
                errors.append(f"cache entry references out-of-range page "
                              f"{e.page}")
                continue
            expect[e.page] += 1
            if e.parent is not None:
                if e.parent not in self._cache:
                    errors.append(f"cache entry for page {e.page} orphaned: "
                                  f"parent key missing")
                else:
                    children[e.parent] = children.get(e.parent, 0) + 1
        for key, e in self._cache.items():
            want = children.get(key, 0)
            if e.children != want:
                errors.append(f"cache entry for page {e.page}: children "
                              f"count {e.children} != {want} actual")
        free = [int(p) for p in self._free]
        fs = set(free)
        if len(fs) != len(free):
            errors.append("free list contains duplicate pages")
        if 0 in fs:
            errors.append("dummy page 0 is on the free list")
        if int(self._ref[0]) < 1:
            errors.append(f"dummy page 0 unpinned (refcount "
                          f"{int(self._ref[0])})")
        for p in fs:
            if not 0 < p < self.num_pages:
                errors.append(f"free list holds out-of-range page {p}")
            elif int(self._ref[p]) != 0:
                errors.append(f"free page {p} has refcount "
                              f"{int(self._ref[p])}")
            if int(expect[p]) != 0 and 0 < p < self.num_pages:
                errors.append(f"free page {p} is still referenced by a row "
                              f"or cache entry")
        for p in range(1, self.num_pages):
            ref = int(self._ref[p])
            if p in fs:
                continue
            if ref == 0:
                errors.append(f"page {p} leaked: refcount 0 but not on the "
                              f"free list")
            elif groups and ref != int(expect[p]):
                errors.append(f"page {p}: refcount {ref} != "
                              f"{int(expect[p])} referents")
            elif not groups and ref < int(expect[p]):
                errors.append(f"page {p}: refcount {ref} below its "
                              f"{int(expect[p])} cache references")
        return {"ok": not errors, "errors": errors, **self.stats()}


class PagedGroup:
    """Per-bucket row bookkeeping over a shared :class:`PagedKVPool` — the
    paged analog of :class:`~.batcher.SlotPool`. Owns the per-row vectors
    the decode program takes, each row's block table and prefill cursor,
    and the host-side emitted-token stream (tokens never live on device in
    paged mode: the decode program takes ``cur_tokens`` and returns the
    next ones, so results are assembled host-side). Single-threaded — only
    the engine worker touches a group."""

    def __init__(self, bucket, width: int, page_len: int,
                 prefill_chunk: int):
        p, s = bucket
        self.bucket = bucket
        self.width = width
        self.page_len = page_len
        #: block-table width for DECODE: pages covering the bucket extent
        self.pages_per_row = -(-(p + s) // page_len)
        #: compiled chunk width in tokens: whole pages, never wider than
        #: the prompt extent (a narrow bucket compiles the smaller
        #: program), and CAPPED below the per-iteration token budget
        #: (serve_prefill_chunk) — the program's cost is fixed at its
        #: width whatever the real token count, so a wide program makes a
        #: prefix-hit row's short tail (the prefix-cache win) as expensive
        #: as a full prefill; the engine instead runs several small chunks
        #: per iteration up to the budget
        cap = max(64, 4 * page_len)
        self.chunk = min(_round_up(max(1, prefill_chunk), page_len),
                         _round_up(p, page_len),
                         _round_up(cap, page_len))
        self.chunk_pages = self.chunk // page_len
        #: stored table width: decode extent + chunk spill (a final chunk
        #: starting near the extent scatters into these dummy-page slots)
        self.table_width = self.pages_per_row + self.chunk_pages
        self.tables = np.zeros((width, self.table_width), np.int32)
        self.entries: list = [None] * width
        self.positions = np.zeros(width, np.int32)
        self.steps_done = np.zeros(width, np.int32)
        self.lengths = np.zeros(width, np.int32)
        self.seeds = np.zeros(width, np.uint32)
        self.temperature = np.zeros(width, np.float32)
        self.top_p = np.ones(width, np.float32)   # 1.0 = nucleus filter off
        self.top_k = np.zeros(width, np.int32)    # 0 = rank filter off
        self.cur_tok = np.zeros(width, np.int32)
        self.ttft_s: list = [None] * width
        #: next chunk_start per row; -1 = not prefilling (free or decoding)
        self.pf_next = np.full(width, -1, np.int64)
        self.prompts: list = [None] * width   # chunk-padded prompt arrays
        self.emitted: list = [None] * width   # host-side generated tokens
        self.row_pages: list = [None] * width  # table pages, position order
        self.shared_pages = np.zeros(width, np.int32)

    # --------------------------------------------------------------- state

    def occupied_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is not None]

    def live_slots(self) -> list[int]:
        """Decode-ready rows (prefill complete)."""
        return [i for i, e in enumerate(self.entries)
                if e is not None and self.pf_next[i] < 0]

    def prefilling_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries)
                if e is not None and self.pf_next[i] >= 0]

    def free_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is None]

    def occupancy(self) -> float:
        return len(self.live_slots()) / self.width

    # ---------------------------------------------------------- transitions

    def assign(self, slot: int, entry, pages: list[int], shared_len: int,
               n_shared: int) -> None:
        """Bind an admitted entry: ``pages`` is the row's full block table
        in position order (``n_shared`` prefix-cache pages first, then the
        freshly allocated remainder); prefill resumes at ``shared_len``."""
        r = entry.request
        n = r.prompt.shape[0]
        self.entries[slot] = entry
        self.lengths[slot] = n
        self.tables[slot, :] = 0
        self.tables[slot, :len(pages)] = pages
        self.row_pages[slot] = list(pages)
        self.shared_pages[slot] = n_shared
        self.pf_next[slot] = shared_len
        padded = np.zeros(_round_up(n, self.chunk), np.int32)
        padded[:n] = r.prompt
        self.prompts[slot] = padded
        self.positions[slot] = 0
        self.steps_done[slot] = 0
        self.cur_tok[slot] = 0
        self.seeds[slot] = np.uint32(r.seed)
        self.temperature[slot] = r.temperature
        self.top_p[slot] = 1.0 if r.top_p is None else r.top_p
        self.top_k[slot] = 0 if r.top_k is None else r.top_k
        self.emitted[slot] = []
        self.ttft_s[slot] = None

    def finish_prefill(self, slot: int, first: int) -> None:
        """The final chunk landed: the row becomes decode-ready with its
        first emitted token in hand (= the slab path's prefill contract)."""
        self.pf_next[slot] = -1
        self.positions[slot] = self.lengths[slot]
        self.steps_done[slot] = 1
        self.cur_tok[slot] = first
        self.emitted[slot] = [int(first)]

    def restore(self, slot: int, entry, row: dict,
                pages: list[int]) -> None:
        """Bind a MIGRATED row mid-stream (:meth:`PagedKVPool.import_rows`
        manifest): like :meth:`assign` but restoring the source replica's
        cursors — position, steps_done, current token, emitted stream, and
        the prefill cursor for rows frozen mid-prefill. With the imported
        KV pages in place, decode resumes bit-identically: the sampling
        stream is ``fold_in(key(seed), step)``, composition-independent,
        so only (seed, steps_done, KV, cur_tok) matter — all restored."""
        r = entry.request
        n = int(row["length"])
        self.entries[slot] = entry
        self.lengths[slot] = n
        self.tables[slot, :] = 0
        self.tables[slot, :len(pages)] = pages
        self.row_pages[slot] = list(pages)
        self.shared_pages[slot] = int(row["n_shared"])
        self.pf_next[slot] = int(row["pf_next"])
        padded = np.zeros(_round_up(n, self.chunk), np.int32)
        padded[:n] = r.prompt
        self.prompts[slot] = padded
        self.positions[slot] = int(row["position"])
        self.steps_done[slot] = int(row["steps_done"])
        self.cur_tok[slot] = int(row["cur_tok"])
        self.seeds[slot] = np.uint32(r.seed)
        self.temperature[slot] = r.temperature
        self.top_p[slot] = 1.0 if r.top_p is None else r.top_p
        self.top_k[slot] = 0 if r.top_k is None else r.top_k
        self.emitted[slot] = [int(t) for t in row["emitted"]]
        self.ttft_s[slot] = row.get("ttft_s")

    def release(self, slot: int) -> list[int]:
        """Free the slot on ANY retirement path; returns the row's pages
        for the caller to hand to :meth:`PagedKVPool.release` — the single
        page-release funnel per row."""
        pages = self.row_pages[slot] or []
        self.entries[slot] = None
        self.tables[slot, :] = 0
        self.row_pages[slot] = None
        self.shared_pages[slot] = 0
        self.pf_next[slot] = -1
        self.positions[slot] = 0
        self.steps_done[slot] = 0
        self.lengths[slot] = 0
        self.cur_tok[slot] = 0
        self.temperature[slot] = 0.0
        self.top_p[slot] = 1.0
        self.top_k[slot] = 0
        self.prompts[slot] = None
        self.emitted[slot] = None
        self.ttft_s[slot] = None
        return pages

    # -------------------------------------------------------- decode inputs

    def decode_inputs(self):
        """(tables, positions, cur_tokens) with every non-live row masked
        to the dummy table/position — a prefilling row's REAL pages must
        never be scribbled by its dummy decode write."""
        live = np.zeros(self.width, bool)
        live[self.live_slots()] = True
        tables = np.where(live[:, None],
                          self.tables[:, :self.pages_per_row], 0)
        positions = np.where(live, self.positions, 0)
        cur = np.where(live, self.cur_tok, 0)
        return tables, positions, cur


# ---------------------------------------------------------------- programs


def paged_program_key(params: dict, bucket, max_batch: int,
                      page_len: int, compute_dtype=None,
                      kernel: str = "gather") -> str:
    """Roofline-accounting key for one bucket's PAGED programs: the slab
    geometry joins the identity (the same bucket at a different page_len
    compiles different programs), and so does the decode-attention backend
    — gather vs the fused pallas kernel are different programs with
    different rooflines. The gather default keeps pre-kernel key strings
    (and their persisted bench anchors) unchanged."""
    from .batcher import bucket_program_key

    key = bucket_program_key(params, bucket, max_batch,
                             compute_dtype) + f"/page{page_len}"
    return key if kernel == "gather" else key + f"/k{kernel}"


def capture_paged_costs(params: dict, heads: int, bucket, max_batch: int,
                        pool: PagedKVPool, prefill_chunk: int,
                        compute_dtype: str | None = None,
                        moe: tuple | None = None,
                        key: str | None = None,
                        kernel: str = "gather") -> None:
    """Capture the XLA cost models of a bucket's paged program pair into
    the process ProgramCosts registry — trace + lower only, gated per
    (program, key) like :func:`~.batcher.capture_bucket_costs`. Never
    raises (observability must not fail warmup or a dispatch).

    With ``kernel='pallas'`` on a Mosaic (non-interpret) lowering, the
    pallas_call is a custom call XLA's cost analysis scores at zero — the
    decode capture supplements the analysis with the kernel's analytic
    cost (:func:`~marlin_tpu.ops.paged_attention.paged_attention_cost`) so
    ``marlin_program_roofline_frac`` covers the kernel too; interpret-mode
    lowerings are plain XLA ops and need no supplement."""
    import jax
    import jax.numpy as jnp

    from ..obs import perf

    costs = perf.get_program_costs()
    if key is None:
        key = paged_program_key(params, bucket, max_batch, pool.page_len,
                                compute_dtype, kernel)
    programs = ("lm_prefill_paged", "lm_decode_paged")
    if all(costs.tried(name, key) for name in programs):
        return
    from ..models.transformer import (_lm_decode_paged_jit,
                                      _lm_prefill_paged_jit, _n_layers,
                                      init_kv_pages)

    def st(shape, dtype=jnp.int32):
        return jax.ShapeDtypeStruct(shape, dtype)

    sds = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype), tree)
    g = PagedGroup(bucket, max_batch, pool.page_len, prefill_chunk)
    try:
        pages = sds(jax.eval_shape(
            lambda pp: init_kv_pages(pp, pool.num_pages, pool.page_len,
                                     heads, compute_dtype), params))
        pre = _lm_prefill_paged_jit.trace(
            sds(params), pages, st((g.table_width,)), st((g.chunk,)),
            st(()), st(()), st((), jnp.uint32), st((), jnp.float32),
            st((), jnp.float32), st(()), heads=heads,
            page_len=pool.page_len, compute_dtype=compute_dtype,
            moe=moe).lower()
        dec = _lm_decode_paged_jit.trace(
            sds(params), pages, st((max_batch, g.pages_per_row)),
            st((max_batch,)), st((max_batch,)), st((max_batch,)),
            st((max_batch,), jnp.uint32), st((max_batch,), jnp.float32),
            st((max_batch,), jnp.float32), st((max_batch,)), heads=heads,
            page_len=pool.page_len, compute_dtype=compute_dtype,
            moe=moe, kernel=kernel).lower()
        costs.capture("lm_prefill_paged", key, lowered=pre)
        dec_cost = None
        if kernel == "pallas":
            from ..ops.pallas_kernels import _interpret
            from ..ops.paged_attention import paged_attention_cost

            if not _interpret():
                d = params["emb"].shape[1]
                dh = d // heads
                kvh = params["l0"]["wk"].shape[1] // dh
                slab = pages["l0"][0]
                kc = paged_attention_cost(
                    max_batch, g.pages_per_row, pool.page_len, kvh,
                    heads // kvh, dh, jnp.dtype(slab.dtype).itemsize)
                dec_cost = dict(dec.cost_analysis() or {})
                n = _n_layers(params)
                for field in ("flops", "bytes accessed"):
                    dec_cost[field] = (float(dec_cost.get(field, 0.0))
                                       + n * kc[field])
        costs.capture("lm_decode_paged", key, lowered=dec, cost=dec_cost)
    except Exception:
        for name in programs:  # even a failed trace marks the attempt
            costs.capture(name, key)


def warmup_paged(params: dict, heads: int, buckets, max_batch: int,
                 pool: PagedKVPool, prefill_chunk: int,
                 compute_dtype: str | None = None,
                 moe: tuple | None = None, kernel: str = "gather") -> int:
    """Compile (and execute once, against dummy page 0) every bucket's
    paged program pair plus the one shared page-copy program — ≤ 3
    programs per bucket, the whole paged compile story. Runs against the
    engine's REAL pool (program identity includes the slab shape, so a
    throwaway pool would compile programs traffic never hits); all dummy
    writes land in page 0. Returns the buckets warmed."""
    import jax

    from ..models.transformer import (kv_page_copy, lm_decode_paged,
                                      lm_prefill_paged)
    from .batcher import normalize_buckets

    buckets = normalize_buckets(buckets)
    for bucket in buckets:
        g = PagedGroup(bucket, max_batch, pool.page_len, prefill_chunk)
        capture_paged_costs(params, heads, bucket, max_batch, pool,
                            prefill_chunk, compute_dtype, moe,
                            kernel=kernel)
        pool.pages, _ = lm_prefill_paged(
            params, pool.pages, np.zeros(g.table_width, np.int32),
            np.zeros(g.chunk, np.int32), 0, 1, heads=heads,
            page_len=pool.page_len, compute_dtype=compute_dtype, moe=moe)
        w = max_batch
        pool.pages, nxt = lm_decode_paged(
            params, pool.pages, np.zeros((w, g.pages_per_row), np.int32),
            np.zeros(w, np.int32), np.zeros(w, np.int32),
            np.zeros(w, np.int32), np.zeros(w, np.uint32),
            np.zeros(w, np.float32), np.ones(w, np.float32),
            np.zeros(w, np.int32), heads=heads, page_len=pool.page_len,
            compute_dtype=compute_dtype, moe=moe, kernel=kernel)
        jax.block_until_ready(nxt)
    pool.pages = kv_page_copy(pool.pages, 0, 0)  # the third program
    jax.block_until_ready(pool.pages["l0"][0])
    return len(buckets)
