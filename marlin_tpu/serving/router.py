"""Multi-replica serving router: obs-fed load balancing over N supervised
:class:`~marlin_tpu.serving.engine.ServeEngine` replicas, with failover and
drain-safe rolling restarts.

One engine is one worker loop on (implicitly) one device set; the ROADMAP's
"millions of users" story needs N of them behind one front door. A
:class:`Router` owns that front door:

- **Routing** is prefix-affine first, power-of-two-choices otherwise
  (``serve_prefix_affinity``): a prompt with at least one shareable KV
  page is keyed by a hash of its FIRST full page of tokens and — once that
  key has been seen before — rendezvous-hashed (highest-random-weight)
  over the ready replicas, so every request sharing a system prompt lands
  on the same replica's prefix cache — without affinity a shared prefix
  sprays misses across the fleet and the router bench records 0 hits where
  one engine gets 63/64. A key's FIRST occurrence routes load-aware like
  any other request (a one-off prompt has no cache hit to win, and pinning
  it to a hash-chosen replica regardless of queue depth measurably costs
  tail TTFT under load); the router remembers recent keys in a small LRU
  (:data:`_SEEN_PREFIX_CAP`) so repeat traffic engages affinity from its
  second request on. Short prompts (nothing shareable) and degraded
  fleets (< 2 ready) fall back to
  power-of-two-choices over the same readiness set: pick two distinct
  candidates at random, route to the less loaded by the same queue-depth
  gauge ``/metrics`` exports (``AdmissionQueue.count`` — the obs-fed
  signal, read directly so routing needs no scrape).
- **Failover**: a replica that rejects (overload), reports shutting-down,
  or fails outright (the ``serve.router_route`` fault point simulates
  this) is skipped for this request and the remaining replicas are tried
  in order — the rendezvous order for affine requests (the second-highest
  replica is every affine request's CONSISTENT fallback, so affinity
  survives a replica failure), load order otherwise. Only when every
  replica refuses does the caller see a terminal Result — deterministic,
  never an exception from a healthy router.
- **Rolling restart** (:meth:`rolling_restart`) is migrate-then-restart:
  one replica at a time is pulled from rotation and FROZEN at a step
  boundary (:meth:`~.engine.ServeEngine.freeze_rows`); its live rows'
  KV pages, cursors, and sampling state are exported into a CRC-framed
  host blob and adopted mid-stream by the least-loaded ready peer
  (:meth:`~.engine.ServeEngine.adopt_rows` — decode continues
  bit-identically, zero tokens re-generated), its queued backlog moves
  wholesale, and only then is the engine closed, rebuilt via the factory,
  its prefix cache warmed from a peer (``serve_cache_warm_prefixes``),
  and put back before the next replica starts. Any migration leg that
  fails (the ``serve.migrate`` fault point simulates each) degrades that
  row to the PR 7 retry path — a fresh-attempt twin on a healthy replica,
  reservation carried exactly once, nothing double-delivers — and a
  replica that cannot freeze at all (slab engine) falls back to the old
  drain-in-place rotation.
- **One scrape target**: the router registers a single aggregated health
  provider (each adopted engine's individual provider is unregistered —
  a draining replica mid-rotation must NOT 503 the process while its
  peers absorb traffic; the router reports not-ready only when NO replica
  accepts) and publishes ``marlin_serve_replica_state{router=,replica=}``
  (0 accepting / 1 draining / 2 restarting / 3 closed / 4 failed).
  Per-engine serving metrics already aggregate in the process registry;
  :meth:`snapshot` merges the per-replica ``ServeMetrics`` snapshots for
  tests and the bench.

- **Elastic membership** (PR 16): :meth:`add_replica` factory-spawns a
  replica, warms its prefix cache from the warmest peer, and joins it to
  the rendezvous ring in one atomic list append (in-flight ``_candidates``
  snapshots either see it fully or not at all); :meth:`retire_replica`
  pulls one out of rotation FIRST (it leaves every rendezvous score list
  immediately — no request can route to a closing replica), migrates its
  live rows and queued backlog out over the same freeze→adopt path the
  rolling restart uses, and removes it. Replica indices are stable and
  never reused (a per-router counter), so the HRW mapping of surviving
  replicas is untouched by membership changes — only keys the lost replica
  owned re-place. :meth:`shed_weight` is the rebalance half: scoring is
  *weighted* rendezvous hashing (at the default weight 1.0 the order is
  exactly the classic digest order), so multiplying one hot replica's
  weight down re-places precisely that fraction of its keys and nobody
  else's. :class:`~marlin_tpu.serving.fleet.FleetController` drives all
  three off the fleet-merged SLO burn signal.

``Router(factory, replicas=N)`` builds N engines up front via the zero-arg
``factory`` (also used by rolling restarts and scale-out);
``Router(engines=[...])`` adopts existing engines but cannot
rolling-restart or scale out without a factory.
"""

from __future__ import annotations

import hashlib
import itertools
import math
from collections import OrderedDict
import random
import threading
import time

import numpy as np

from ..config import get_config
from ..obs.exposition import (register_health_provider,
                              register_slo_provider,
                              unregister_health_provider,
                              unregister_slo_provider)
from ..obs.slo import fleet_merge
from ..obs.metrics import get_registry
from ..utils import faults
from .engine import MigrationError
from .request import (STATUS_REJECTED, STATUS_SHUTTING_DOWN, Request, Result,
                      ResultHandle)
from .supervisor import Supervisor, _emit

__all__ = ["Router", "REPLICA_STATES"]

_router_ids = itertools.count()

#: the ``marlin_serve_replica_state`` gauge encoding
REPLICA_STATES = {"accepting": 0, "draining": 1, "restarting": 2,
                  "closed": 3, "failed": 4}

#: handle statuses that trigger failover to the next replica (an expired
#: deadline is final everywhere; an error Result means the request RAN)
_FAILOVER = (STATUS_REJECTED, STATUS_SHUTTING_DOWN)

#: ServeMetrics counters a retired replica's final snapshot folds into the
#: router's running totals at rotation — without this, every rotation
#: silently zeroes the fleet's history (the PR 11 router bench lost its
#: prefix hit/miss record exactly this way). Gauges (pages_*) stay
#: current-replicas-only: a dead pool holds no pages.
_COUNTER_KEYS = ("submitted", "rejected", "expired", "completed", "errors",
                 "shut_down", "retries", "batches", "steps", "new_tokens",
                 "prefix_hits", "prefix_misses", "migrated_out",
                 "migrated_in", "migrate_fallback", "busy_s",
                 "program_steps", "program_rows", "swaps")


def _prefix_route_key(request, ready) -> bytes | None:
    """The affinity key: a 16-byte hash of the prompt's FIRST full KV page
    of tokens — the same granularity the prefix cache shares at, and
    deliberately ONLY the first page, so requests sharing a system prompt
    map together whatever their tails do. None when nothing is shareable
    (prompt must be strictly longer than a page: the cache never shares
    the last-token page) or no ready replica is paged. Non-LM BucketProgram
    requests have no KV prefix to be affine to, so they deterministically
    fall back to power-of-two-choices placement — mixed traffic load-
    balances instead of piling onto whichever replica owns a hot prompt."""
    if getattr(request, "program", "lm") != "lm":
        return None
    if not get_config().serve_prefix_affinity:
        return None
    prompt = getattr(request, "prompt", None)
    page_len = next((r.engine._page_len for r in ready
                     if getattr(r.engine, "paged", False)), 0)
    if prompt is None or not page_len or len(prompt) <= page_len:
        return None
    head = np.ascontiguousarray(np.asarray(prompt[:page_len], np.int32))
    return hashlib.blake2b(head.tobytes(), digest_size=16).digest()


#: Distinct first-page keys the router remembers for affinity gating. A
#: shared system prompt is one key however many requests ride it, so even a
#: small window outlives any realistic hot-prefix set; unique-prompt traffic
#: cycles through without growing the router.
_SEEN_PREFIX_CAP = 1024


def _rendezvous_score(key: bytes, idx: int) -> bytes:
    """Highest-random-weight score of (prefix key, replica): each replica
    set change remaps only the keys that hashed to the lost/gained replica
    — a rolling restart does not reshuffle the whole fleet's affinity."""
    return hashlib.blake2b(key + idx.to_bytes(4, "little"),
                           digest_size=8).digest()


def _weighted_score(key: bytes, idx: int, weight: float) -> float:
    """Weighted rendezvous score (Mosharaf/HRW with weights): map the
    8-byte digest to a uniform u in (0, 1) and score ``-weight / ln(u)``.
    At weight 1.0 the score is strictly monotone in the digest, so the
    ordering is exactly the classic unweighted rendezvous order; shrinking
    one replica's weight moves ONLY the keys it owned (each key's other
    scores are untouched) — the minimal-churn property rebalance relies
    on. Weights are clamped to a small positive floor: a zero weight
    would un-rank the replica for every key at once."""
    digest = _rendezvous_score(key, idx)
    u = (int.from_bytes(digest, "big") + 1) / (2 ** 64 + 1)
    return -max(weight, 1e-6) / math.log(u)


class _Replica:
    """One engine + its supervisor + routing state. ``routable`` is the
    router-side gate (rolling restart pulls a replica from rotation before
    the engine itself starts draining); ``weight`` scales its rendezvous
    scores (1.0 = classic HRW; rebalance sheds by shrinking it)."""

    __slots__ = ("idx", "engine", "supervisor", "routable", "restarts",
                 "weight")

    def __init__(self, idx: int, engine, supervisor):
        self.idx = idx
        self.engine = engine
        self.supervisor = supervisor
        self.routable = True
        self.restarts = 0
        self.weight = 1.0

    def state(self) -> str:
        if self.supervisor is not None and self.supervisor.breaker_open:
            return "failed"
        eng_state = {"running": "accepting", "draining": "draining",
                     "freezing": "draining", "frozen": "draining",
                     "closing": "closed",
                     "closed": "closed"}[self.engine._state]
        if eng_state == "closed":
            return "closed"
        if not self.routable:
            return "restarting"   # pulled from rotation, being rebuilt
        return eng_state

    def ready(self) -> bool:
        return self.state() == "accepting"

    def load(self) -> int:
        return self.engine._queue.count


class Router:
    """Route :class:`Request` submissions across N engine replicas.

    ``factory`` is a zero-arg callable returning a fresh, started
    :class:`ServeEngine`; ``replicas`` defaults from
    ``config.serve_replicas``. Pass ``engines=[...]`` to adopt
    pre-built engines instead (``factory`` then remains optional but is
    required for :meth:`rolling_restart`). ``supervise=True`` (default)
    wraps every replica in a :class:`~.supervisor.Supervisor`;
    ``supervisor_kw`` tunes it (watchdog_s, restart_max, ...). ``rng``
    seeds the power-of-two choice for deterministic tests.

    Thread-safe: ``submit`` may be called from any number of threads;
    ``rolling_restart``/``drain``/``close`` serialize against each other.
    Usable as a context manager (``close()`` on exit)."""

    def __init__(self, factory=None, replicas: int | None = None, *,
                 engines=None, supervise: bool = True,
                 supervisor_kw: dict | None = None, rng=None, log=None,
                 warmup: bool = False):
        if factory is None and engines is None:
            raise ValueError("Router needs a factory or engines=[...]")
        self._factory = factory
        self._supervise = supervise
        self._supervisor_kw = dict(supervisor_kw or {})
        self._log = log
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()        # replica list + lifecycle
        self._restart_lock = threading.Lock()  # one rotation at a time
        self._closed = False
        self._seen_prefixes = OrderedDict()  # first-page key -> True (LRU)
        self._retired = {k: 0 for k in _COUNTER_KEYS}  # rotated-out totals
        self._name = f"marlin-router-{next(_router_ids)}"
        reg = get_registry()
        self._m_replica_state = reg.gauge(
            "marlin_serve_replica_state",
            "Router replica state (0 accepting / 1 draining / 2 restarting "
            "/ 3 closed / 4 failed)", labelnames=("router", "replica"))
        if engines is None:
            n = int(get_config().serve_replicas if replicas is None
                    else replicas)
            if n < 1:
                raise ValueError(f"replicas must be >= 1, got {n}")
            engines = [factory() for _ in range(n)]
        self._replicas = [self._adopt(i, eng)
                          for i, eng in enumerate(engines)]
        # stable replica indices, never reused: a scale-out after a retire
        # must not resurrect a retired index — rendezvous keys the index,
        # and reuse would silently inherit the dead replica's affinity
        self._next_idx = itertools.count(len(self._replicas))
        if warmup:
            for rep in self._replicas:
                rep.engine.warmup()
        register_health_provider(self._name, self._health_info)
        # fleet-wide SLO view: the replicas' per-engine /debug/slo scopes
        # stay registered (drill-down); the router adds the worst-case
        # merge (obs/slo.py fleet_merge) under its own name
        register_slo_provider(self._name, self._fleet_slo)
        self._publish_states()

    # -------------------------------------------------------------- plumbing

    def _adopt(self, idx: int, engine) -> _Replica:
        # the router is THE scrape target: fold the engine's readiness into
        # the aggregate view so one draining replica cannot 503 a process
        # whose other replicas are absorbing its traffic
        unregister_health_provider(engine._name)
        sup = Supervisor(engine, log=self._log,
                         **self._supervisor_kw) if self._supervise else None
        return _Replica(idx, engine, sup)

    def _emit(self, **fields) -> None:
        _emit(self._log, **fields)

    def _publish_states(self) -> None:
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            self._m_replica_state.labels(
                router=self._name, replica=rep.idx).set(
                    REPLICA_STATES[rep.state()])

    # --------------------------------------------------------------- routing

    def _prefix_seen(self, key: bytes) -> bool:
        """Record ``key`` in the LRU window; True iff it was already there.
        Affinity engages only for prefixes observed more than once: the
        first occurrence has no warm cache anywhere, so hashing it to a
        fixed replica regardless of queue depth would trade real load
        balance for a hit that cannot happen — exactly the tail-TTFT
        regression the unique-prompt router bench leg caught."""
        with self._lock:
            seen = key in self._seen_prefixes
            self._seen_prefixes[key] = True
            self._seen_prefixes.move_to_end(key)
            while len(self._seen_prefixes) > _SEEN_PREFIX_CAP:
                self._seen_prefixes.popitem(last=False)
        return seen

    def _candidates(self, request: Request | None = None) -> list[_Replica]:
        """Ready replicas in routing preference order. A request whose
        shareable prefix has been seen before gets the full rendezvous
        order over its key (affine pick first; the runner-up is the
        consistent fallback); everything else — short prompts, first
        touches of a new prefix — gets power-of-two-choices first (two
        distinct random picks, less loaded first), then the rest by load.
        Either order doubles as the failover order."""
        with self._lock:
            ready = [r for r in self._replicas if r.ready()]
        if request is not None and len(ready) >= 2:
            key = _prefix_route_key(request, ready)
            if key is not None and self._prefix_seen(key):
                return sorted(
                    ready, reverse=True,
                    key=lambda r: _weighted_score(key, r.idx, r.weight))
        if len(ready) <= 2:
            return sorted(ready, key=lambda r: r.load())
        a, b = self._rng.sample(ready, 2)
        first = sorted([a, b], key=lambda r: r.load())
        rest = sorted((r for r in ready if r is not a and r is not b),
                      key=lambda r: r.load())
        return first + rest

    def submit(self, request: Request) -> ResultHandle:
        """Route one request: exactly one terminal Result, always. Tries
        the affine / power-of-two pick, then fails over across every
        remaining ready replica on rejection / shutdown / route failure;
        only when all refuse does the caller see the last refusal (or a
        synthesized ``rejected`` Result when no replica is ready at
        all)."""
        last = None
        for rep in self._candidates(request):
            try:
                faults.fire("serve.router_route", path=f"replica-{rep.idx}")
                h = rep.engine.submit(request)
            except Exception as exc:
                self._emit(ev="route_failover", router=self._name,
                           replica=rep.idx, rid=request.rid,
                           reason=f"{type(exc).__name__}: {exc}")
                continue
            if h.done() and h.result().status in _FAILOVER:
                last = h
                self._emit(ev="route_failover", router=self._name,
                           replica=rep.idx, rid=request.rid,
                           reason=h.result().reason)
                continue
            return h
        if last is not None:
            return last
        handle = ResultHandle(request)
        handle._set(Result(
            request.rid, STATUS_REJECTED,
            reason=f"no ready replica ({self._name}: "
                   f"{[r.state() for r in self._replicas]})"))
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    # ------------------------------------------------------------- lifecycle

    def rolling_restart(self) -> dict:
        """Migrate-then-restart fleet rotation: one replica at a time
        leaves rotation, its live rows are FROZEN and handed to a ready
        peer (KV pages + cursors over the wire, decode resumes mid-stream
        bit-identically — zero decodes restart from token 0), its queued
        backlog moves wholesale, and only then is the engine closed,
        rebuilt via the factory, its prefix cache warmed from a peer, and
        rejoined before the next replica leaves — peers absorb traffic
        throughout. A replica that cannot freeze (slab engine) falls back
        to the PR 7 drain-in-place rotation; a migration leg that fails
        degrades those rows to retry twins — zero dropped requests either
        way. Returns per-replica timings. Requires a factory; serialized
        against concurrent rotations."""
        if self._factory is None:
            raise RuntimeError("rolling_restart needs the Router built "
                               "with a factory")
        out = {}
        with self._restart_lock:
            with self._lock:
                rotation = list(self._replicas)
            for rep in rotation:
                t0 = time.monotonic()
                with self._lock:
                    if self._closed:
                        break  # close() won the race; nothing to rotate
                    if rep not in self._replicas:
                        continue  # retired underneath us (scale-in)
                    rep.routable = False
                idx = rep.idx
                self._publish_states()
                self._emit(ev="replica_rotate", router=self._name,
                           replica=idx, phase="migrate")
                # supervisor still attached while we freeze: a worker
                # crash mid-freeze is stashed (freeze_rows consumes it
                # into the retry fallback) and the supervisor idles on the
                # freezing/frozen states rather than respawning under us
                if not self._migrate_out(rep):
                    # can't freeze (slab engine / already terminal): the
                    # PR 7 path — drain FIRST, supervisor attached, so a
                    # crash mid-drain recovers and accepted work completes
                    self._emit(ev="replica_rotate", router=self._name,
                               replica=idx, phase="drain")
                    rep.engine.drain()
                if rep.supervisor is not None:
                    rep.supervisor.close()
                rep.engine.close()
                self._accumulate(rep.engine)
                fresh = self._factory()
                with self._lock:
                    pos = self._replicas.index(rep)
                    newrep = self._adopt(idx, fresh)
                    newrep.restarts = rep.restarts + 1
                    newrep.weight = rep.weight
                    self._replicas[pos] = newrep
                self._publish_states()
                self._warm_replica(newrep)
                out[idx] = round(time.monotonic() - t0, 6)
                self._emit(ev="replica_rotate", router=self._name,
                           replica=idx, phase="done", seconds=out[idx])
        return out

    # ---------------------------------------------------- elastic membership

    def add_replica(self) -> int:
        """Scale-out: factory-spawn a replica, warm its prefix cache from
        the warmest ready peer, and join it to the rendezvous ring — the
        join is one list append under the lock, so a concurrent
        ``_candidates`` snapshot sees the fleet either before or after,
        never half-joined. The fresh replica gets a brand-new supervisor
        (fresh restart-breaker window — it must not inherit a struggling
        peer's sliding-window history) and a never-before-used index. A
        spawn that fails or dies before the join is closed and discarded
        — the ring is untouched, no work existed to lose. Returns the new
        replica's index. Serialized against rotations/retires."""
        if self._factory is None:
            raise RuntimeError("add_replica needs the Router built with "
                               "a factory")
        with self._restart_lock:
            with self._lock:
                if self._closed:
                    raise RuntimeError("router is closed")
                idx = next(self._next_idx)
            faults.fire("serve.fleet", path=f"spawn-{idx}")
            rep = self._adopt(idx, self._factory())
            try:
                self._warm_replica(rep)
                faults.fire("serve.fleet", path=f"join-{idx}")
                if not rep.ready():
                    raise RuntimeError(
                        f"fresh replica {idx} not accepting "
                        f"(state {rep.state()}) — refusing to join it")
                with self._lock:
                    if self._closed:
                        raise RuntimeError("router closed during spawn")
                    self._replicas.append(rep)
            except BaseException:
                # orphan cleanup: the spawn never joined, nothing routed
                # to it, closing it drops no work
                if rep.supervisor is not None:
                    rep.supervisor.close()
                rep.engine.close()
                raise
        self._publish_states()
        self._emit(ev="replica_add", router=self._name, replica=idx,
                   replicas=self.replica_count())
        return idx

    def retire_replica(self, idx: int | None = None) -> int:
        """Scale-in: pull one replica (the least-loaded ready one when
        ``idx`` is None) out of rotation FIRST — it drops out of every
        rendezvous score list and readiness snapshot immediately — then
        migrate its live rows and queued backlog to its peers over the
        same lossless freeze→adopt path the rolling restart uses (legs
        that fail degrade to retry twins, never to dropped work), close
        it, and remove it from the fleet. Refuses to retire the last
        replica. Returns the retired index. Serialized against
        rotations/adds."""
        with self._restart_lock:
            with self._lock:
                if self._closed:
                    raise RuntimeError("router is closed")
                live = list(self._replicas)
                if len(live) <= 1:
                    raise RuntimeError("cannot retire the last replica")
                if idx is None:
                    ready = [r for r in live if r.ready()]
                    pool = ready if len(ready) >= 2 else live
                    rep = min(pool, key=lambda r: r.load())
                else:
                    rep = next((r for r in live if r.idx == idx), None)
                    if rep is None:
                        raise ValueError(f"no replica with index {idx}")
                rep.routable = False  # leaves every rendezvous list NOW
            self._publish_states()
            try:
                faults.fire("serve.fleet", path=f"retire-{rep.idx}")
            except BaseException:
                with self._lock:
                    rep.routable = True  # aborted before any state moved
                self._publish_states()
                raise
            self._emit(ev="replica_retire", router=self._name,
                       replica=rep.idx, phase="migrate")
            if not self._migrate_out(rep):
                self._emit(ev="replica_retire", router=self._name,
                           replica=rep.idx, phase="drain")
                rep.engine.drain()
            if rep.supervisor is not None:
                rep.supervisor.close()
            rep.engine.close()
            self._accumulate(rep.engine)
            with self._lock:
                if rep in self._replicas:
                    self._replicas.remove(rep)
            self._m_replica_state.labels(
                router=self._name, replica=rep.idx).set(
                    REPLICA_STATES["closed"])
        self._publish_states()
        self._emit(ev="replica_retire", router=self._name, replica=rep.idx,
                   phase="done", replicas=self.replica_count())
        return rep.idx

    def shed_weight(self, idx: int | None = None,
                    frac: float = 0.5) -> tuple[int, float]:
        """Rebalance: shrink one replica's rendezvous weight by ``frac``
        (the most-loaded ready replica when ``idx`` is None), re-placing
        exactly that share of its seen-prefix ownership onto its peers —
        weighted HRW guarantees no other replica's keys move. In-flight
        rows stay where they are (re-placement affects new routing only);
        the weight floor keeps the replica in every score list so it
        still serves as a failover candidate. Returns (index, new
        weight)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            live = [r for r in self._replicas if r.ready()]
            if not live:
                raise RuntimeError("no ready replica to rebalance")
            if idx is None:
                rep = max(live, key=lambda r: r.load())
            else:
                rep = next((r for r in self._replicas if r.idx == idx),
                           None)
                if rep is None:
                    raise ValueError(f"no replica with index {idx}")
        faults.fire("serve.fleet", path=f"shed-{rep.idx}")
        with self._lock:
            rep.weight = max(0.05, rep.weight * (1.0 - float(frac)))
            new = rep.weight
        self._emit(ev="rebalance", router=self._name, replica=rep.idx,
                   weight=round(new, 4), frac=frac)
        return rep.idx, new

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def replica_view(self) -> list[dict]:
        """Per-replica routing state for the fleet controller and
        ``GET /debug/fleet``: index, lifecycle state, queue depth,
        rendezvous weight, restart count. The controller's ONLY source of
        truth — it keeps no fleet state of its own, so a restarted
        controller reconstructs everything from this view."""
        with self._lock:
            reps = list(self._replicas)
        return [{"replica": r.idx, "state": r.state(), "load": r.load(),
                 "weight": round(r.weight, 4), "restarts": r.restarts}
                for r in reps]

    def _migrate_out(self, rep: _Replica) -> bool:
        """Freeze ``rep`` and move everything it holds: live rows adopt
        onto the least-loaded ready paged peer (KV travels, decode resumes
        mid-stream), the queued backlog moves as-is (same entries — they
        never started, no twin needed), and rows any leg failed on degrade
        to fresh-attempt retry twins. Admission reservations move exactly
        once: the target charges at bind (``AdmissionQueue.adopt``), the
        source releases here per moved row; a row nobody can take retires
        on the SOURCE (still charged there) so the release stays paired.
        Returns False when the engine cannot freeze — caller drains."""
        eng = rep.engine
        try:
            frozen = eng.freeze_rows()
        except Exception as exc:
            self._emit(ev="migrate", router=self._name, replica=rep.idx,
                       phase="freeze_failed",
                       reason=f"{type(exc).__name__}: {exc}")
            return False
        if frozen is None:
            return False
        entries = dict(frozen["entries"])
        fallback = list(frozen["fallback"])
        adopted: list = []
        target = None
        if frozen["blob"] is not None and entries:
            target = self._pick_target(exclude=rep)
            if target is None:
                fallback.extend(entries.values())
            else:
                try:
                    res = target.engine.adopt_rows(frozen)
                    adopted = list(res["adopted"])
                    fallback.extend(res["fallback"])
                except MigrationError as exc:
                    self._emit(ev="migrate", router=self._name,
                               replica=rep.idx, target=target.idx,
                               phase="adopt_failed",
                               reason=f"{type(exc).__name__}: {exc}")
                    fallback.extend(entries.values())
        elif entries:
            fallback.extend(entries.values())
        # the target charged each adopted row's reservation at bind —
        # release the source's half of the handoff
        for rid in adopted:
            eng._queue.release(entries[rid].cost)
        moved_q = self._place_entries(rep, frozen["queued"], retry=False)
        retried = self._place_entries(rep, fallback, retry=True)
        if fallback:
            eng.metrics.record_migration("fallback", len(fallback))
        self._emit(ev="migrate", router=self._name, replica=rep.idx,
                   target=target.idx if target is not None else None,
                   adopted=len(adopted), queued_moved=moved_q,
                   fallback=len(fallback), retried=retried)
        return True

    def _pick_target(self, exclude: _Replica) -> _Replica | None:
        """Least-loaded ready PAGED peer — the adoption target."""
        with self._lock:
            cands = [r for r in self._replicas
                     if r is not exclude and r.ready()
                     and getattr(r.engine, "paged", False)]
        return min(cands, key=lambda r: r.load(), default=None)

    def _place_entries(self, src: _Replica, entries, retry: bool) -> int:
        """Move queue-only work off a frozen source: each entry (or its
        fresh-attempt twin when ``retry`` — the PR 7 contract for rows
        whose migration failed) is force-admitted on a ready peer and the
        source's reservation released; an entry no peer can take — or a
        retry with no attempts left — retires on the source, whose charge
        the retirement releases. Returns how many were placed."""
        placed = 0
        for e in entries:
            if e.superseded or e.handle.done():
                continue
            if retry:
                moved = e.retry()  # supersedes e; reservation carried
                # an infrastructure-initiated restart is not the request's
                # fault: the attempt budget charges compute faults (PR 7
                # crash/decode retries), never a migration fallback — a
                # max_attempts=1 request must still survive a rotation
                moved.attempt = e.attempt
                src.engine.metrics.record_retry(
                    e.request.rid, moved.attempt, e.request.max_attempts,
                    "migration fallback")
            else:
                moved = e
            landed = False
            with self._lock:
                cands = sorted((r for r in self._replicas
                                if r is not src and r.ready()),
                               key=lambda r: r.load())
            for cand in cands:
                try:
                    if cand.engine.adopt_entries([moved]):
                        landed = True
                        break
                except Exception:
                    continue
            if landed:
                src.engine._queue.release(e.cost)
                placed += 1
            else:
                # nobody accepting: retire on the source, still charged
                # there — its release pairs with the original admit
                src.engine._retire(moved, Result(
                    moved.request.rid, STATUS_SHUTTING_DOWN,
                    reason="no ready replica to migrate to"))
        return placed

    def _warm_replica(self, fresh: _Replica) -> None:
        """Warm a rebuilt or freshly spawned replica's prefix cache from
        the busiest ready peer's hottest chains
        (``serve_cache_warm_prefixes``). ``fresh`` need not be in the
        replica list yet — scale-out warms BEFORE the ring join. Entirely
        best-effort: every failure path is a cold cache, never a failed
        rotation."""
        n = get_config().serve_cache_warm_prefixes
        with self._lock:
            peers = [r for r in self._replicas
                     if r is not fresh and r.ready()
                     and getattr(r.engine, "paged", False)]
        if n <= 0 or not getattr(fresh.engine, "paged", False) or not peers:
            return
        # warmest peer first: the one whose cache has answered the most —
        # affinity concentrates a shared prefix there
        peers.sort(key=lambda r: r.engine.metrics.snapshot()["prefix_hits"],
                   reverse=True)
        for peer in peers:
            try:
                blob = peer.engine.export_prefixes(n)
                if not blob:
                    continue
                got = fresh.engine.import_prefixes(blob)
            except Exception:
                continue
            if got:
                self._emit(ev="migrate", router=self._name,
                           replica=fresh.idx, phase="cache_warm",
                           source=peer.idx, prefixes=got)
                return

    def _accumulate(self, engine) -> None:
        """Fold a retiring engine's final counter snapshot into the
        router's running totals (see ``_COUNTER_KEYS``)."""
        try:
            snap = engine.metrics.snapshot()
        except Exception:
            return
        with self._lock:
            for k in _COUNTER_KEYS:
                self._retired[k] += snap.get(k) or 0

    def drain(self) -> None:
        """Drain every replica (concurrently — they are independent) and
        stop routing. Terminal. Serializes behind an in-flight rotation —
        the documented drain/close/rolling_restart mutual exclusion; a
        drain racing the rotation's replica swap would miss the fresh
        engine."""
        with self._restart_lock:
            with self._lock:
                reps = list(self._replicas)
                for rep in reps:
                    rep.routable = False
            threads = [threading.Thread(target=rep.engine.drain)
                       for rep in reps]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self._publish_states()

    def close(self) -> None:
        """Close supervisors and engines; unregister the health provider.
        Idempotent; waits out an in-flight rolling restart so a
        freshly-built replica can never be swapped in (and leaked) after
        the close."""
        with self._restart_lock:
            with self._lock:
                if self._closed:
                    return
                self._closed = True
                reps = list(self._replicas)
                for rep in reps:
                    rep.routable = False
            for rep in reps:
                if rep.supervisor is not None:
                    rep.supervisor.close()
                rep.engine.close()
        self._publish_states()
        unregister_health_provider(self._name)
        unregister_slo_provider(self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------- introspection

    def pending(self) -> int:
        with self._lock:
            return sum(r.engine.pending() for r in self._replicas)

    def _fleet_slo(self) -> dict | None:
        """The fleet scope for ``GET /debug/slo``: every live replica's SLO
        payload worst-case-merged (:func:`~marlin_tpu.obs.slo.fleet_merge`)
        so one burning replica surfaces at the top level with its name.
        None (provider prunes) when no replica has objectives configured."""
        with self._lock:
            if self._closed:
                return None
            reps = list(self._replicas)
        payloads = []
        for rep in reps:
            try:
                p = rep.engine._slo_payload()
            except Exception:
                p = None
            if p is not None:
                payloads.append(p)
        if not payloads:
            return None
        merged = fleet_merge(payloads)
        merged["router"] = self._name
        return merged

    def _health_info(self) -> dict:
        """The aggregated /healthz payload: ready while ANY replica
        accepts (a rolling restart must not 503 the process), with the
        per-replica detail inline."""
        with self._lock:
            reps = list(self._replicas)
        detail = []
        for rep in reps:
            info = rep.engine._health_info()
            info["name"] = rep.engine._name
            info["replica"] = rep.idx
            info["state"] = rep.state() if rep.state() != "accepting" \
                else info["state"]
            if rep.supervisor is not None:
                info["supervisor"] = rep.supervisor.info()
            detail.append(info)
        any_ready = any(rep.ready() for rep in reps)
        return {"state": "accepting" if any_ready else "closed",
                "replicas": detail}

    def snapshot(self) -> dict:
        """Merged per-replica ``ServeMetrics.snapshot()`` counters plus the
        per-replica list — the router-level accounting the bench records.
        The replica list is copied under the lock so a concurrent rotation
        cannot be read mid-swap. Counters (including the prefix hit/miss
        pair and the migration legs) span the fleet's whole history:
        engines retired by a rotation folded their final snapshots into
        the router's totals at swap time. Gauges (pages_*) are
        current-replicas-only."""
        with self._lock:
            reps = list(self._replicas)
            retired = dict(self._retired)
        snaps = [(rep.idx, rep.engine.metrics.snapshot()) for rep in reps]
        agg: dict = {"replicas": {i: s for i, s in snaps}}
        for key in ("submitted", "rejected", "expired", "completed",
                    "errors", "shut_down", "retries", "batches", "steps",
                    "new_tokens", "prefix_hits", "prefix_misses",
                    "migrated_out", "migrated_in", "migrate_fallback",
                    "program_steps", "program_rows", "swaps"):
            agg[key] = (sum(s.get(key, 0) for _, s in snaps)
                        + retired.get(key, 0))
        for key in ("pages_total", "pages_used", "pages_shared"):
            agg[key] = sum(s.get(key, 0) for _, s in snaps)
        busy = sum(s["busy_s"] for _, s in snaps) + retired.get("busy_s", 0)
        agg["busy_s"] = round(busy, 6)
        agg["tok_s"] = (round(agg["new_tokens"] / busy, 2) if busy > 0
                        else None)
        lookups = agg["prefix_hits"] + agg["prefix_misses"]
        agg["prefix_hit_rate"] = (round(agg["prefix_hits"] / lookups, 4)
                                  if lookups else None)
        return agg
