"""Multi-replica serving router: obs-fed load balancing over N supervised
:class:`~marlin_tpu.serving.engine.ServeEngine` replicas, with failover and
drain-safe rolling restarts.

One engine is one worker loop on (implicitly) one device set; the ROADMAP's
"millions of users" story needs N of them behind one front door. A
:class:`Router` owns that front door:

- **Routing** is power-of-two-choices over the replicas that are *ready*
  (engine accepting, supervisor breaker closed, not mid-restart): pick two
  distinct candidates at random, route to the less loaded by the same
  queue-depth gauge ``/metrics`` exports (``AdmissionQueue.count`` — the
  obs-fed signal, read directly so routing needs no scrape). Two random
  choices beat one by an exponential load-spread factor and beat
  full-scan-least-loaded by not herding every submit onto one replica
  between gauge updates.
- **Failover**: a replica that rejects (overload), reports shutting-down,
  or fails outright (the ``serve.router_route`` fault point simulates
  this) is skipped for this request and the remaining replicas are tried
  in load order. Only when every replica refuses does the caller see a
  terminal Result — deterministic, never an exception from a healthy
  router.
- **Rolling restart** (:meth:`rolling_restart`): one replica at a time is
  pulled from rotation, drained (everything it accepted completes),
  closed with its supervisor, rebuilt via the factory, and put back before
  the next replica starts — the rest absorb traffic throughout, so a
  fleet-wide restart drops zero requests and double-delivers none (the
  per-engine exactly-once contract is untouched).
- **One scrape target**: the router registers a single aggregated health
  provider (each adopted engine's individual provider is unregistered —
  a draining replica mid-rotation must NOT 503 the process while its
  peers absorb traffic; the router reports not-ready only when NO replica
  accepts) and publishes ``marlin_serve_replica_state{router=,replica=}``
  (0 accepting / 1 draining / 2 restarting / 3 closed / 4 failed).
  Per-engine serving metrics already aggregate in the process registry;
  :meth:`snapshot` merges the per-replica ``ServeMetrics`` snapshots for
  tests and the bench.

``Router(factory, replicas=N)`` builds N engines up front via the zero-arg
``factory`` (also used by rolling restarts); ``Router(engines=[...])``
adopts existing engines but cannot rolling-restart without a factory.
"""

from __future__ import annotations

import itertools
import random
import threading
import time

from ..config import get_config
from ..obs.exposition import (register_health_provider,
                              unregister_health_provider)
from ..obs.metrics import get_registry
from ..utils import faults
from .request import (STATUS_REJECTED, STATUS_SHUTTING_DOWN, Request, Result,
                      ResultHandle)
from .supervisor import Supervisor, _emit

__all__ = ["Router", "REPLICA_STATES"]

_router_ids = itertools.count()

#: the ``marlin_serve_replica_state`` gauge encoding
REPLICA_STATES = {"accepting": 0, "draining": 1, "restarting": 2,
                  "closed": 3, "failed": 4}

#: handle statuses that trigger failover to the next replica (an expired
#: deadline is final everywhere; an error Result means the request RAN)
_FAILOVER = (STATUS_REJECTED, STATUS_SHUTTING_DOWN)


class _Replica:
    """One engine + its supervisor + routing state. ``routable`` is the
    router-side gate (rolling restart pulls a replica from rotation before
    the engine itself starts draining)."""

    __slots__ = ("idx", "engine", "supervisor", "routable", "restarts")

    def __init__(self, idx: int, engine, supervisor):
        self.idx = idx
        self.engine = engine
        self.supervisor = supervisor
        self.routable = True
        self.restarts = 0

    def state(self) -> str:
        if self.supervisor is not None and self.supervisor.breaker_open:
            return "failed"
        eng_state = {"running": "accepting", "draining": "draining",
                     "closing": "closed",
                     "closed": "closed"}[self.engine._state]
        if eng_state == "closed":
            return "closed"
        if not self.routable:
            return "restarting"   # pulled from rotation, being rebuilt
        return eng_state

    def ready(self) -> bool:
        return self.state() == "accepting"

    def load(self) -> int:
        return self.engine._queue.count


class Router:
    """Route :class:`Request` submissions across N engine replicas.

    ``factory`` is a zero-arg callable returning a fresh, started
    :class:`ServeEngine`; ``replicas`` defaults from
    ``config.serve_replicas``. Pass ``engines=[...]`` to adopt
    pre-built engines instead (``factory`` then remains optional but is
    required for :meth:`rolling_restart`). ``supervise=True`` (default)
    wraps every replica in a :class:`~.supervisor.Supervisor`;
    ``supervisor_kw`` tunes it (watchdog_s, restart_max, ...). ``rng``
    seeds the power-of-two choice for deterministic tests.

    Thread-safe: ``submit`` may be called from any number of threads;
    ``rolling_restart``/``drain``/``close`` serialize against each other.
    Usable as a context manager (``close()`` on exit)."""

    def __init__(self, factory=None, replicas: int | None = None, *,
                 engines=None, supervise: bool = True,
                 supervisor_kw: dict | None = None, rng=None, log=None,
                 warmup: bool = False):
        if factory is None and engines is None:
            raise ValueError("Router needs a factory or engines=[...]")
        self._factory = factory
        self._supervise = supervise
        self._supervisor_kw = dict(supervisor_kw or {})
        self._log = log
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()        # replica list + lifecycle
        self._restart_lock = threading.Lock()  # one rotation at a time
        self._closed = False
        self._name = f"marlin-router-{next(_router_ids)}"
        reg = get_registry()
        self._m_replica_state = reg.gauge(
            "marlin_serve_replica_state",
            "Router replica state (0 accepting / 1 draining / 2 restarting "
            "/ 3 closed / 4 failed)", labelnames=("router", "replica"))
        if engines is None:
            n = int(get_config().serve_replicas if replicas is None
                    else replicas)
            if n < 1:
                raise ValueError(f"replicas must be >= 1, got {n}")
            engines = [factory() for _ in range(n)]
        self._replicas = [self._adopt(i, eng)
                          for i, eng in enumerate(engines)]
        if warmup:
            for rep in self._replicas:
                rep.engine.warmup()
        register_health_provider(self._name, self._health_info)
        self._publish_states()

    # -------------------------------------------------------------- plumbing

    def _adopt(self, idx: int, engine) -> _Replica:
        # the router is THE scrape target: fold the engine's readiness into
        # the aggregate view so one draining replica cannot 503 a process
        # whose other replicas are absorbing its traffic
        unregister_health_provider(engine._name)
        sup = Supervisor(engine, log=self._log,
                         **self._supervisor_kw) if self._supervise else None
        return _Replica(idx, engine, sup)

    def _emit(self, **fields) -> None:
        _emit(self._log, **fields)

    def _publish_states(self) -> None:
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            self._m_replica_state.labels(
                router=self._name, replica=rep.idx).set(
                    REPLICA_STATES[rep.state()])

    # --------------------------------------------------------------- routing

    def _candidates(self) -> list[_Replica]:
        """Ready replicas in routing preference order: power-of-two-choices
        first (two distinct random picks, less loaded first), then the rest
        by load — the failover order."""
        with self._lock:
            ready = [r for r in self._replicas if r.ready()]
        if len(ready) <= 2:
            return sorted(ready, key=lambda r: r.load())
        a, b = self._rng.sample(ready, 2)
        first = sorted([a, b], key=lambda r: r.load())
        rest = sorted((r for r in ready if r is not a and r is not b),
                      key=lambda r: r.load())
        return first + rest

    def submit(self, request: Request) -> ResultHandle:
        """Route one request: exactly one terminal Result, always. Tries
        the power-of-two pick, then fails over across every remaining
        ready replica on rejection / shutdown / route failure; only when
        all refuse does the caller see the last refusal (or a synthesized
        ``rejected`` Result when no replica is ready at all)."""
        last = None
        for rep in self._candidates():
            try:
                faults.fire("serve.router_route", path=f"replica-{rep.idx}")
                h = rep.engine.submit(request)
            except Exception as exc:
                self._emit(ev="route_failover", router=self._name,
                           replica=rep.idx, rid=request.rid,
                           reason=f"{type(exc).__name__}: {exc}")
                continue
            if h.done() and h.result().status in _FAILOVER:
                last = h
                self._emit(ev="route_failover", router=self._name,
                           replica=rep.idx, rid=request.rid,
                           reason=h.result().reason)
                continue
            return h
        if last is not None:
            return last
        handle = ResultHandle(request)
        handle._set(Result(
            request.rid, STATUS_REJECTED,
            reason=f"no ready replica ({self._name}: "
                   f"{[r.state() for r in self._replicas]})"))
        return handle

    def submit_many(self, requests) -> list[ResultHandle]:
        return [self.submit(r) for r in requests]

    # ------------------------------------------------------------- lifecycle

    def rolling_restart(self) -> dict:
        """Drain-safe fleet rotation: one replica at a time leaves rotation,
        drains (all accepted work completes), closes with its supervisor,
        is rebuilt via the factory, and rejoins before the next leaves —
        peers absorb traffic throughout. Returns per-replica timings.
        Requires a factory; serialized against concurrent rotations."""
        if self._factory is None:
            raise RuntimeError("rolling_restart needs the Router built "
                               "with a factory")
        out = {}
        with self._restart_lock:
            for idx in range(len(self._replicas)):
                t0 = time.monotonic()
                with self._lock:
                    if self._closed:
                        break  # close() won the race; nothing to rotate
                    rep = self._replicas[idx]
                    rep.routable = False
                self._publish_states()
                self._emit(ev="replica_rotate", router=self._name,
                           replica=idx, phase="drain")
                # drain FIRST, supervisor still attached: a worker crash
                # mid-drain is recovered and the accepted work completes
                # (drain's join waits out supervised recoveries) — closing
                # the supervisor first would turn that crash into failed
                # requests, breaking the zero-dropped rotation guarantee
                rep.engine.drain()
                if rep.supervisor is not None:
                    rep.supervisor.close()
                rep.engine.close()
                fresh = self._factory()
                with self._lock:
                    self._replicas[idx] = self._adopt(idx, fresh)
                    self._replicas[idx].restarts = rep.restarts + 1
                self._publish_states()
                out[idx] = round(time.monotonic() - t0, 6)
                self._emit(ev="replica_rotate", router=self._name,
                           replica=idx, phase="done", seconds=out[idx])
        return out

    def drain(self) -> None:
        """Drain every replica (concurrently — they are independent) and
        stop routing. Terminal. Serializes behind an in-flight rotation —
        the documented drain/close/rolling_restart mutual exclusion; a
        drain racing the rotation's replica swap would miss the fresh
        engine."""
        with self._restart_lock:
            with self._lock:
                reps = list(self._replicas)
                for rep in reps:
                    rep.routable = False
            threads = [threading.Thread(target=rep.engine.drain)
                       for rep in reps]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self._publish_states()

    def close(self) -> None:
        """Close supervisors and engines; unregister the health provider.
        Idempotent; waits out an in-flight rolling restart so a
        freshly-built replica can never be swapped in (and leaked) after
        the close."""
        with self._restart_lock:
            with self._lock:
                if self._closed:
                    return
                self._closed = True
                reps = list(self._replicas)
                for rep in reps:
                    rep.routable = False
            for rep in reps:
                if rep.supervisor is not None:
                    rep.supervisor.close()
                rep.engine.close()
        self._publish_states()
        unregister_health_provider(self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------- introspection

    def pending(self) -> int:
        with self._lock:
            return sum(r.engine.pending() for r in self._replicas)

    def _health_info(self) -> dict:
        """The aggregated /healthz payload: ready while ANY replica
        accepts (a rolling restart must not 503 the process), with the
        per-replica detail inline."""
        with self._lock:
            reps = list(self._replicas)
        detail = []
        for rep in reps:
            info = rep.engine._health_info()
            info["name"] = rep.engine._name
            info["replica"] = rep.idx
            info["state"] = rep.state() if rep.state() != "accepting" \
                else info["state"]
            if rep.supervisor is not None:
                info["supervisor"] = rep.supervisor.info()
            detail.append(info)
        any_ready = any(rep.ready() for rep in reps)
        return {"state": "accepting" if any_ready else "closed",
                "replicas": detail}

    def snapshot(self) -> dict:
        """Merged per-replica ``ServeMetrics.snapshot()`` counters plus the
        per-replica list — the router-level accounting the bench records.
        The replica list is copied under the lock so a concurrent rotation
        cannot be read mid-swap (counters of a replica retired by the
        rotation are gone — snapshot totals span the CURRENT engines)."""
        with self._lock:
            reps = list(self._replicas)
        snaps = [(rep.idx, rep.engine.metrics.snapshot()) for rep in reps]
        agg: dict = {"replicas": {i: s for i, s in snaps}}
        for key in ("submitted", "rejected", "expired", "completed",
                    "errors", "shut_down", "retries", "batches", "steps",
                    "new_tokens", "prefix_hits", "prefix_misses",
                    "pages_total", "pages_used", "pages_shared"):
            agg[key] = sum(s.get(key, 0) for _, s in snaps)
        busy = sum(s["busy_s"] for _, s in snaps)
        agg["busy_s"] = round(busy, 6)
        agg["tok_s"] = (round(agg["new_tokens"] / busy, 2) if busy > 0
                        else None)
        return agg
