"""Continuous-batching inference serving (docs/serving.md).

The ROADMAP north star is "heavy traffic from millions of users"; the
reference delegated all request scheduling to Spark (SURVEY.md §0). This
package is the TPU-native replacement front half: admission control
(request.py), shape bucketing + per-bucket claim queues (batcher.py), the
paged KV-cache pool with copy-on-write prefix sharing (kvpool.py), the
worker-loop engine with chunked prefill and a drain-safe lifecycle
(engine.py), serving
observability through the EventLog (metrics.py), supervised worker
recovery with a restart circuit breaker (supervisor.py), and a
multi-replica router with failover, drain-safe rolling restarts, and
elastic membership (router.py), driven by the SLO-burn fleet controller
(fleet.py — docs/robustness.md covers the resilience layer).

The spine is workload-pluggable (programs/): ``Request.program`` routes a
request to a registered :class:`~.programs.BucketProgram` — paged LM
decode is the first implementation, and ALS recommendation scoring,
incremental PageRank queries, and batched classification ship alongside
it, all sharing the same admission budget, bucketing, supervisor, and
router (docs/serving.md, "BucketProgram interface").

Quick start::

    from marlin_tpu.serving import Request, ServeEngine

    with ServeEngine(params, heads=lm.heads) as eng:
        eng.warmup()                              # compile once per bucket
        h = eng.submit(Request(prompt=[1, 2, 3], steps=16))
        tokens = h.result(timeout=60).tokens
"""

from .batcher import (  # noqa: F401
    BatchFormer,
    SlotPool,
    aot_compile_buckets,
    bucket_kv_bytes,
    normalize_buckets,
    pick_bucket,
    planner_ratio_warning,
    warmup_buckets,
)
from .engine import ServeEngine  # noqa: F401
from .kvpool import (  # noqa: F401
    PagedGroup,
    PagedKVPool,
    PagePoolExhausted,
    auto_num_pages,
)
from .fleet import FleetController  # noqa: F401
from .metrics import ServeMetrics, percentile  # noqa: F401
from .programs import (  # noqa: F401
    PROGRAM_REGISTRY,
    ALSScoreProgram,
    BucketProgram,
    ClassifyProgram,
    PagedLMProgram,
    PageRankQueryProgram,
    ProgramRowSet,
    available_programs,
    register_program,
)
from .router import Router  # noqa: F401
from .supervisor import Supervisor  # noqa: F401
from .request import (  # noqa: F401
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTTING_DOWN,
    AdmissionQueue,
    Request,
    Result,
    ResultHandle,
)
