"""Serving observability: counters + per-request latencies through EventLog.

Every record goes to the engine's :class:`~marlin_tpu.utils.tracing.EventLog`
(or the process default, resolved per emit so a log installed mid-run is
picked up) under the single kind ``"serve"`` with an ``ev`` discriminator:

=============  ===========================================================
``ev``         fields
=============  ===========================================================
``enqueue``    ``rid``, ``bucket``, ``depth`` (queue depth after admit)
``reject``     ``rid``, ``reason``
``prefill``    row-level scheduling, one per slot prefill: ``bucket``,
               ``new_tokens`` (1 — the row's first token lands here),
               ``seconds`` (prefill wall time)
``batch``      gang scheduling, one per dispatched batch: ``bucket``,
               ``rows`` (live), ``occupancy`` (live/max_batch),
               ``new_tokens``, ``seconds`` (wall), ``tok_s``
``step``       row-level scheduling, one per DECODE STEP over the slab:
               ``bucket``, ``rows`` (live this step), ``occupancy``,
               ``new_tokens`` (= live rows), ``seconds`` (wall decode-step
               latency), ``tok_s`` — the per-step occupancy stream is how
               slot refill is asserted (a finished row's slot shows
               occupied again on the next step's record)
``result``     ``rid``, ``status``, ``bucket``, ``queue_s``, ``ttft_s``,
               ``total_s``
=============  ===========================================================

Latencies are measured on the engine's *injected* clock (deterministic
tests), throughput (``tok_s``) on the real wall clock (it is a measurement,
not a policy input). Under gang scheduling a row's first token becomes
visible only when its batch's whole generation program returns, so
``ttft_s`` equals ``total_s`` there; under row-level scheduling the first
token lands with the slot's prefill, so ``ttft_s`` is genuinely earlier —
the headline latency the row-level split buys (docs/serving.md).

:meth:`ServeMetrics.snapshot` aggregates everything for tests and the bench
(`bench_all.py serve`) without re-reading the log file.
"""

from __future__ import annotations

import math
import threading

from ..utils.tracing import get_default_event_log

__all__ = ["ServeMetrics", "percentile"]


def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of a non-empty list — tiny and
    dependency-free so the bench and tests share one definition."""
    xs = sorted(values)
    if not xs:
        raise ValueError("percentile of empty list")
    i = max(0, min(len(xs) - 1, math.ceil(q / 100.0 * len(xs)) - 1))
    return xs[i]


class ServeMetrics:
    """Thread-safe counter/latency sink for one engine. All record_* methods
    are called by the engine (submit path + worker thread) — never raise out
    of them into the serving path."""

    def __init__(self, log=None, keep_latencies: int = 4096):
        self._log = log
        self._lock = threading.Lock()
        self._keep = keep_latencies
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.completed = 0
        self.errors = 0
        self.shut_down = 0
        self.batches = 0
        self.steps = 0
        self.new_tokens = 0
        self.busy_s = 0.0
        self._occupancy_sum = 0.0
        self._step_occupancy_sum = 0.0
        self._total_s: list[float] = []
        self._queue_s: list[float] = []
        self._ttft_s: list[float] = []
        self._step_s: list[float] = []

    def _emit(self, **fields) -> None:
        log = self._log or get_default_event_log()
        if log is not None:
            log.event("serve", **fields)

    def record_enqueue(self, rid: int, bucket, depth: int) -> None:
        with self._lock:
            self.submitted += 1
        self._emit(ev="enqueue", rid=rid, bucket=list(bucket), depth=depth)

    def record_reject(self, rid: int, reason: str) -> None:
        with self._lock:
            self.rejected += 1
        self._emit(ev="reject", rid=rid, reason=reason)

    def record_batch(self, bucket, rows: int, max_batch: int,
                     new_tokens: int, seconds: float) -> None:
        with self._lock:
            self.batches += 1
            self.new_tokens += new_tokens
            self.busy_s += seconds
            self._occupancy_sum += rows / max_batch
        self._emit(ev="batch", bucket=list(bucket), rows=rows,
                   occupancy=round(rows / max_batch, 4),
                   new_tokens=new_tokens, seconds=seconds,
                   tok_s=round(new_tokens / max(seconds, 1e-9), 2))

    def record_prefill(self, bucket, seconds: float) -> None:
        """One row-level slot prefill: the row's FIRST token is emitted here
        (real TTFT), so it counts toward ``new_tokens``/``busy_s`` — without
        this, steps=1 traffic would report zero tokens and every request
        would be undercounted by one versus the gang accounting."""
        with self._lock:
            self.new_tokens += 1
            self.busy_s += seconds
        self._emit(ev="prefill", bucket=list(bucket), new_tokens=1,
                   seconds=seconds)

    def record_step(self, bucket, rows: int, max_batch: int,
                    seconds: float) -> None:
        """One row-level decode step over a bucket's slab: ``rows`` live
        slots each emitted one token (``new_tokens`` == ``rows``)."""
        with self._lock:
            self.steps += 1
            self.new_tokens += rows
            self.busy_s += seconds
            self._step_occupancy_sum += rows / max_batch
            if len(self._step_s) < self._keep:
                self._step_s.append(seconds)
        self._emit(ev="step", bucket=list(bucket), rows=rows,
                   occupancy=round(rows / max_batch, 4), new_tokens=rows,
                   seconds=seconds,
                   tok_s=round(rows / max(seconds, 1e-9), 2))

    def record_result(self, rid: int, status: str, bucket=None,
                      queue_s: float | None = None,
                      total_s: float | None = None,
                      ttft_s: float | None = None) -> None:
        with self._lock:
            if status == "ok":
                self.completed += 1
            elif status == "expired":
                self.expired += 1
            elif status == "error":
                self.errors += 1
            elif status == "shutting_down":
                self.shut_down += 1
            if total_s is not None and len(self._total_s) < self._keep:
                self._total_s.append(total_s)
            if queue_s is not None and len(self._queue_s) < self._keep:
                self._queue_s.append(queue_s)
            # ttft falls back to total_s ONLY for completed gang results
            # (their first token really does surface with the whole batch);
            # expired/error requests never produced a token, and counting
            # their wait as time-to-first-token would corrupt the headline
            # percentile the row-level A/B measures
            if ttft_s is None and status == "ok":
                ttft_s = total_s
            if ttft_s is not None and len(self._ttft_s) < self._keep:
                self._ttft_s.append(ttft_s)
        fields = {"ev": "result", "rid": rid, "status": status}
        if bucket is not None:
            fields["bucket"] = list(bucket)
        if queue_s is not None:
            fields["queue_s"] = queue_s
        if ttft_s is not None:
            fields["ttft_s"] = ttft_s
        if total_s is not None:
            fields["total_s"] = total_s
        self._emit(**fields)

    def snapshot(self) -> dict:
        """One aggregate dict: counters plus occupancy mean (over gang
        batches and row-level decode steps alike), tokens/s over engine busy
        time, and p50/p99 total / ttft latency (None until data)."""
        with self._lock:
            lat = list(self._total_s)
            qs = list(self._queue_s)
            tt = list(self._ttft_s)
            ss = list(self._step_s)
            dispatches = self.batches + self.steps
            occ = self._occupancy_sum + self._step_occupancy_sum
            out = {
                "submitted": self.submitted, "rejected": self.rejected,
                "expired": self.expired, "completed": self.completed,
                "errors": self.errors, "shut_down": self.shut_down,
                "batches": self.batches, "steps": self.steps,
                "new_tokens": self.new_tokens,
                "busy_s": round(self.busy_s, 6),
                "occupancy_mean": (round(occ / dispatches, 4)
                                   if dispatches else None),
                "tok_s": (round(self.new_tokens / self.busy_s, 2)
                          if self.busy_s > 0 else None),
            }
        out["p50_total_s"] = percentile(lat, 50) if lat else None
        out["p99_total_s"] = percentile(lat, 99) if lat else None
        out["p50_queue_s"] = percentile(qs, 50) if qs else None
        out["p50_ttft_s"] = percentile(tt, 50) if tt else None
        out["p99_ttft_s"] = percentile(tt, 99) if tt else None
        out["p50_step_s"] = percentile(ss, 50) if ss else None
        return out
