"""Serving observability: counters + per-request latencies through EventLog.

Every record goes to the engine's :class:`~marlin_tpu.utils.tracing.EventLog`
(or the process default, resolved per emit so a log installed mid-run is
picked up) under the single kind ``"serve"`` with an ``ev`` discriminator:

=============  ===========================================================
``ev``         fields
=============  ===========================================================
``enqueue``    ``rid``, ``bucket``, ``depth`` (queue depth after admit)
``reject``     ``rid``, ``reason``
``batch``      ``bucket``, ``rows`` (live), ``occupancy`` (live/max_batch),
               ``new_tokens``, ``seconds`` (wall), ``tok_s``
``result``     ``rid``, ``status``, ``bucket``, ``queue_s``, ``ttft_s``,
               ``total_s``
=============  ===========================================================

Latencies are measured on the engine's *injected* clock (deterministic
tests), throughput (``tok_s``) on the real wall clock (it is a measurement,
not a policy input). Under the engine's gang scheduling a row's first token
becomes visible only when its batch's whole generation program returns, so
``ttft_s`` equals ``total_s`` today; both are recorded so the contract is
stable when a streaming decode loop lands (docs/serving.md).

:meth:`ServeMetrics.snapshot` aggregates everything for tests and the bench
(`bench_all.py serve`) without re-reading the log file.
"""

from __future__ import annotations

import math
import threading

from ..utils.tracing import get_default_event_log

__all__ = ["ServeMetrics", "percentile"]


def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of a non-empty list — tiny and
    dependency-free so the bench and tests share one definition."""
    xs = sorted(values)
    if not xs:
        raise ValueError("percentile of empty list")
    i = max(0, min(len(xs) - 1, math.ceil(q / 100.0 * len(xs)) - 1))
    return xs[i]


class ServeMetrics:
    """Thread-safe counter/latency sink for one engine. All record_* methods
    are called by the engine (submit path + worker thread) — never raise out
    of them into the serving path."""

    def __init__(self, log=None, keep_latencies: int = 4096):
        self._log = log
        self._lock = threading.Lock()
        self._keep = keep_latencies
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.completed = 0
        self.errors = 0
        self.shut_down = 0
        self.batches = 0
        self.new_tokens = 0
        self.busy_s = 0.0
        self._occupancy_sum = 0.0
        self._total_s: list[float] = []
        self._queue_s: list[float] = []

    def _emit(self, **fields) -> None:
        log = self._log or get_default_event_log()
        if log is not None:
            log.event("serve", **fields)

    def record_enqueue(self, rid: int, bucket, depth: int) -> None:
        with self._lock:
            self.submitted += 1
        self._emit(ev="enqueue", rid=rid, bucket=list(bucket), depth=depth)

    def record_reject(self, rid: int, reason: str) -> None:
        with self._lock:
            self.rejected += 1
        self._emit(ev="reject", rid=rid, reason=reason)

    def record_batch(self, bucket, rows: int, max_batch: int,
                     new_tokens: int, seconds: float) -> None:
        with self._lock:
            self.batches += 1
            self.new_tokens += new_tokens
            self.busy_s += seconds
            self._occupancy_sum += rows / max_batch
        self._emit(ev="batch", bucket=list(bucket), rows=rows,
                   occupancy=round(rows / max_batch, 4),
                   new_tokens=new_tokens, seconds=seconds,
                   tok_s=round(new_tokens / max(seconds, 1e-9), 2))

    def record_result(self, rid: int, status: str, bucket=None,
                      queue_s: float | None = None,
                      total_s: float | None = None) -> None:
        with self._lock:
            if status == "ok":
                self.completed += 1
            elif status == "expired":
                self.expired += 1
            elif status == "error":
                self.errors += 1
            elif status == "shutting_down":
                self.shut_down += 1
            if total_s is not None and len(self._total_s) < self._keep:
                self._total_s.append(total_s)
            if queue_s is not None and len(self._queue_s) < self._keep:
                self._queue_s.append(queue_s)
        fields = {"ev": "result", "rid": rid, "status": status}
        if bucket is not None:
            fields["bucket"] = list(bucket)
        if queue_s is not None:
            fields["queue_s"] = queue_s
        if total_s is not None:
            # gang scheduling: the first token surfaces with the whole batch
            fields["ttft_s"] = total_s
            fields["total_s"] = total_s
        self._emit(**fields)

    def snapshot(self) -> dict:
        """One aggregate dict: counters plus occupancy mean, tokens/s over
        engine busy time, and p50/p99 total latency (None until data)."""
        with self._lock:
            lat = list(self._total_s)
            qs = list(self._queue_s)
            out = {
                "submitted": self.submitted, "rejected": self.rejected,
                "expired": self.expired, "completed": self.completed,
                "errors": self.errors, "shut_down": self.shut_down,
                "batches": self.batches, "new_tokens": self.new_tokens,
                "busy_s": round(self.busy_s, 6),
                "occupancy_mean": (round(self._occupancy_sum / self.batches, 4)
                                   if self.batches else None),
                "tok_s": (round(self.new_tokens / self.busy_s, 2)
                          if self.busy_s > 0 else None),
            }
        out["p50_total_s"] = percentile(lat, 50) if lat else None
        out["p99_total_s"] = percentile(lat, 99) if lat else None
        out["p50_queue_s"] = percentile(qs, 50) if qs else None
        return out
