"""Serving observability: counters + per-request latencies through EventLog
and the process metrics registry.

Every record goes to the engine's :class:`~marlin_tpu.utils.tracing.EventLog`
(or the process default, resolved per emit so a log installed mid-run is
picked up) under the single kind ``"serve"`` with an ``ev`` discriminator:

=============  ===========================================================
``ev``         fields
=============  ===========================================================
``enqueue``    ``rid``, ``bucket``, ``depth`` (queue depth after admit)
``reject``     ``rid``, ``reason``
``prefill``    row-level scheduling, one per slot prefill: ``rid``,
               ``bucket``, ``new_tokens`` (1 — the row's first token lands
               here), ``seconds`` (prefill wall time)
``batch``      gang scheduling, one per dispatched batch: ``bucket``,
               ``rows`` (live), ``occupancy`` (live/max_batch),
               ``new_tokens``, ``seconds`` (wall), ``tok_s``
``step``       row-level scheduling, one per DECODE STEP over the slab:
               ``bucket``, ``rows`` (live this step), ``occupancy``,
               ``new_tokens`` (= live rows), ``seconds`` (wall decode-step
               latency), ``tok_s`` — the per-step occupancy stream is how
               slot refill is asserted (a finished row's slot shows
               occupied again on the next step's record)
``retry``      ``rid``, ``attempt`` (the attempt about to run),
               ``max_attempts``, ``reason`` — one failed attempt re-queued
``result``     ``rid``, ``status``, ``bucket``, ``queue_s``, ``ttft_s``,
               ``total_s``; retried requests add ``attempt`` (the final,
               serving attempt — latency is attributed to it)
=============  ===========================================================

The engine activates each request's span context around the rid-carrying
emits, so one request's ``enqueue``/``prefill``/``result`` records share a
``trace_id`` in the JSONL (obs/trace.py; the analyzer joins them).

In parallel, everything aggregates into the process registry
(:mod:`marlin_tpu.obs.metrics`) so a ``/metrics`` scrape sees live serving
state: ``marlin_serve_submitted_total``,
``marlin_serve_requests_total{status=...}``, ``marlin_serve_tokens_total``,
``marlin_serve_dispatches_total{kind=batch|step|prefill}``,
``marlin_serve_busy_seconds_total``, gauges ``marlin_serve_queue_depth`` /
``marlin_serve_slot_occupancy`` / ``marlin_serve_kv_inflight_bytes``, and
histograms ``marlin_serve_ttft_seconds`` / ``marlin_serve_total_seconds`` /
``marlin_serve_step_seconds``.

Latencies are measured on the engine's *injected* clock (deterministic
tests), throughput (``tok_s``) on the real wall clock (it is a measurement,
not a policy input). Under gang scheduling a row's first token becomes
visible only when its batch's whole generation program returns, so
``ttft_s`` equals ``total_s`` there; under row-level scheduling the first
token lands with the slot's prefill, so ``ttft_s`` is genuinely earlier —
the headline latency the row-level split buys (docs/serving.md).

:meth:`ServeMetrics.snapshot` aggregates everything for tests and the bench
(`bench_all.py serve`) without re-reading the log file. Its percentiles run
over *uniform reservoir samples* (:class:`Reservoir`, Algorithm R with an
injectable RNG) — the previous first-``keep_latencies``-then-drop scheme
silently stopped sampling after warmup, biasing every long-run percentile
toward the coldest requests the engine ever served.
"""

from __future__ import annotations

import random
import threading

from ..obs.metrics import get_registry, percentile  # noqa: F401  (re-export)
from ..obs.perf import get_program_costs
from ..utils.tracing import get_default_event_log

__all__ = ["ServeMetrics", "Reservoir", "percentile"]


class Reservoir:
    """Uniform reservoir sampling (Algorithm R): after ``n`` adds, each of
    the ``n`` values had probability ``k/n`` of being retained — percentiles
    over the sample estimate the whole stream, not its first ``k`` entries.
    The RNG is injectable (tests pin it; callers share one across
    reservoirs). NOT thread-safe on its own — :class:`ServeMetrics` adds
    under its lock."""

    __slots__ = ("k", "n", "items", "_rng")

    def __init__(self, k: int, rng: random.Random):
        self.k = int(k)
        self.n = 0
        self.items: list[float] = []
        self._rng = rng

    def add(self, value: float) -> None:
        self.n += 1
        if len(self.items) < self.k:
            self.items.append(value)
        else:
            j = self._rng.randrange(self.n)
            if j < self.k:
                self.items[j] = value

    def values(self) -> list[float]:
        return list(self.items)


class ServeMetrics:
    """Thread-safe counter/latency sink for one engine. All record_* methods
    are called by the engine (submit path + worker thread) — never raise out
    of them into the serving path."""

    def __init__(self, log=None, keep_latencies: int = 4096, rng=None):
        self._log = log
        self._lock = threading.Lock()
        rng = rng if rng is not None else random.Random(0)
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.completed = 0
        self.errors = 0
        self.shut_down = 0
        self.retries = 0
        self.batches = 0
        self.steps = 0
        self.new_tokens = 0
        self.busy_s = 0.0
        self._occupancy_sum = 0.0
        self._step_occupancy_sum = 0.0
        self._total_s = Reservoir(keep_latencies, rng)
        self._queue_s = Reservoir(keep_latencies, rng)
        self._ttft_s = Reservoir(keep_latencies, rng)
        self._step_s = Reservoir(keep_latencies, rng)
        reg = get_registry()
        self._m_submitted = reg.counter(
            "marlin_serve_submitted_total", "Requests admitted by submit()")
        self._m_requests = reg.counter(
            "marlin_serve_requests_total",
            "Terminal request outcomes by status",
            labelnames=("status",))
        self._m_tokens = reg.counter(
            "marlin_serve_tokens_total", "Generated tokens (all requests)")
        self._m_dispatch = reg.counter(
            "marlin_serve_dispatches_total",
            "Engine dispatches by kind (gang batch / row-level decode step "
            "/ slot prefill)", labelnames=("kind",))
        self._m_busy = reg.counter(
            "marlin_serve_busy_seconds_total",
            "Wall seconds the engine spent inside compiled programs")
        self._m_queue_depth = reg.gauge(
            "marlin_serve_queue_depth",
            "Requests admitted but not yet retired (queued + in flight)")
        self._m_occupancy = reg.gauge(
            "marlin_serve_slot_occupancy",
            "Live rows / max_batch of the most recent dispatch")
        self._m_kv_bytes = reg.gauge(
            "marlin_serve_kv_inflight_bytes",
            "Admitted-but-unretired KV-cache bytes against the planner's "
            "HBM budget")
        self._m_ttft = reg.histogram(
            "marlin_serve_ttft_seconds", "Time to first generated token")
        self._m_total = reg.histogram(
            "marlin_serve_total_seconds", "Submit-to-result latency")
        self._m_step = reg.histogram(
            "marlin_serve_step_seconds", "Row-level decode-step wall time")
        self._m_retries = reg.counter(
            "marlin_serve_retries_total",
            "Failed attempts transparently re-queued (decode/prefill fault "
            "or worker crash) within the request's max_attempts budget")

    def _emit(self, **fields) -> None:
        log = self._log or get_default_event_log()
        if log is not None:
            log.event("serve", **fields)

    def record_queue(self, depth: int, kv_bytes: int) -> None:
        """Live admission-gate state (the engine calls this on every admit
        and retirement) — gauges only, no EventLog record."""
        self._m_queue_depth.set(depth)
        self._m_kv_bytes.set(kv_bytes)

    def record_enqueue(self, rid: int, bucket, depth: int) -> None:
        with self._lock:
            self.submitted += 1
        self._m_submitted.inc()
        # queue-depth gauge: record_queue is the single writer (the engine
        # calls it right after, with the admission gate's own count)
        self._emit(ev="enqueue", rid=rid, bucket=list(bucket), depth=depth)

    def record_reject(self, rid: int, reason: str) -> None:
        with self._lock:
            self.rejected += 1
        self._m_requests.labels(status="rejected").inc()
        self._emit(ev="reject", rid=rid, reason=reason)

    def record_batch(self, bucket, rows: int, max_batch: int,
                     new_tokens: int, seconds: float,
                     program_key: str | None = None) -> None:
        with self._lock:
            self.batches += 1
            self.new_tokens += new_tokens
            self.busy_s += seconds
            self._occupancy_sum += rows / max_batch
        if program_key is not None:
            get_program_costs().observe("lm_generate_batch", program_key,
                                        seconds)
        self._m_dispatch.labels(kind="batch").inc()
        self._m_tokens.inc(new_tokens)
        self._m_busy.inc(seconds)
        self._m_occupancy.set(rows / max_batch)
        self._emit(ev="batch", bucket=list(bucket), rows=rows,
                   occupancy=round(rows / max_batch, 4),
                   new_tokens=new_tokens, seconds=seconds,
                   tok_s=round(new_tokens / max(seconds, 1e-9), 2))

    def record_prefill(self, bucket, seconds: float,
                       rid: int | None = None,
                       program_key: str | None = None) -> None:
        """One row-level slot prefill: the row's FIRST token is emitted here
        (real TTFT), so it counts toward ``new_tokens``/``busy_s`` — without
        this, steps=1 traffic would report zero tokens and every request
        would be undercounted by one versus the gang accounting.
        ``program_key`` joins the wall time onto the bucket's captured XLA
        cost model (obs/perf.py) — the roofline side of the same record."""
        with self._lock:
            self.new_tokens += 1
            self.busy_s += seconds
        if program_key is not None:
            get_program_costs().observe("lm_prefill_slot", program_key,
                                        seconds)
        self._m_dispatch.labels(kind="prefill").inc()
        self._m_tokens.inc()
        self._m_busy.inc(seconds)
        fields = {"ev": "prefill", "bucket": list(bucket), "new_tokens": 1,
                  "seconds": seconds}
        if rid is not None:
            fields["rid"] = rid
        self._emit(**fields)

    def record_step(self, bucket, rows: int, max_batch: int,
                    seconds: float,
                    program_key: str | None = None) -> None:
        """One row-level decode step over a bucket's slab: ``rows`` live
        slots each emitted one token (``new_tokens`` == ``rows``).
        ``program_key`` joins the step's wall time onto the decode
        program's cost model, feeding ``marlin_program_roofline_frac``."""
        with self._lock:
            self.steps += 1
            self.new_tokens += rows
            self.busy_s += seconds
            self._step_occupancy_sum += rows / max_batch
            self._step_s.add(seconds)
        if program_key is not None:
            get_program_costs().observe("lm_decode_rows", program_key,
                                        seconds)
        self._m_dispatch.labels(kind="step").inc()
        self._m_tokens.inc(rows)
        self._m_busy.inc(seconds)
        self._m_occupancy.set(rows / max_batch)
        self._m_step.observe(seconds)
        self._emit(ev="step", bucket=list(bucket), rows=rows,
                   occupancy=round(rows / max_batch, 4), new_tokens=rows,
                   seconds=seconds,
                   tok_s=round(rows / max(seconds, 1e-9), 2))

    def record_retry(self, rid: int, attempt: int, max_attempts: int,
                     reason: str) -> None:
        """One failed attempt re-queued for another try. The request stays
        admitted (no terminal counter moves); latency/TTFT land only with
        the final attempt's result — a retried request is attributed to the
        attempt that actually served it."""
        with self._lock:
            self.retries += 1
        self._m_retries.inc()
        self._emit(ev="retry", rid=rid, attempt=attempt,
                   max_attempts=max_attempts, reason=reason)

    def record_result(self, rid: int, status: str, bucket=None,
                      queue_s: float | None = None,
                      total_s: float | None = None,
                      ttft_s: float | None = None,
                      attempt: int = 1) -> None:
        with self._lock:
            if status == "ok":
                self.completed += 1
            elif status == "expired":
                self.expired += 1
            elif status == "error":
                self.errors += 1
            elif status == "shutting_down":
                self.shut_down += 1
            if total_s is not None:
                self._total_s.add(total_s)
            if queue_s is not None:
                self._queue_s.add(queue_s)
            # ttft falls back to total_s ONLY for completed gang results
            # (their first token really does surface with the whole batch);
            # expired/error requests never produced a token, and counting
            # their wait as time-to-first-token would corrupt the headline
            # percentile the row-level A/B measures
            if ttft_s is None and status == "ok":
                ttft_s = total_s
            if ttft_s is not None:
                self._ttft_s.add(ttft_s)
        self._m_requests.labels(status=status).inc()
        if total_s is not None:
            self._m_total.observe(total_s)
        if ttft_s is not None:
            self._m_ttft.observe(ttft_s)
        fields = {"ev": "result", "rid": rid, "status": status}
        if attempt > 1:
            fields["attempt"] = attempt
        if bucket is not None:
            fields["bucket"] = list(bucket)
        if queue_s is not None:
            fields["queue_s"] = queue_s
        if ttft_s is not None:
            fields["ttft_s"] = ttft_s
        if total_s is not None:
            fields["total_s"] = total_s
        self._emit(**fields)

    def snapshot(self) -> dict:
        """One aggregate dict: counters plus occupancy mean (over gang
        batches and row-level decode steps alike), tokens/s over engine busy
        time, and p50/p99 total / ttft latency (None until data; percentiles
        over the uniform reservoirs)."""
        with self._lock:
            lat = self._total_s.values()
            qs = self._queue_s.values()
            tt = self._ttft_s.values()
            ss = self._step_s.values()
            dispatches = self.batches + self.steps
            occ = self._occupancy_sum + self._step_occupancy_sum
            out = {
                "submitted": self.submitted, "rejected": self.rejected,
                "expired": self.expired, "completed": self.completed,
                "errors": self.errors, "shut_down": self.shut_down,
                "retries": self.retries,
                "batches": self.batches, "steps": self.steps,
                "new_tokens": self.new_tokens,
                "busy_s": round(self.busy_s, 6),
                "occupancy_mean": (round(occ / dispatches, 4)
                                   if dispatches else None),
                "tok_s": (round(self.new_tokens / self.busy_s, 2)
                          if self.busy_s > 0 else None),
            }
        out["p50_total_s"] = percentile(lat, 50) if lat else None
        out["p99_total_s"] = percentile(lat, 99) if lat else None
        out["p50_queue_s"] = percentile(qs, 50) if qs else None
        out["p50_ttft_s"] = percentile(tt, 50) if tt else None
        out["p99_ttft_s"] = percentile(tt, 99) if tt else None
        out["p50_step_s"] = percentile(ss, 50) if ss else None
        return out
