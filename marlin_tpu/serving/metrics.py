"""Serving observability: counters + per-request latencies through EventLog
and the process metrics registry.

Every record goes to the engine's :class:`~marlin_tpu.utils.tracing.EventLog`
(or the process default, resolved per emit so a log installed mid-run is
picked up) under the single kind ``"serve"`` with an ``ev`` discriminator:

=============  ===========================================================
``ev``         fields
=============  ===========================================================
``enqueue``    ``rid``, ``bucket``, ``depth`` (queue depth after admit)
``reject``     ``rid``, ``reason``
``prefill``    one per prefill dispatch: ``rid``, ``bucket``, ``seconds``
               (wall time), ``new_tokens`` (1 on the completing dispatch —
               the row's first token lands there — else 0); paged chunked
               prefill additionally carries ``chunk`` = [start, tokens]
               (a long prompt emits one record per chunk, resumable across
               worker iterations)
``step``       one per DECODE STEP over a bucket's rows: ``bucket``,
               ``rows`` (live this step), ``occupancy``, ``new_tokens``
               (= live rows), ``seconds`` (wall decode-step latency),
               ``tok_s`` — the per-step occupancy stream is how slot
               refill is asserted (a finished row's slot shows occupied
               again on the next step's record)
``page``       paged KV pool accounting: ``action`` (``alloc`` at row
               admission / ``free`` at retirement / ``cow`` on a
               copy-on-write split / ``lost`` when a failed donated call
               consumed the slab), ``rid``, ``pages`` (moved by this
               action), ``shared`` (of them, prefix-cache shares), and
               the pool ``used``/``total`` after it — the stream
               ``obs.report`` turns into the prefix-hit-rate /
               page-occupancy line
``retry``      ``rid``, ``attempt`` (the attempt about to run),
               ``max_attempts``, ``reason`` — one failed attempt re-queued
``result``     ``rid``, ``status``, ``bucket``, ``queue_s``, ``ttft_s``,
               ``total_s``; retried requests add ``attempt`` (the final,
               serving attempt — latency is attributed to it); paged rows
               add ``pages``/``shared_pages``
``swap``       one atomic model hot-update on a resident BucketProgram:
               ``program``
=============  ===========================================================

Non-LM BucketProgram traffic (serving/programs/) threads a ``program``
field through its ``enqueue``/``step``/``reject``/``result`` records (LM
records stay byte-identical — readers default a missing field to ``lm``),
and aggregates into three labelled families:
``marlin_serve_program_requests_total{program,status}`` (terminal outcomes
per serving program), ``marlin_serve_program_rows_total{program}`` (rows
executed by one-shot program steps), and
``marlin_serve_program_swaps_total{program}`` (atomic model hot-updates).

The engine activates each request's span context around the rid-carrying
emits, so one request's ``enqueue``/``prefill``/``result`` records share a
``trace_id`` in the JSONL (obs/trace.py; the analyzer joins them).

In parallel, everything aggregates into the process registry
(:mod:`marlin_tpu.obs.metrics`) so a ``/metrics`` scrape sees live serving
state: ``marlin_serve_submitted_total``,
``marlin_serve_requests_total{status=...}``, ``marlin_serve_tokens_total``,
``marlin_serve_dispatches_total{kind=step|prefill}``,
``marlin_serve_busy_seconds_total``, gauges ``marlin_serve_queue_depth`` /
``marlin_serve_slot_occupancy`` / ``marlin_serve_kv_inflight_bytes`` /
``marlin_serve_kv_pages_total`` / ``marlin_serve_kv_pages_used`` /
``marlin_serve_kv_pages_shared`` (paged pool state), the
``marlin_serve_prefix_cache_total{result=hit|miss}`` counter, and
histograms ``marlin_serve_ttft_seconds`` / ``marlin_serve_total_seconds`` /
``marlin_serve_step_seconds``.

Latencies are measured on the engine's *injected* clock (deterministic
tests), throughput (``tok_s``) on the real wall clock (it is a measurement,
not a policy input). The first token lands with the row's (final) prefill
dispatch, so ``ttft_s`` is genuinely earlier than ``total_s`` — the
headline latency row-level scheduling buys, and what paged chunked prefill
bounds under long-prompt load (docs/serving.md).

:meth:`ServeMetrics.snapshot` aggregates everything for tests and the bench
(`bench_all.py serve`) without re-reading the log file. Its percentiles run
over *uniform reservoir samples* (:class:`Reservoir`, Algorithm R with an
injectable RNG) — the previous first-``keep_latencies``-then-drop scheme
silently stopped sampling after warmup, biasing every long-run percentile
toward the coldest requests the engine ever served.
"""

from __future__ import annotations

import random
import threading

from ..obs.metrics import get_registry, percentile  # noqa: F401  (re-export)
from ..obs.perf import get_program_costs
from ..utils.tracing import get_default_event_log

__all__ = ["ServeMetrics", "Reservoir", "percentile"]


class Reservoir:
    """Uniform reservoir sampling (Algorithm R): after ``n`` adds, each of
    the ``n`` values had probability ``k/n`` of being retained — percentiles
    over the sample estimate the whole stream, not its first ``k`` entries.
    The RNG is injectable (tests pin it; callers share one across
    reservoirs). NOT thread-safe on its own — :class:`ServeMetrics` adds
    under its lock."""

    __slots__ = ("k", "n", "items", "_rng")

    def __init__(self, k: int, rng: random.Random):
        self.k = int(k)
        self.n = 0
        self.items: list[float] = []
        self._rng = rng

    def add(self, value: float) -> None:
        self.n += 1
        if len(self.items) < self.k:
            self.items.append(value)
        else:
            j = self._rng.randrange(self.n)
            if j < self.k:
                self.items[j] = value

    def values(self) -> list[float]:
        return list(self.items)


class ServeMetrics:
    """Thread-safe counter/latency sink for one engine. All record_* methods
    are called by the engine (submit path + worker thread) — never raise out
    of them into the serving path."""

    def __init__(self, log=None, keep_latencies: int = 4096, rng=None):
        self._log = log
        self._lock = threading.Lock()
        rng = rng if rng is not None else random.Random(0)
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.completed = 0
        self.errors = 0
        self.shut_down = 0
        self.retries = 0
        self.batches = 0  # legacy (gang scheduler, retired PR 8): always 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.pages_total = 0
        self.pages_used = 0
        self.pages_shared = 0
        self.steps = 0
        self.new_tokens = 0
        self.busy_s = 0.0
        self.migrated_out = 0   # rows exported into a migration blob
        self.migrated_in = 0    # rows adopted mid-stream from a peer
        self.migrate_fallback = 0  # rows that fell back to the retry path
        self.program_steps = 0  # one-shot program batch dispatches
        self.program_rows = 0   # rows those dispatches served
        self.swaps = 0          # atomic model hot-updates (record_swap)
        self._occupancy_sum = 0.0
        self._step_occupancy_sum = 0.0
        self._total_s = Reservoir(keep_latencies, rng)
        self._queue_s = Reservoir(keep_latencies, rng)
        self._ttft_s = Reservoir(keep_latencies, rng)
        self._step_s = Reservoir(keep_latencies, rng)
        self._ts = None  # optional TimeSeriesStore (attach_timeseries)
        reg = get_registry()
        self._m_submitted = reg.counter(
            "marlin_serve_submitted_total", "Requests admitted by submit()")
        self._m_requests = reg.counter(
            "marlin_serve_requests_total",
            "Terminal request outcomes by status",
            labelnames=("status",))
        self._m_tokens = reg.counter(
            "marlin_serve_tokens_total", "Generated tokens (all requests)")
        self._m_dispatch = reg.counter(
            "marlin_serve_dispatches_total",
            "Engine dispatches by kind (decode step / prefill — one "
            "prefill dispatch per chunk under paged chunked prefill)",
            labelnames=("kind",))
        self._m_busy = reg.counter(
            "marlin_serve_busy_seconds_total",
            "Wall seconds the engine spent inside compiled programs")
        self._m_queue_depth = reg.gauge(
            "marlin_serve_queue_depth",
            "Requests admitted but not yet retired (queued + in flight)")
        self._m_occupancy = reg.gauge(
            "marlin_serve_slot_occupancy",
            "Live rows / max_batch of the most recent dispatch")
        self._m_kv_bytes = reg.gauge(
            "marlin_serve_kv_inflight_bytes",
            "Admitted-but-unretired KV-cache bytes against the planner's "
            "HBM budget")
        self._m_ttft = reg.histogram(
            "marlin_serve_ttft_seconds", "Time to first generated token")
        self._m_total = reg.histogram(
            "marlin_serve_total_seconds", "Submit-to-result latency")
        self._m_step = reg.histogram(
            "marlin_serve_step_seconds", "Row-level decode-step wall time")
        self._m_retries = reg.counter(
            "marlin_serve_retries_total",
            "Failed attempts transparently re-queued (decode/prefill fault "
            "or worker crash) within the request's max_attempts budget")
        self._m_pages_total = reg.gauge(
            "marlin_serve_kv_pages_total",
            "Allocatable pages in the paged KV pool (serve_num_pages minus "
            "the dummy page)")
        self._m_pages_used = reg.gauge(
            "marlin_serve_kv_pages_used",
            "Pages held by live rows and/or the prefix cache")
        self._m_pages_shared = reg.gauge(
            "marlin_serve_kv_pages_shared",
            "Pages with more than one referent (copy-on-write prefix "
            "sharing: cache + row, or row + row)")
        self._m_prefix = reg.counter(
            "marlin_serve_prefix_cache_total",
            "Prefix-cache lookups at row admission by result (hit = at "
            "least one full prompt page reused)", labelnames=("result",))
        self._m_prog_requests = reg.counter(
            "marlin_serve_program_requests_total",
            "Terminal request outcomes by serving program (BucketProgram "
            "name: lm, als, pagerank, classify, ...) and status",
            labelnames=("program", "status"))
        self._m_prog_rows = reg.counter(
            "marlin_serve_program_rows_total",
            "Rows executed by one-shot (non-LM) BucketProgram step "
            "dispatches, by program",
            labelnames=("program",))
        self._m_prog_swaps = reg.counter(
            "marlin_serve_program_swaps_total",
            "Atomic model hot-updates (swap_model) on resident "
            "BucketPrograms, by program",
            labelnames=("program",))
        self._m_migrate = reg.counter(
            "marlin_serve_migrations_total",
            "Cross-replica row migrations by leg (export = rows serialized "
            "off a frozen engine, adopt = rows resumed mid-stream on this "
            "engine, fallback = rows degraded to the retry path)",
            labelnames=("leg",))

    def attach_timeseries(self, store) -> None:
        """Feed raw latency samples into a
        :class:`~marlin_tpu.obs.timeseries.TimeSeriesStore` so windowed
        percentiles (the SLO engine's ``p99:...`` objectives) see every
        observation, not just the cumulative histogram the registry pump
        carries. Series are named after the histogram families
        (``marlin_serve_ttft_seconds`` etc. — the pump's derived cum
        series use ``_count``/``_sum`` suffixes, so the names never
        collide). Pass ``None`` to detach."""
        with self._lock:
            self._ts = store

    def _ts_observe(self, name: str, value: float) -> None:
        ts = getattr(self, "_ts", None)
        if ts is not None:
            try:
                ts.observe(name, value)
            except Exception:
                pass  # observability stays passive on the serving path

    def _emit(self, **fields) -> None:
        log = self._log or get_default_event_log()
        if log is not None:
            log.event("serve", **fields)

    def record_queue(self, depth: int, kv_bytes: int) -> None:
        """Live admission-gate state (the engine calls this on every admit
        and retirement) — gauges only, no EventLog record."""
        self._m_queue_depth.set(depth)
        self._m_kv_bytes.set(kv_bytes)

    def record_enqueue(self, rid: int, bucket, depth: int,
                       program: str | None = None) -> None:
        with self._lock:
            self.submitted += 1
        self._m_submitted.inc()
        # queue-depth gauge: record_queue is the single writer (the engine
        # calls it right after, with the admission gate's own count)
        fields = {"ev": "enqueue", "rid": rid, "bucket": list(bucket),
                  "depth": depth}
        if program is not None and program != "lm":
            fields["program"] = program
        self._emit(**fields)

    def record_reject(self, rid: int, reason: str,
                      program: str | None = None) -> None:
        with self._lock:
            self.rejected += 1
        self._m_requests.labels(status="rejected").inc()
        self._m_prog_requests.labels(program=program or "lm",
                                     status="rejected").inc()
        fields = {"ev": "reject", "rid": rid, "reason": reason}
        if program is not None and program != "lm":
            fields["program"] = program
        self._emit(**fields)

    def record_prefill(self, bucket, seconds: float,
                       rid: int | None = None,
                       program_key: str | None = None,
                       program: str = "lm_prefill_slot",
                       chunk=None, final: bool = True) -> None:
        """One prefill dispatch. The row's FIRST token is emitted by the
        COMPLETING dispatch (real TTFT), so that one counts toward
        ``new_tokens`` — without it, steps=1 traffic would report zero
        tokens; paged chunked prefill additionally records one
        zero-new-token event per earlier chunk (``chunk`` = [start,
        tokens], ``final=False``). ``program_key`` joins the wall time onto
        the bucket's captured XLA cost model for ``program`` (obs/perf.py)
        — the roofline side of the same record."""
        emitted = 1 if final else 0
        with self._lock:
            self.new_tokens += emitted
            self.busy_s += seconds
        if program_key is not None:
            get_program_costs().observe(program, program_key, seconds)
        self._m_dispatch.labels(kind="prefill").inc()
        if emitted:
            self._m_tokens.inc()
        self._m_busy.inc(seconds)
        fields = {"ev": "prefill", "bucket": list(bucket),
                  "new_tokens": emitted, "seconds": seconds}
        if chunk is not None:
            fields["chunk"] = list(chunk)
        if rid is not None:
            fields["rid"] = rid
        self._emit(**fields)

    def record_step(self, bucket, rows: int, max_batch: int,
                    seconds: float,
                    program_key: str | None = None,
                    program: str = "lm_decode_rows",
                    label: str | None = None) -> None:
        """One decode step over a bucket's rows: ``rows`` live slots each
        emitted one token (``new_tokens`` == ``rows``). ``program_key``
        joins the step's wall time onto ``program``'s cost model, feeding
        ``marlin_program_roofline_frac``. ``label`` marks a non-LM
        BucketProgram batch (the serving-program name, distinct from
        ``program`` — the ProgramCosts family): its rows are program rows,
        not generated tokens, so they count into
        ``marlin_serve_program_rows_total{program}`` instead of the token
        counters and never touch LM's tok/s arithmetic."""
        with self._lock:
            self.steps += 1
            self.busy_s += seconds
            self._step_occupancy_sum += rows / max_batch
            self._step_s.add(seconds)
            if label is None:
                self.new_tokens += rows
            else:
                self.program_steps += 1
                self.program_rows += rows
        if program_key is not None:
            get_program_costs().observe(program, program_key, seconds)
        self._m_dispatch.labels(kind="step").inc()
        self._m_busy.inc(seconds)
        self._m_occupancy.set(rows / max_batch)
        self._m_step.observe(seconds)
        self._ts_observe("marlin_serve_step_seconds", seconds)
        fields = {"ev": "step", "bucket": list(bucket), "rows": rows,
                  "occupancy": round(rows / max_batch, 4),
                  "seconds": seconds}
        if label is None:
            self._m_tokens.inc(rows)
            fields["new_tokens"] = rows
            fields["tok_s"] = round(rows / max(seconds, 1e-9), 2)
        else:
            self._m_prog_rows.labels(program=label).inc(rows)
            fields["new_tokens"] = 0
            fields["program"] = label
        self._emit(**fields)

    def record_swap(self, program: str) -> None:
        """One atomic model hot-update (``swap_model``) installed on a
        resident BucketProgram."""
        with self._lock:
            self.swaps += 1
        self._m_prog_swaps.labels(program=program).inc()
        self._emit(ev="swap", program=program)

    def record_retry(self, rid: int, attempt: int, max_attempts: int,
                     reason: str) -> None:
        """One failed attempt re-queued for another try. The request stays
        admitted (no terminal counter moves); latency/TTFT land only with
        the final attempt's result — a retried request is attributed to the
        attempt that actually served it."""
        with self._lock:
            self.retries += 1
        self._m_retries.inc()
        self._emit(ev="retry", rid=rid, attempt=attempt,
                   max_attempts=max_attempts, reason=reason)

    def record_migration(self, leg: str, rows: int) -> None:
        """One cross-replica migration leg over ``rows`` rows: ``export``
        (frozen rows serialized off this engine), ``adopt`` (rows resumed
        mid-stream here), or ``fallback`` (rows degraded to the retry
        path). Counter + one ``ev="migrate"`` EventLog record."""
        if rows <= 0:
            return
        with self._lock:
            if leg == "export":
                self.migrated_out += rows
            elif leg == "adopt":
                self.migrated_in += rows
            elif leg == "fallback":
                self.migrate_fallback += rows
        self._m_migrate.labels(leg=leg).inc(rows)
        self._emit(ev="migrate", leg=leg, rows=rows)

    def record_pages(self, total: int, used: int, shared: int) -> None:
        """Live paged-pool state (the engine calls this after admissions,
        retirements, and pool drops) — gauges only, no EventLog record."""
        with self._lock:
            self.pages_total = total
            self.pages_used = used
            self.pages_shared = shared
        self._m_pages_total.set(total)
        self._m_pages_used.set(used)
        self._m_pages_shared.set(shared)

    def record_prefix(self, hit: bool) -> None:
        """One prefix-cache lookup at row admission (hit = at least one
        full prompt page reused instead of re-prefilled)."""
        with self._lock:
            if hit:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1
        self._m_prefix.labels(result="hit" if hit else "miss").inc()

    def record_page_event(self, action: str, rid: int | None = None,
                          pages: int | None = None,
                          shared: int | None = None,
                          used: int | None = None,
                          total: int | None = None) -> None:
        """One ``ev="page"`` EventLog record (see the module table); the
        stream obs.report aggregates into the paging line."""
        fields = {"ev": "page", "action": action}
        for name, v in (("rid", rid), ("pages", pages), ("shared", shared),
                        ("used", used), ("total", total)):
            if v is not None:
                fields[name] = v
        self._emit(**fields)

    def record_result(self, rid: int, status: str, bucket=None,
                      queue_s: float | None = None,
                      total_s: float | None = None,
                      ttft_s: float | None = None,
                      attempt: int = 1,
                      pages: int | None = None,
                      shared_pages: int | None = None,
                      program: str | None = None) -> None:
        with self._lock:
            if status == "ok":
                self.completed += 1
            elif status == "expired":
                self.expired += 1
            elif status == "error":
                self.errors += 1
            elif status == "shutting_down":
                self.shut_down += 1
            if total_s is not None:
                self._total_s.add(total_s)
            if queue_s is not None:
                self._queue_s.add(queue_s)
            # ttft falls back to total_s ONLY for completed results with no
            # measured first-token time (legacy streams; every current
            # scheduler stamps ttft at the final prefill dispatch);
            # expired/error requests never produced a token, and counting
            # their wait as time-to-first-token would corrupt the headline
            # percentile the serving A/Bs measure
            if ttft_s is None and status == "ok":
                ttft_s = total_s
            if ttft_s is not None:
                self._ttft_s.add(ttft_s)
        self._m_requests.labels(status=status).inc()
        self._m_prog_requests.labels(program=program or "lm",
                                     status=status).inc()
        if total_s is not None:
            self._m_total.observe(total_s)
            self._ts_observe("marlin_serve_total_seconds", total_s)
        if ttft_s is not None:
            self._m_ttft.observe(ttft_s)
            self._ts_observe("marlin_serve_ttft_seconds", ttft_s)
        if queue_s is not None:
            self._ts_observe("marlin_serve_queue_seconds", queue_s)
        fields = {"ev": "result", "rid": rid, "status": status}
        if program is not None and program != "lm":
            fields["program"] = program
        if attempt > 1:
            fields["attempt"] = attempt
        if bucket is not None:
            fields["bucket"] = list(bucket)
        if queue_s is not None:
            fields["queue_s"] = queue_s
        if ttft_s is not None:
            fields["ttft_s"] = ttft_s
        if total_s is not None:
            fields["total_s"] = total_s
        if pages is not None:
            fields["pages"] = pages
        if shared_pages is not None:
            fields["shared_pages"] = shared_pages
        self._emit(**fields)

    def snapshot(self) -> dict:
        """One aggregate dict: counters (paging hit/page fields included)
        plus decode-step occupancy mean, tokens/s over engine busy time,
        and p50/p99 total / ttft latency (None until data; percentiles
        over the uniform reservoirs)."""
        with self._lock:
            lat = self._total_s.values()
            qs = self._queue_s.values()
            tt = self._ttft_s.values()
            ss = self._step_s.values()
            dispatches = self.batches + self.steps
            occ = self._occupancy_sum + self._step_occupancy_sum
            out = {
                "submitted": self.submitted, "rejected": self.rejected,
                "expired": self.expired, "completed": self.completed,
                "errors": self.errors, "shut_down": self.shut_down,
                "retries": self.retries,
                "batches": self.batches, "steps": self.steps,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "pages_total": self.pages_total,
                "pages_used": self.pages_used,
                "pages_shared": self.pages_shared,
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "migrate_fallback": self.migrate_fallback,
                "program_steps": self.program_steps,
                "program_rows": self.program_rows,
                "swaps": self.swaps,
                "new_tokens": self.new_tokens,
                "busy_s": round(self.busy_s, 6),
                "occupancy_mean": (round(occ / dispatches, 4)
                                   if dispatches else None),
                "tok_s": (round(self.new_tokens / self.busy_s, 2)
                          if self.busy_s > 0 else None),
            }
        out["p50_total_s"] = percentile(lat, 50) if lat else None
        out["p99_total_s"] = percentile(lat, 99) if lat else None
        out["p50_queue_s"] = percentile(qs, 50) if qs else None
        out["p50_ttft_s"] = percentile(tt, 50) if tt else None
        out["p99_ttft_s"] = percentile(tt, 99) if tt else None
        out["p50_step_s"] = percentile(ss, 50) if ss else None
        return out
