"""Shape bucketing and dynamic batch formation for the serving engine.

``_lm_generate_batch_jit`` compiles one XLA program per *shape* — batch B,
padded prompt P, decode steps S are all baked into the executable. Serving
traffic is ragged, so without discipline every new (B, P, S) triple pays a
fresh multi-second compile. The discipline here:

- **Buckets** — a small static set of ``(P_bucket, steps_bucket)`` pairs. A
  request pads its prompt up to the smallest fitting ``P_bucket`` and rounds
  its steps up to that bucket's ``steps_bucket`` (the result is sliced back
  to the requested length).
- **Fixed batch width** — every dispatched batch is padded to exactly
  ``max_batch`` rows (free rows carry an inert 1-token dummy prompt), so B
  never varies and the compile count is bounded by the bucket count, not the
  traffic pattern.
- **Dynamic forming** — :class:`BatchFormer` groups admitted requests by
  (bucket, sampling knobs) and closes a group's batch when it reaches
  ``max_batch`` rows or its oldest request has waited ``max_wait`` seconds,
  whichever first. The clock is injectable, so tests drive the wait logic
  deterministically.
- **Warmup** — :func:`warmup_buckets` runs one dummy full-width batch per
  bucket so the per-bucket compile happens before traffic (the engine
  exposes it as ``ServeEngine.warmup()``); :func:`aot_compile_buckets`
  compiles the same programs against a compile-only TPU topology
  (:mod:`marlin_tpu.utils.aot` — no chip needed) and returns the compiler's
  per-bucket peak-HBM accounting, the offline sizing channel for
  ``serve_buckets`` / ``serve_max_batch``.
"""

from __future__ import annotations

import collections
from typing import Iterable, Sequence

import numpy as np

__all__ = ["normalize_buckets", "pick_bucket", "bucket_kv_bytes",
           "BatchFormer", "warmup_buckets", "aot_compile_buckets"]

Bucket = tuple[int, int]  # (P_bucket, steps_bucket)


def normalize_buckets(buckets: Iterable[Sequence[int]]) -> tuple[Bucket, ...]:
    """Validate and sort a bucket set ascending by (P, steps) — the order
    :func:`pick_bucket` scans, so "smallest fitting bucket" is first hit."""
    out = []
    for b in buckets:
        p, s = int(b[0]), int(b[1])
        if p < 1 or s < 1:
            raise ValueError(f"bucket dims must be >= 1, got {(p, s)}")
        out.append((p, s))
    if not out:
        raise ValueError("at least one (P, steps) bucket is required")
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate buckets in {out}")
    return tuple(sorted(out))


def pick_bucket(prompt_len: int, steps: int,
                buckets: Sequence[Bucket]) -> Bucket | None:
    """The smallest bucket holding a ``prompt_len``-token prompt generating
    ``steps`` tokens, or None when nothing fits (an admission rejection —
    better than a surprise compile)."""
    for p, s in buckets:
        if prompt_len <= p and steps <= s:
            return (p, s)
    return None


def bucket_kv_bytes(params: dict, heads: int, bucket: Bucket,
                    compute_dtype=None, batch: int = 1) -> int:
    """Per-request KV-cache bytes for one bucket row (times ``batch``): the
    decode working set is layers x 2 x max_len x kv_heads x dh in the compute
    dtype, and max_len = P + steps. This is the admission-control cost model
    — the cache IS the decode memory (models/transformer.py), so bounding the
    summed row cost bounds what a burst of admissions can pin in HBM."""
    import jax.numpy as jnp

    from ..models.transformer import _n_layers

    p, s = bucket
    d = params["emb"].shape[1]
    dh = d // heads
    kv_dim = params["l0"]["wk"].shape[1]  # kv_heads * dh (GQA-aware)
    dt = jnp.dtype(compute_dtype) if compute_dtype else params["emb"].dtype
    return _n_layers(params) * 2 * (p + s) * (kv_dim // dh) * dh \
        * dt.itemsize * batch


class _Group:
    """One (bucket, sampling-signature) stream of pending entries, kept in
    dispatch order: higher priority first, FIFO among equals (stable sort on
    a monotonic sequence number keeps arrival order)."""

    def __init__(self):
        self.entries: list = []  # (-priority, seq, entry)

    def add(self, entry, seq: int) -> None:
        self.entries.append((-entry.request.priority, seq, entry))
        self.entries.sort(key=lambda t: t[:2])

    def oldest_t(self) -> float:
        """Earliest enqueue time among pending entries (groups are at most
        ~max_batch long, so the scan is trivial)."""
        return min(e.enq_t for _, _, e in self.entries)

    def take(self, n: int):
        taken = [e for _, _, e in self.entries[:n]]
        del self.entries[:n]
        return taken


class BatchFormer:
    """Groups pending entries by (bucket, temperature, top_p, top_k) and
    decides when a batch closes. Not thread-safe by itself — the engine calls
    it under its own condition lock (one mutator, one reader)."""

    def __init__(self, buckets: Sequence[Bucket], max_batch: int,
                 max_wait: float):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait < 0:
            raise ValueError(f"max_wait must be >= 0, got {max_wait}")
        self.buckets = normalize_buckets(buckets)
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._groups: dict[tuple, _Group] = collections.defaultdict(_Group)
        self._seq = 0

    def add(self, entry) -> None:
        """File one admitted entry under its (bucket, sampling) group.
        ``entry.bucket`` and ``entry.enq_t`` were set at admission
        (engine.submit). Sampled requests (temperature > 0) additionally
        group by seed — the whole batch decodes under ONE PRNG key, so a
        different-seed co-tenant would silently get its neighbor's stream;
        greedy requests ignore the key, so seed never fragments their
        batches."""
        r = entry.request
        seed = r.seed if r.temperature > 0 else None
        key = (entry.bucket, float(r.temperature), r.top_p, r.top_k, seed)
        self._groups[key].add(entry, self._seq)
        self._seq += 1

    def pending(self) -> int:
        return sum(len(g.entries) for g in self._groups.values())

    def next_batch(self, now: float, force: bool = False):
        """``(group_key, entries)`` for the batch to dispatch now, else
        ``(None, wait_hint)`` — ``wait_hint`` the seconds (on the injected
        clock) until the oldest partial batch hits ``max_wait`` (``None``
        when nothing is pending). Full groups dispatch immediately; among
        ripe partial groups the longest-waiting dispatches first. ``force``
        treats every non-empty group as ripe — the drain path, where waiting
        out ``max_wait`` for stragglers that can never arrive is pointless."""
        ripe, ripe_t, hint = None, None, None
        for key, g in self._groups.items():
            if not g.entries:
                continue
            if len(g.entries) >= self.max_batch:
                return key, g.take(self.max_batch)
            oldest = g.oldest_t()
            waited = now - oldest
            if force or waited >= self.max_wait:
                if ripe is None or oldest < ripe_t:
                    ripe, ripe_t = key, oldest
            else:
                left = self.max_wait - waited
                hint = left if hint is None else min(hint, left)
        if ripe is not None:
            return ripe, self._groups[ripe].take(self.max_batch)
        return None, hint

    def take_all(self) -> list:
        """Drain every pending entry (close() path — they get ShuttingDown
        results, never a decode)."""
        out = []
        for g in self._groups.values():
            out.extend(g.take(len(g.entries)))
        return out


def _dummy_batch(bucket: Bucket, batch: int):
    """An inert full-width batch for a bucket: 1-token rows of token 0."""
    p, s = bucket
    prompts = np.zeros((batch, p), np.int32)
    lengths = np.ones((batch,), np.int32)
    return prompts, lengths


def warmup_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                   max_batch: int, compute_dtype: str | None = None,
                   moe: tuple | None = None) -> int:
    """Compile (and execute once, on dummy rows) the full-width batch program
    of every bucket, so the first real request never pays the compile.
    Returns the number of buckets warmed. Greedy, top_p/top_k off — the
    default-sampling program; a float top_p or a top_k adds its own variant
    on first use (docs/serving.md)."""
    import jax

    from ..models.transformer import lm_generate_batch

    buckets = normalize_buckets(buckets)
    for bucket in buckets:
        p, s = bucket
        prompts, lengths = _dummy_batch(bucket, max_batch)
        out = lm_generate_batch(params, prompts, lengths, jax.random.key(0),
                                heads=heads, max_len=p + s, steps=s,
                                compute_dtype=compute_dtype, moe=moe)
        jax.block_until_ready(out)
    return len(buckets)


def _peak_bytes(ma) -> int:
    """Peak device bytes from a ``memory_analysis()`` result. Some PJRT
    builds expose ``peak_memory_in_bytes``; where the stats object lacks it
    (the repo's getattr-guarded jaxlib-variance convention), fall back to
    the documented lower bound temp + argument + output bytes."""
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is not None:
        return int(peak)
    return int(ma.temp_size_in_bytes + ma.argument_size_in_bytes
               + ma.output_size_in_bytes)


def aot_compile_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                        max_batch: int, compute_dtype: str | None = None,
                        moe: tuple | None = None,
                        topology_name: str = "v5e:2x2") -> dict[Bucket, int]:
    """Compile every bucket's batch program against a compile-only TPU
    topology (no chip; :mod:`marlin_tpu.utils.aot`) and return
    ``{bucket: peak_hbm_bytes}`` from the compiler's own accounting — the
    offline evidence for sizing ``serve_buckets`` x ``serve_max_batch``
    against :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (the same
    budget the admission gate enforces at runtime). Requires libtpu
    (:func:`~marlin_tpu.utils.aot.supports_aot_tpu`). Peak accounting
    degrades to the temp+argument+output lower bound on PJRT builds whose
    stats object lacks ``peak_memory_in_bytes`` (:func:`_peak_bytes`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..config import config_context
    from ..models.transformer import _lm_generate_batch_jit
    from ..utils.aot import topology_mesh

    mesh = topology_mesh(("rows",), (1,), topology_name=topology_name)
    rep = NamedSharding(mesh, PartitionSpec())

    def sds(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                           sharding=rep), tree)

    out = {}
    for bucket in normalize_buckets(buckets):
        p, s = bucket
        args = (sds(params),
                jax.ShapeDtypeStruct((max_batch, p), jnp.int32, sharding=rep),
                jax.ShapeDtypeStruct((max_batch,), jnp.int32, sharding=rep),
                sds(jax.eval_shape(jax.random.key, 0)),
                jax.ShapeDtypeStruct((), jnp.float32, sharding=rep),
                jax.ShapeDtypeStruct((), jnp.float32, sharding=rep))
        with config_context(pallas_interpret=False):
            compiled = _lm_generate_batch_jit.trace(
                *args[:4], heads=heads, max_len=p + s, steps=s,
                temperature=args[4], compute_dtype=compute_dtype,
                top_p=args[5], use_top_p=False, top_k=None,
                moe=moe).lower().compile()
        out[bucket] = _peak_bytes(compiled.memory_analysis())
    return out
