"""Shape bucketing and per-bucket claim queues for the serving engine.

The serving programs compile one XLA executable per *shape* — slot width B,
padded prompt P, decode steps S are all baked in. Serving traffic is
ragged, so without discipline every new shape pays a fresh multi-second
compile. The discipline here:

- **Buckets** — a small static set of ``(P_bucket, steps_bucket)`` pairs. A
  request pads its prompt up to the smallest fitting ``P_bucket``; rows
  retire at their *requested* steps (the bucket only sizes the cache
  extent).
- **Fixed slot width** — every bucket's row set is exactly ``max_batch``
  wide (free rows run masked-harmless dummies), so B never varies and the
  compile count is bounded by the bucket set, not the traffic pattern.
- **Claim queues** — :class:`BatchFormer` keeps one priority-ordered FIFO
  per bucket; :meth:`BatchFormer.take_for_bucket` hands freed rows the best
  pending request immediately (prefill-on-admit — higher ``priority``
  first, FIFO among equals; sampling knobs never partition anything, they
  are per-row traced vectors in the decode programs). The gang scheduler's
  batch-forming machinery (sampling-knob grouping, ``max_wait`` ripening,
  ``next_batch``) was retired with it in PR 8 — paging superseded the gang
  fallback.
- **Warmup** — :func:`warmup_buckets` compiles the slab scheduler's
  prefill/decode-step pair per bucket before traffic (paged engines warm
  through :func:`~.kvpool.warmup_paged` instead — the engine's
  ``warmup()`` picks); :func:`aot_compile_buckets` compiles the same
  programs against a compile-only TPU topology (:mod:`marlin_tpu.utils
  .aot` — no chip needed) and returns the compiler's per-bucket peak-HBM
  accounting, the offline sizing channel for ``serve_buckets`` /
  ``serve_max_batch`` (paged pools size by page arithmetic instead:
  ``models/planner.kv_page_bytes`` × ``serve_num_pages``).

:class:`SlotPool` tracks the dense-slab backend's per-bucket state
(``serve_paged=False``): a persistent device-resident KV slab of
``max_batch`` slots plus the per-row vectors its decode program takes. The
paged backend's analog lives in :mod:`.kvpool` (:class:`~.kvpool
.PagedGroup`).
"""

from __future__ import annotations

import collections
from typing import Iterable, Sequence

import numpy as np

__all__ = ["normalize_buckets", "pick_bucket", "bucket_kv_bytes",
           "BatchFormer", "SlotPool", "warmup_buckets",
           "aot_compile_buckets", "bucket_program_key",
           "capture_bucket_costs"]

Bucket = tuple[int, int]  # (P_bucket, steps_bucket)


def normalize_buckets(buckets: Iterable[Sequence[int]]) -> tuple[Bucket, ...]:
    """Validate and sort a bucket set ascending by (P, steps) — the order
    :func:`pick_bucket` scans, so "smallest fitting bucket" is first hit."""
    out = []
    for b in buckets:
        p, s = int(b[0]), int(b[1])
        if p < 1 or s < 1:
            raise ValueError(f"bucket dims must be >= 1, got {(p, s)}")
        out.append((p, s))
    if not out:
        raise ValueError("at least one (P, steps) bucket is required")
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate buckets in {out}")
    return tuple(sorted(out))


def pick_bucket(prompt_len: int, steps: int,
                buckets: Sequence[Bucket]) -> Bucket | None:
    """The smallest bucket holding a ``prompt_len``-token prompt generating
    ``steps`` tokens, or None when nothing fits (an admission rejection —
    better than a surprise compile)."""
    for p, s in buckets:
        if prompt_len <= p and steps <= s:
            return (p, s)
    return None


def bucket_kv_bytes(params: dict, heads: int, bucket: Bucket,
                    compute_dtype=None, batch: int = 1) -> int:
    """Per-request KV-cache bytes for one bucket row (times ``batch``): the
    decode working set is layers x 2 x max_len x kv_heads x dh in the compute
    dtype, and max_len = P + steps. This is the admission-control cost model
    — the cache IS the decode memory (models/transformer.py), so bounding the
    summed row cost bounds what a burst of admissions can pin in HBM. The
    charge is taken at admission (reserving the slot the request WILL
    occupy) and must be released on every retirement path — ok, expired,
    error, shutting_down — or admission wedges permanently
    (tests/test_serving.py guards this)."""
    import jax.numpy as jnp

    from ..models.transformer import _n_layers

    p, s = bucket
    d = params["emb"].shape[1]
    dh = d // heads
    kv_dim = params["l0"]["wk"].shape[1]  # kv_heads * dh (GQA-aware)
    dt = jnp.dtype(compute_dtype) if compute_dtype else params["emb"].dtype
    return _n_layers(params) * 2 * (p + s) * (kv_dim // dh) * dh \
        * dt.itemsize * batch


class _Group:
    """One bucket's stream of pending entries, kept in dispatch order:
    higher priority first, FIFO among equals (stable sort on a monotonic
    sequence number keeps arrival order)."""

    def __init__(self):
        self.entries: list = []  # (-priority, seq, entry)

    def add(self, entry, seq: int) -> None:
        self.entries.append((-entry.request.priority, seq, entry))
        self.entries.sort(key=lambda t: t[:2])

    def take(self, n: int):
        taken = [e for _, _, e in self.entries[:n]]
        del self.entries[:n]
        return taken


class BatchFormer:
    """One priority-ordered claim queue per bucket. Sampling knobs never
    partition anything — they are per-row traced vectors in the decode
    programs, so ANY mix shares a step (the gang scheduler's sampling-knob
    grouping and ``max_wait`` ripening retired with it, PR 8; ``max_wait``
    is still accepted and ignored so old call sites don't break). Not
    thread-safe by itself — the engine calls it under its own condition
    lock (one mutator, one reader)."""

    def __init__(self, buckets: Sequence[Bucket], max_batch: int,
                 max_wait: float = 0.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait < 0:
            raise ValueError(f"max_wait must be >= 0, got {max_wait}")
        self.buckets = normalize_buckets(buckets)
        self.max_batch = max_batch
        self.max_wait = max_wait  # legacy knob: nothing ripens anymore
        self._groups: dict[Bucket, _Group] = collections.defaultdict(_Group)
        self._seq = 0

    def add(self, entry) -> None:
        """File one admitted entry under its bucket. ``entry.bucket`` and
        ``entry.enq_t`` were set at admission (engine.submit)."""
        self._groups[entry.bucket].add(entry, self._seq)
        self._seq += 1

    def pending(self) -> int:
        return sum(len(g.entries) for g in self._groups.values())

    def take_all(self) -> list:
        """Drain every pending entry (close() path — they get ShuttingDown
        results, never a decode)."""
        out = []
        for g in self._groups.values():
            out.extend(g.take(len(g.entries)))
        return out

    def pending_buckets(self) -> set:
        """Buckets that currently have pending entries (which groups might
        claim work this iteration)."""
        return {b for b, g in self._groups.items() if g.entries}

    def take_for_bucket(self, bucket: Bucket, n: int) -> list:
        """Up to ``n`` entries bound for ``bucket`` in dispatch order —
        the prefill-on-admit path: a freed row takes the best pending
        request immediately."""
        return self._groups[bucket].take(n) if bucket in self._groups else []


class SlotPool:
    """Slot bookkeeping for one bucket's persistent KV slab (row-level
    scheduling, docs/serving.md): which slot holds which entry, the per-row
    vectors the decode-step program takes (positions, emitted-step counts,
    sampling knobs), and the device-resident ``caches``/``tokens`` slab
    state itself (:func:`~marlin_tpu.models.transformer.init_kv_slab`; the
    engine replaces both references after every donated prefill/decode
    call). Single-threaded — only the engine worker touches a pool."""

    def __init__(self, params: dict, heads: int, bucket: Bucket, width: int,
                 compute_dtype: str | None = None):
        import jax.numpy as jnp

        from ..models.transformer import init_kv_slab

        p, s = bucket
        self.bucket = bucket
        self.width = width
        self.max_len = p + s
        self.caches = init_kv_slab(params, width, self.max_len, heads,
                                   compute_dtype)
        self.tokens = jnp.zeros((width, self.max_len), jnp.int32)
        self.entries: list = [None] * width
        # decode-program inputs; free slots keep position 0 (a harmless
        # dummy step inside their own row — see lm_decode_rows)
        self.positions = np.zeros(width, np.int32)
        self.steps_done = np.zeros(width, np.int32)
        self.lengths = np.zeros(width, np.int32)
        self.seeds = np.zeros(width, np.uint32)
        self.temperature = np.zeros(width, np.float32)
        self.top_p = np.ones(width, np.float32)   # 1.0 = nucleus filter off
        self.top_k = np.zeros(width, np.int32)    # 0 = rank filter off
        self.ttft_s = [None] * width

    def live_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is not None]

    def free_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is None]

    def occupancy(self) -> float:
        return len(self.live_slots()) / self.width

    def assign(self, slot: int, entry) -> None:
        """Bind an admitted entry to a freed slot: after the slot's prefill
        lands, the row's position is its first emitted token (= prompt
        length) and its sampling vectors come from the request."""
        r = entry.request
        self.entries[slot] = entry
        n = r.prompt.shape[0]
        self.lengths[slot] = n
        self.positions[slot] = n          # index of the last written token
        self.steps_done[slot] = 1         # prefill emitted the first token
        self.seeds[slot] = np.uint32(r.seed)
        self.temperature[slot] = r.temperature
        self.top_p[slot] = 1.0 if r.top_p is None else r.top_p
        self.top_k[slot] = 0 if r.top_k is None else r.top_k
        self.ttft_s[slot] = None

    def release(self, slot: int) -> None:
        """Free a slot on ANY retirement path (the stale cache/token row is
        fully overwritten by the next occupant's prefill)."""
        self.entries[slot] = None
        self.positions[slot] = 0
        self.steps_done[slot] = 0
        self.lengths[slot] = 0
        self.temperature[slot] = 0.0
        self.top_p[slot] = 1.0
        self.top_k[slot] = 0
        self.ttft_s[slot] = None


def _dummy_batch(bucket: Bucket, batch: int):
    """An inert full-width batch for a bucket: 1-token rows of token 0."""
    p, s = bucket
    prompts = np.zeros((batch, p), np.int32)
    lengths = np.ones((batch,), np.int32)
    return prompts, lengths


def bucket_program_key(params: dict, bucket: Bucket, max_batch: int,
                       compute_dtype=None) -> str:
    """The roofline-accounting key for one bucket's compiled programs
    (obs/perf.py). Capture sites (warmup/AOT/pool creation) and measurement
    sites (the engine's step/prefill timings) MUST both build the key here,
    or the cost/timing join silently misses."""
    import jax.numpy as jnp

    from ..obs import perf

    p, s = bucket
    dt = jnp.dtype(compute_dtype) if compute_dtype else params["emb"].dtype
    # the model geometry is part of the program identity: two models with
    # the same bucket/width/dtype compile different programs with different
    # costs, and their entries must not collide
    v, d = params["emb"].shape
    try:
        from ..models.transformer import _n_layers

        layers = _n_layers(params)
    except Exception:
        layers = "?"
    return perf.program_key(bucket=f"{p}x{s}", rows=max_batch, dtype=dt.name,
                            model=f"v{v}d{d}l{layers}")


def capture_bucket_costs(params: dict, heads: int, bucket: Bucket,
                         max_batch: int, compute_dtype: str | None = None,
                         moe: tuple | None = None,
                         key: str | None = None) -> None:
    """Capture the XLA cost model (flops, bytes accessed) of a bucket's
    slab program pair into the process :class:`~marlin_tpu.obs.perf
    .ProgramCosts` registry — trace + lower only (no backend compile; the
    bucket's real compile already happened or is about to through the jit
    cache). Gated per (program, bucket key) so repeated calls — the engine
    invokes this on every pool creation — cost two dict lookups after the
    first. Callers on the dispatch path pass their cached ``key`` (the
    engine's ``_prog_key``) so the gate really is that cheap — rebuilding
    it walks the params tree. Never raises: cost capture is observability
    and must not fail warmup or a dispatch. The paged pair captures through
    :func:`~.kvpool.capture_paged_costs`."""
    import jax

    from ..obs import perf

    costs = perf.get_program_costs()
    if key is None:
        key = bucket_program_key(params, bucket, max_batch, compute_dtype)
    programs = ("lm_prefill_slot", "lm_decode_rows")
    # gate on attempted, not succeeded: a backend without cost_analysis()
    # must not re-pay this trace+lower on every dispatch
    if all(costs.tried(name, key) for name in programs):
        return
    import jax.numpy as jnp

    from ..models.transformer import (_lm_decode_rows_jit,
                                      _lm_prefill_slot_jit, init_kv_slab)

    def st(shape, dtype=jnp.int32):
        return jax.ShapeDtypeStruct(shape, dtype)

    sds = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype), tree)
    p, s = bucket
    try:
        caches = sds(jax.eval_shape(
            lambda pp: init_kv_slab(pp, max_batch, p + s, heads,
                                    compute_dtype), params))
        tokens = st((max_batch, p + s))
        pre = _lm_prefill_slot_jit.trace(
            sds(params), caches, tokens, st(()), st((p,)), st(()),
            st((), jnp.uint32), st((), jnp.float32),
            st((), jnp.float32), st(()), heads=heads, max_len=p + s,
            compute_dtype=compute_dtype, moe=moe).lower()
        dec = _lm_decode_rows_jit.trace(
            sds(params), caches, tokens, st((max_batch,)),
            st((max_batch,)), st((max_batch,), jnp.uint32),
            st((max_batch,), jnp.float32),
            st((max_batch,), jnp.float32), st((max_batch,)),
            heads=heads, max_len=p + s, compute_dtype=compute_dtype,
            moe=moe).lower()
        costs.capture("lm_prefill_slot", key, lowered=pre)
        costs.capture("lm_decode_rows", key, lowered=dec)
    except Exception:
        # even a failed trace marks the attempt — never retry per dispatch
        for name in programs:
            costs.capture(name, key)


def warmup_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                   max_batch: int, compute_dtype: str | None = None,
                   moe: tuple | None = None) -> int:
    """Compile (and execute once, on dummy rows) every bucket's dense-slab
    program pair — slot-targeted prefill and the single-token decode step
    over a throwaway slab — so the first real request never pays the
    compile. Sampling knobs are per-row traced, so the two programs are
    the whole slab compile story (docs/serving.md); paged engines warm
    through :func:`~.kvpool.warmup_paged` against their live pool instead.
    Returns the buckets warmed."""
    import jax

    from ..models.transformer import lm_decode_rows, lm_prefill_slot

    buckets = normalize_buckets(buckets)
    for bucket in buckets:
        p, s = bucket
        prompts, _ = _dummy_batch(bucket, max_batch)
        # roofline accounting: the bucket's XLA cost model lands in the
        # process ProgramCosts registry alongside the warmup compile
        capture_bucket_costs(params, heads, bucket, max_batch,
                             compute_dtype, moe)
        pool = SlotPool(params, heads, bucket, max_batch, compute_dtype)
        caches, tokens, _ = lm_prefill_slot(
            params, pool.caches, pool.tokens, 0, prompts[0], 1,
            heads=heads, max_len=p + s, compute_dtype=compute_dtype,
            moe=moe)
        caches, tokens, nxt = lm_decode_rows(
            params, caches, tokens, pool.positions, pool.steps_done,
            pool.seeds, pool.temperature, pool.top_p, pool.top_k,
            heads=heads, max_len=p + s, compute_dtype=compute_dtype,
            moe=moe)
        jax.block_until_ready(nxt)
    return len(buckets)


def _peak_bytes(ma) -> int:
    """Peak device bytes from a ``memory_analysis()`` result. Some PJRT
    builds expose ``peak_memory_in_bytes``; where the stats object lacks it
    (the repo's getattr-guarded jaxlib-variance convention), fall back to
    the documented lower bound temp + argument + output bytes."""
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is not None:
        return int(peak)
    return int(ma.temp_size_in_bytes + ma.argument_size_in_bytes
               + ma.output_size_in_bytes)


def planner_ratio_warning(bucket: Bucket, peak_bytes: int,
                          planner_bytes: int,
                          factor: float = 2.0) -> str | None:
    """Planner honesty check: the warning text when the compiler's own peak
    accounting for a bucket exceeds the planner's slab arithmetic
    (``bucket_kv_bytes`` at full batch) by more than ``factor``, else
    ``None``. Pure so tests pin the threshold without a TPU: a ratio this
    far above 1.0 means the planner's admission budget is not the number
    HBM will actually see, and ``serve_max_batch`` sized from it will OOM
    under load."""
    if planner_bytes <= 0:
        return None
    ratio = peak_bytes / planner_bytes
    if ratio <= factor:
        return None
    return (f"bucket {bucket}: compiler peak {peak_bytes} B is "
            f"{ratio:.1f}x the planner's {planner_bytes} B slab "
            f"arithmetic — size serve_buckets/serve_max_batch from the "
            f"measured peak, not the planner (docs/serving.md, bucket "
            f"tuning)")


def aot_compile_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                        max_batch: int, compute_dtype: str | None = None,
                        moe: tuple | None = None,
                        topology_name: str = "v5e:2x2"
                        ) -> dict[Bucket, int]:
    """Compile every bucket's program(s) against a compile-only TPU
    topology (no chip; :mod:`marlin_tpu.utils.aot`) and return
    ``{bucket: peak_hbm_bytes}`` from the compiler's own accounting — the
    offline evidence for sizing ``serve_buckets`` x ``serve_max_batch``
    against :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (the same
    budget the admission gate enforces at runtime). Compiles the dense-slab
    backend's program pair (slot prefill + decode step) and reports the
    larger peak, warning (``RuntimeWarning``) when that peak exceeds the
    planner's slab arithmetic by more than 2x
    (:func:`planner_ratio_warning`). Sizing rule: every bucket's persistent
    slab stays
    device-resident simultaneously (the engine never frees a pool), so
    steady-state HBM is the SUM over buckets of ``bucket_kv_bytes(...,
    batch=max_batch)`` plus the largest per-bucket program peak reported
    here — not the largest bucket alone. The paged backend sizes by page
    arithmetic instead: ``serve_num_pages`` x
    :func:`~marlin_tpu.models.planner.kv_page_bytes` IS its steady-state
    cache footprint, whatever the bucket set (docs/serving.md, bucket
    tuning). Requires libtpu
    (:func:`~marlin_tpu.utils.aot.supports_aot_tpu`). Peak accounting
    degrades to the temp+argument+output lower bound on PJRT builds whose
    stats object lacks ``peak_memory_in_bytes`` (:func:`_peak_bytes`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..config import config_context
    from ..models.transformer import (_lm_decode_rows_jit,
                                      _lm_prefill_slot_jit, init_kv_slab)
    from ..utils.aot import topology_mesh

    mesh = topology_mesh(("rows",), (1,), topology_name=topology_name)
    rep = NamedSharding(mesh, PartitionSpec())

    def sds(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                           sharding=rep), tree)

    def st(shape, dtype=jnp.int32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

    from ..obs import perf

    costs = perf.get_program_costs()
    out = {}
    for bucket in normalize_buckets(buckets):
        p, s = bucket
        prog_key = bucket_program_key(params, bucket, max_batch,
                                      compute_dtype)
        with config_context(pallas_interpret=False):
            # derive the slab structs from init_kv_slab itself (the one
            # source of truth for the layout) instead of re-deriving
            # d/dh/kvh by hand — a layout change there cannot silently
            # diverge from what this tool sizes
            caches = sds(jax.eval_shape(
                lambda pp: init_kv_slab(pp, max_batch, p + s, heads,
                                        compute_dtype), params))
            tokens = st((max_batch, p + s))
            pre = _lm_prefill_slot_jit.trace(
                sds(params), caches, tokens, st(()), st((p,)), st(()),
                st((), jnp.uint32), st((), jnp.float32),
                st((), jnp.float32), st(()), heads=heads, max_len=p + s,
                compute_dtype=compute_dtype, moe=moe).lower().compile()
            dec = _lm_decode_rows_jit.trace(
                sds(params), caches, tokens, st((max_batch,)),
                st((max_batch,)), st((max_batch,), jnp.uint32),
                st((max_batch,), jnp.float32),
                st((max_batch,), jnp.float32), st((max_batch,)),
                heads=heads, max_len=p + s, compute_dtype=compute_dtype,
                moe=moe).lower().compile()
            # the compiled objects carry BOTH analyses — richest
            # capture the registry gets (memory_analysis included)
            costs.capture("lm_prefill_slot", prog_key, compiled=pre)
            costs.capture("lm_decode_rows", prog_key, compiled=dec)
            out[bucket] = max(_peak_bytes(pre.memory_analysis()),
                              _peak_bytes(dec.memory_analysis()))
            msg = planner_ratio_warning(
                bucket, out[bucket],
                bucket_kv_bytes(params, heads, bucket, compute_dtype))
            if msg is not None:
                import warnings

                warnings.warn(msg, RuntimeWarning, stacklevel=2)
    return out
