"""Shape bucketing and dynamic batch formation for the serving engine.

``_lm_generate_batch_jit`` compiles one XLA program per *shape* — batch B,
padded prompt P, decode steps S are all baked into the executable. Serving
traffic is ragged, so without discipline every new (B, P, S) triple pays a
fresh multi-second compile. The discipline here:

- **Buckets** — a small static set of ``(P_bucket, steps_bucket)`` pairs. A
  request pads its prompt up to the smallest fitting ``P_bucket`` and rounds
  its steps up to that bucket's ``steps_bucket`` (the result is sliced back
  to the requested length).
- **Fixed batch width** — every dispatched batch is padded to exactly
  ``max_batch`` rows (free rows carry an inert 1-token dummy prompt), so B
  never varies and the compile count is bounded by the bucket count, not the
  traffic pattern.
- **Dynamic forming** — :class:`BatchFormer` groups admitted requests by
  (bucket, sampling knobs) and closes a group's batch when it reaches
  ``max_batch`` rows or its oldest request has waited ``max_wait`` seconds,
  whichever first. The clock is injectable, so tests drive the wait logic
  deterministically.
- **Warmup** — :func:`warmup_buckets` runs one dummy full-width batch per
  bucket so the per-bucket compile happens before traffic (the engine
  exposes it as ``ServeEngine.warmup()``); :func:`aot_compile_buckets`
  compiles the same programs against a compile-only TPU topology
  (:mod:`marlin_tpu.utils.aot` — no chip needed) and returns the compiler's
  per-bucket peak-HBM accounting, the offline sizing channel for
  ``serve_buckets`` / ``serve_max_batch``.

Row-level mode (``serve_rowlevel``, the default) keeps the buckets and the
admission cost model but swaps the dispatch unit: :class:`SlotPool` tracks a
persistent device-resident KV slab of ``max_batch`` slots per bucket,
:meth:`BatchFormer.take_for_bucket` hands freed slots the best pending
request immediately (prefill-on-admit — no ``max_wait`` ripening, no
sampling-knob grouping: the decode-step program takes per-row traced
knobs), and warmup/AOT compile exactly TWO programs per bucket (slot
prefill + single-token decode step).
"""

from __future__ import annotations

import collections
import heapq
import itertools
from typing import Iterable, Sequence

import numpy as np

__all__ = ["normalize_buckets", "pick_bucket", "bucket_kv_bytes",
           "BatchFormer", "SlotPool", "warmup_buckets",
           "aot_compile_buckets", "bucket_program_key",
           "capture_bucket_costs"]

Bucket = tuple[int, int]  # (P_bucket, steps_bucket)


def normalize_buckets(buckets: Iterable[Sequence[int]]) -> tuple[Bucket, ...]:
    """Validate and sort a bucket set ascending by (P, steps) — the order
    :func:`pick_bucket` scans, so "smallest fitting bucket" is first hit."""
    out = []
    for b in buckets:
        p, s = int(b[0]), int(b[1])
        if p < 1 or s < 1:
            raise ValueError(f"bucket dims must be >= 1, got {(p, s)}")
        out.append((p, s))
    if not out:
        raise ValueError("at least one (P, steps) bucket is required")
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate buckets in {out}")
    return tuple(sorted(out))


def pick_bucket(prompt_len: int, steps: int,
                buckets: Sequence[Bucket]) -> Bucket | None:
    """The smallest bucket holding a ``prompt_len``-token prompt generating
    ``steps`` tokens, or None when nothing fits (an admission rejection —
    better than a surprise compile)."""
    for p, s in buckets:
        if prompt_len <= p and steps <= s:
            return (p, s)
    return None


def bucket_kv_bytes(params: dict, heads: int, bucket: Bucket,
                    compute_dtype=None, batch: int = 1) -> int:
    """Per-request KV-cache bytes for one bucket row (times ``batch``): the
    decode working set is layers x 2 x max_len x kv_heads x dh in the compute
    dtype, and max_len = P + steps. This is the admission-control cost model
    — the cache IS the decode memory (models/transformer.py), so bounding the
    summed row cost bounds what a burst of admissions can pin in HBM. The
    charge is taken at admission (reserving the slot the request WILL
    occupy) and must be released on every retirement path — ok, expired,
    error, shutting_down — or admission wedges permanently
    (tests/test_serving.py guards this)."""
    import jax.numpy as jnp

    from ..models.transformer import _n_layers

    p, s = bucket
    d = params["emb"].shape[1]
    dh = d // heads
    kv_dim = params["l0"]["wk"].shape[1]  # kv_heads * dh (GQA-aware)
    dt = jnp.dtype(compute_dtype) if compute_dtype else params["emb"].dtype
    return _n_layers(params) * 2 * (p + s) * (kv_dim // dh) * dh \
        * dt.itemsize * batch


class _Group:
    """One (bucket, sampling-signature) stream of pending entries, kept in
    dispatch order: higher priority first, FIFO among equals (stable sort on
    a monotonic sequence number keeps arrival order)."""

    def __init__(self):
        self.entries: list = []  # (-priority, seq, entry)

    def add(self, entry, seq: int) -> None:
        self.entries.append((-entry.request.priority, seq, entry))
        self.entries.sort(key=lambda t: t[:2])

    def oldest_t(self) -> float:
        """Earliest enqueue time among pending entries (groups are at most
        ~max_batch long, so the scan is trivial)."""
        return min(e.enq_t for _, _, e in self.entries)

    def take(self, n: int):
        taken = [e for _, _, e in self.entries[:n]]
        del self.entries[:n]
        return taken


class BatchFormer:
    """Groups pending entries by (bucket, temperature, top_p, top_k) and
    decides when a batch closes. Not thread-safe by itself — the engine calls
    it under its own condition lock (one mutator, one reader)."""

    def __init__(self, buckets: Sequence[Bucket], max_batch: int,
                 max_wait: float):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait < 0:
            raise ValueError(f"max_wait must be >= 0, got {max_wait}")
        self.buckets = normalize_buckets(buckets)
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._groups: dict[tuple, _Group] = collections.defaultdict(_Group)
        self._seq = 0

    def add(self, entry) -> None:
        """File one admitted entry under its (bucket, sampling) group.
        ``entry.bucket`` and ``entry.enq_t`` were set at admission
        (engine.submit). Sampled requests (temperature > 0) additionally
        group by seed — the whole batch decodes under ONE PRNG key, so a
        different-seed co-tenant would silently get its neighbor's stream;
        greedy requests ignore the key, so seed never fragments their
        batches."""
        r = entry.request
        seed = r.seed if r.temperature > 0 else None
        key = (entry.bucket, float(r.temperature), r.top_p, r.top_k, seed)
        self._groups[key].add(entry, self._seq)
        self._seq += 1

    def pending(self) -> int:
        return sum(len(g.entries) for g in self._groups.values())

    def next_batch(self, now: float, force: bool = False):
        """``(group_key, entries)`` for the batch to dispatch now, else
        ``(None, wait_hint)`` — ``wait_hint`` the seconds (on the injected
        clock) until the oldest partial batch hits ``max_wait`` (``None``
        when nothing is pending). Full groups dispatch immediately; among
        ripe partial groups the longest-waiting dispatches first. ``force``
        treats every non-empty group as ripe — the drain path, where waiting
        out ``max_wait`` for stragglers that can never arrive is pointless."""
        ripe, ripe_t, hint = None, None, None
        for key, g in self._groups.items():
            if not g.entries:
                continue
            if len(g.entries) >= self.max_batch:
                return key, g.take(self.max_batch)
            oldest = g.oldest_t()
            waited = now - oldest
            if force or waited >= self.max_wait:
                if ripe is None or oldest < ripe_t:
                    ripe, ripe_t = key, oldest
            else:
                left = self.max_wait - waited
                hint = left if hint is None else min(hint, left)
        if ripe is not None:
            return ripe, self._groups[ripe].take(self.max_batch)
        return None, hint

    def take_all(self) -> list:
        """Drain every pending entry (close() path — they get ShuttingDown
        results, never a decode)."""
        out = []
        for g in self._groups.values():
            out.extend(g.take(len(g.entries)))
        return out

    # ---- row-level claiming (serve_rowlevel): slots admit individually, so
    # the gang machinery above (sampling-knob grouping, max_wait ripening)
    # does not apply — the decode-step program takes per-row traced sampling
    # knobs and every row draws its own stream, so ANY mix shares a step.

    def pending_buckets(self) -> set:
        """Buckets that currently have pending entries (row-level scheduler:
        which slot pools might claim work this iteration)."""
        return {key[0] for key, g in self._groups.items() if g.entries}

    def take_for_bucket(self, bucket: Bucket, n: int) -> list:
        """Up to ``n`` entries bound for ``bucket``, merged across every
        sampling group in dispatch order (higher priority first, FIFO among
        equals) — the prefill-on-admit path: a freed slot takes the best
        pending request immediately, no max_wait ripening. Each group's list
        is already sorted by its (-priority, seq) tuples (``_Group.add``),
        so a k-way heap merge preserves that one ordering rule instead of
        duplicating the comparator here; ``seq`` is globally unique, so the
        tuple comparison never reaches the entry itself."""
        groups = [g for key, g in self._groups.items()
                  if key[0] == bucket and g.entries]
        taken = list(itertools.islice(
            heapq.merge(*(g.entries for g in groups)), n))
        take_ids = {id(t) for t in taken}
        for g in groups:
            g.entries = [t for t in g.entries if id(t) not in take_ids]
        return [e for _, _, e in taken]


class SlotPool:
    """Slot bookkeeping for one bucket's persistent KV slab (row-level
    scheduling, docs/serving.md): which slot holds which entry, the per-row
    vectors the decode-step program takes (positions, emitted-step counts,
    sampling knobs), and the device-resident ``caches``/``tokens`` slab
    state itself (:func:`~marlin_tpu.models.transformer.init_kv_slab`; the
    engine replaces both references after every donated prefill/decode
    call). Single-threaded — only the engine worker touches a pool."""

    def __init__(self, params: dict, heads: int, bucket: Bucket, width: int,
                 compute_dtype: str | None = None):
        import jax.numpy as jnp

        from ..models.transformer import init_kv_slab

        p, s = bucket
        self.bucket = bucket
        self.width = width
        self.max_len = p + s
        self.caches = init_kv_slab(params, width, self.max_len, heads,
                                   compute_dtype)
        self.tokens = jnp.zeros((width, self.max_len), jnp.int32)
        self.entries: list = [None] * width
        # decode-program inputs; free slots keep position 0 (a harmless
        # dummy step inside their own row — see lm_decode_rows)
        self.positions = np.zeros(width, np.int32)
        self.steps_done = np.zeros(width, np.int32)
        self.lengths = np.zeros(width, np.int32)
        self.seeds = np.zeros(width, np.uint32)
        self.temperature = np.zeros(width, np.float32)
        self.top_p = np.ones(width, np.float32)   # 1.0 = nucleus filter off
        self.top_k = np.zeros(width, np.int32)    # 0 = rank filter off
        self.ttft_s = [None] * width

    def live_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is not None]

    def free_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is None]

    def occupancy(self) -> float:
        return len(self.live_slots()) / self.width

    def assign(self, slot: int, entry) -> None:
        """Bind an admitted entry to a freed slot: after the slot's prefill
        lands, the row's position is its first emitted token (= prompt
        length) and its sampling vectors come from the request."""
        r = entry.request
        self.entries[slot] = entry
        n = r.prompt.shape[0]
        self.lengths[slot] = n
        self.positions[slot] = n          # index of the last written token
        self.steps_done[slot] = 1         # prefill emitted the first token
        self.seeds[slot] = np.uint32(r.seed)
        self.temperature[slot] = r.temperature
        self.top_p[slot] = 1.0 if r.top_p is None else r.top_p
        self.top_k[slot] = 0 if r.top_k is None else r.top_k
        self.ttft_s[slot] = None

    def release(self, slot: int) -> None:
        """Free a slot on ANY retirement path (the stale cache/token row is
        fully overwritten by the next occupant's prefill)."""
        self.entries[slot] = None
        self.positions[slot] = 0
        self.steps_done[slot] = 0
        self.lengths[slot] = 0
        self.temperature[slot] = 0.0
        self.top_p[slot] = 1.0
        self.top_k[slot] = 0
        self.ttft_s[slot] = None


def _dummy_batch(bucket: Bucket, batch: int):
    """An inert full-width batch for a bucket: 1-token rows of token 0."""
    p, s = bucket
    prompts = np.zeros((batch, p), np.int32)
    lengths = np.ones((batch,), np.int32)
    return prompts, lengths


def bucket_program_key(params: dict, bucket: Bucket, max_batch: int,
                       compute_dtype=None) -> str:
    """The roofline-accounting key for one bucket's compiled programs
    (obs/perf.py). Capture sites (warmup/AOT/pool creation) and measurement
    sites (the engine's step/prefill timings) MUST both build the key here,
    or the cost/timing join silently misses."""
    import jax.numpy as jnp

    from ..obs import perf

    p, s = bucket
    dt = jnp.dtype(compute_dtype) if compute_dtype else params["emb"].dtype
    # the model geometry is part of the program identity: two models with
    # the same bucket/width/dtype compile different programs with different
    # costs, and their entries must not collide
    v, d = params["emb"].shape
    try:
        from ..models.transformer import _n_layers

        layers = _n_layers(params)
    except Exception:
        layers = "?"
    return perf.program_key(bucket=f"{p}x{s}", rows=max_batch, dtype=dt.name,
                            model=f"v{v}d{d}l{layers}")


def capture_bucket_costs(params: dict, heads: int, bucket: Bucket,
                         max_batch: int, compute_dtype: str | None = None,
                         moe: tuple | None = None,
                         rowlevel: bool | None = None,
                         key: str | None = None) -> None:
    """Capture the XLA cost model (flops, bytes accessed) of a bucket's
    compiled program(s) into the process :class:`~marlin_tpu.obs.perf
    .ProgramCosts` registry — trace + lower only (no backend compile; the
    bucket's real compile already happened or is about to through the jit
    cache). Gated per (program, bucket key) so repeated calls — the engine
    invokes this on every pool creation and gang dispatch — cost two dict
    lookups after the first. Callers on the dispatch path pass their cached
    ``key`` (the engine's ``_prog_key``) so the gate really is that cheap —
    rebuilding it walks the params tree. Never raises: cost capture is
    observability and must not fail warmup or a dispatch."""
    import jax

    from ..config import get_config
    from ..obs import perf

    if rowlevel is None:
        rowlevel = get_config().serve_rowlevel
    costs = perf.get_program_costs()
    if key is None:
        key = bucket_program_key(params, bucket, max_batch, compute_dtype)
    programs = (("lm_prefill_slot", "lm_decode_rows") if rowlevel
                else ("lm_generate_batch",))
    # gate on attempted, not succeeded: a backend without cost_analysis()
    # must not re-pay this trace+lower on every gang dispatch
    if all(costs.tried(name, key) for name in programs):
        return
    import jax.numpy as jnp

    from ..models.transformer import (_lm_decode_rows_jit,
                                      _lm_generate_batch_jit,
                                      _lm_prefill_slot_jit, init_kv_slab)

    def st(shape, dtype=jnp.int32):
        return jax.ShapeDtypeStruct(shape, dtype)

    sds = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype), tree)
    p, s = bucket
    try:
        if rowlevel:
            caches = sds(jax.eval_shape(
                lambda pp: init_kv_slab(pp, max_batch, p + s, heads,
                                        compute_dtype), params))
            tokens = st((max_batch, p + s))
            pre = _lm_prefill_slot_jit.trace(
                sds(params), caches, tokens, st(()), st((p,)), st(()),
                st((), jnp.uint32), st((), jnp.float32),
                st((), jnp.float32), st(()), heads=heads, max_len=p + s,
                compute_dtype=compute_dtype, moe=moe).lower()
            dec = _lm_decode_rows_jit.trace(
                sds(params), caches, tokens, st((max_batch,)),
                st((max_batch,)), st((max_batch,), jnp.uint32),
                st((max_batch,), jnp.float32),
                st((max_batch,), jnp.float32), st((max_batch,)),
                heads=heads, max_len=p + s, compute_dtype=compute_dtype,
                moe=moe).lower()
            costs.capture("lm_prefill_slot", key, lowered=pre)
            costs.capture("lm_decode_rows", key, lowered=dec)
        else:
            lo = _lm_generate_batch_jit.trace(
                sds(params), st((max_batch, p)), st((max_batch,)),
                sds(jax.eval_shape(jax.random.key, 0)),
                heads=heads, max_len=p + s, steps=s,
                temperature=st((), jnp.float32),
                compute_dtype=compute_dtype, top_p=st((), jnp.float32),
                use_top_p=False, top_k=None, moe=moe).lower()
            costs.capture("lm_generate_batch", key, lowered=lo)
    except Exception:
        # even a failed trace marks the attempt — never retry per dispatch
        for name in programs:
            costs.capture(name, key)


def warmup_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                   max_batch: int, compute_dtype: str | None = None,
                   moe: tuple | None = None,
                   rowlevel: bool | None = None) -> int:
    """Compile (and execute once, on dummy rows) every bucket's programs, so
    the first real request never pays the compile. ``rowlevel`` defaults
    from ``config.serve_rowlevel``, matching what an all-default engine
    runs: gang mode warms the one fused full-width batch program per
    bucket; row-level warms the TWO programs per bucket — slot-targeted
    prefill and the single-token decode step over a throwaway slab.
    Returns the number of buckets warmed. Greedy/default-sampling programs
    in gang mode (a float top_p or a top_k adds its own variant on first
    use); row-level sampling knobs are per-row traced, so the two programs
    are the whole compile story (docs/serving.md)."""
    import jax

    from ..config import get_config
    from ..models.transformer import lm_generate_batch

    if rowlevel is None:
        rowlevel = get_config().serve_rowlevel
    buckets = normalize_buckets(buckets)
    for bucket in buckets:
        p, s = bucket
        prompts, lengths = _dummy_batch(bucket, max_batch)
        # roofline accounting: the bucket's XLA cost model lands in the
        # process ProgramCosts registry alongside the warmup compile
        capture_bucket_costs(params, heads, bucket, max_batch,
                             compute_dtype, moe, rowlevel=rowlevel)
        if rowlevel:
            from ..models.transformer import lm_decode_rows, lm_prefill_slot

            pool = SlotPool(params, heads, bucket, max_batch, compute_dtype)
            caches, tokens, _ = lm_prefill_slot(
                params, pool.caches, pool.tokens, 0, prompts[0], 1,
                heads=heads, max_len=p + s, compute_dtype=compute_dtype,
                moe=moe)
            caches, tokens, nxt = lm_decode_rows(
                params, caches, tokens, pool.positions, pool.steps_done,
                pool.seeds, pool.temperature, pool.top_p, pool.top_k,
                heads=heads, max_len=p + s, compute_dtype=compute_dtype,
                moe=moe)
            jax.block_until_ready(nxt)
        else:
            out = lm_generate_batch(
                params, prompts, lengths, jax.random.key(0), heads=heads,
                max_len=p + s, steps=s, compute_dtype=compute_dtype, moe=moe)
            jax.block_until_ready(out)
    return len(buckets)


def _peak_bytes(ma) -> int:
    """Peak device bytes from a ``memory_analysis()`` result. Some PJRT
    builds expose ``peak_memory_in_bytes``; where the stats object lacks it
    (the repo's getattr-guarded jaxlib-variance convention), fall back to
    the documented lower bound temp + argument + output bytes."""
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is not None:
        return int(peak)
    return int(ma.temp_size_in_bytes + ma.argument_size_in_bytes
               + ma.output_size_in_bytes)


def aot_compile_buckets(params: dict, heads: int, buckets: Sequence[Bucket],
                        max_batch: int, compute_dtype: str | None = None,
                        moe: tuple | None = None,
                        topology_name: str = "v5e:2x2",
                        rowlevel: bool | None = None) -> dict[Bucket, int]:
    """Compile every bucket's program(s) against a compile-only TPU
    topology (no chip; :mod:`marlin_tpu.utils.aot`) and return
    ``{bucket: peak_hbm_bytes}`` from the compiler's own accounting — the
    offline evidence for sizing ``serve_buckets`` x ``serve_max_batch``
    against :func:`~marlin_tpu.models.planner.usable_hbm_bytes` (the same
    budget the admission gate enforces at runtime). ``rowlevel`` defaults
    from ``config.serve_rowlevel`` — the same scheduler an all-default
    :class:`~.engine.ServeEngine` will actually run. Gang mode compiles the
    fused batch program; row-level compiles BOTH programs (slot prefill +
    decode step) and reports the larger peak. NOTE the row-level sizing
    rule differs from gang: every bucket's persistent slab stays device-
    resident simultaneously (the engine never frees a pool), so steady-
    state HBM is the SUM over buckets of ``bucket_kv_bytes(...,
    batch=max_batch)`` plus the largest per-bucket program peak reported
    here — not the largest bucket alone (docs/serving.md, bucket tuning).
    Requires libtpu (:func:`~marlin_tpu.utils.aot.supports_aot_tpu`). Peak
    accounting degrades to the temp+argument+output lower bound on PJRT
    builds whose stats object lacks ``peak_memory_in_bytes``
    (:func:`_peak_bytes`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..config import config_context, get_config
    from ..models.transformer import (_lm_decode_rows_jit,
                                      _lm_generate_batch_jit,
                                      _lm_prefill_slot_jit, init_kv_slab)
    from ..utils.aot import topology_mesh

    if rowlevel is None:
        rowlevel = get_config().serve_rowlevel
    mesh = topology_mesh(("rows",), (1,), topology_name=topology_name)
    rep = NamedSharding(mesh, PartitionSpec())

    def sds(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                           sharding=rep), tree)

    def st(shape, dtype=jnp.int32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

    from ..obs import perf

    costs = perf.get_program_costs()
    out = {}
    for bucket in normalize_buckets(buckets):
        p, s = bucket
        prog_key = bucket_program_key(params, bucket, max_batch,
                                      compute_dtype)
        with config_context(pallas_interpret=False):
            if rowlevel:
                # derive the slab structs from init_kv_slab itself (the one
                # source of truth for the layout) instead of re-deriving
                # d/dh/kvh by hand — a layout change there cannot silently
                # diverge from what this tool sizes
                caches = sds(jax.eval_shape(
                    lambda pp: init_kv_slab(pp, max_batch, p + s, heads,
                                            compute_dtype), params))
                tokens = st((max_batch, p + s))
                pre = _lm_prefill_slot_jit.trace(
                    sds(params), caches, tokens, st(()), st((p,)), st(()),
                    st((), jnp.uint32), st((), jnp.float32),
                    st((), jnp.float32), st(()), heads=heads, max_len=p + s,
                    compute_dtype=compute_dtype, moe=moe).lower().compile()
                dec = _lm_decode_rows_jit.trace(
                    sds(params), caches, tokens, st((max_batch,)),
                    st((max_batch,)), st((max_batch,), jnp.uint32),
                    st((max_batch,), jnp.float32),
                    st((max_batch,), jnp.float32), st((max_batch,)),
                    heads=heads, max_len=p + s, compute_dtype=compute_dtype,
                    moe=moe).lower().compile()
                # the compiled objects carry BOTH analyses — richest
                # capture the registry gets (memory_analysis included)
                costs.capture("lm_prefill_slot", prog_key, compiled=pre)
                costs.capture("lm_decode_rows", prog_key, compiled=dec)
                out[bucket] = max(_peak_bytes(pre.memory_analysis()),
                                  _peak_bytes(dec.memory_analysis()))
            else:
                args = (sds(params), st((max_batch, p)), st((max_batch,)),
                        sds(jax.eval_shape(jax.random.key, 0)),
                        st((), jnp.float32), st((), jnp.float32))
                compiled = _lm_generate_batch_jit.trace(
                    *args[:4], heads=heads, max_len=p + s, steps=s,
                    temperature=args[4], compute_dtype=compute_dtype,
                    top_p=args[5], use_top_p=False, top_k=None,
                    moe=moe).lower().compile()
                costs.capture("lm_generate_batch", prog_key,
                              compiled=compiled)
                out[bucket] = _peak_bytes(compiled.memory_analysis())
    return out
