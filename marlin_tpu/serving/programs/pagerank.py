"""Incremental PageRank queries as a BucketProgram.

The graph stays resident in edge form (:class:`~marlin_tpu.ml.pagerank
.TransitionOperator` — the never-densify representation) next to a live
rank vector. A request names a node (payload ``{"node": int, "k": int?}``)
and gets the top-k *out-neighbors of that node by current global rank* —
the "who should this page link-surf to" query — computed as one batched
edge-mask + ``lax.top_k`` over the resident arrays.

"Incremental" is :meth:`PageRankQueryProgram.refresh`: between queries the
operator advances the resident rank vector by a few power-iteration steps
(:func:`~marlin_tpu.ml.pagerank._pagerank_step`, the same edge-form SpMV
the offline solver runs), so ranks track the graph without ever blocking
the serving path — queries read whatever vector is installed, swaps are
atomic under the program lock, and refresh compiles once per iteration
count.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...config import get_config
from ...ml.pagerank import (TransitionOperator, _pagerank_step,
                            build_transition_operator)
from ...obs import perf
from . import register_program
from .base import BucketProgram

__all__ = ["PageRankQueryProgram"]


@functools.partial(jax.jit, static_argnames=("k",))
def _pr_neighbor_topk(src, dst, ranks, nodes, k: int):
    """Top-k out-neighbors by rank for a padded batch of query nodes: mask
    the edge list per query row, score each edge by its destination's rank,
    top-k over the edge axis. (W, E) is the honest cost of an unsorted
    adjacency — the admission budget charges exactly this row."""
    sel = src[None, :] == nodes[:, None]                      # (W, E)
    scored = jnp.where(sel, ranks[dst][None, :], -jnp.inf)    # (W, E)
    vals, eidx = jax.lax.top_k(scored, k)
    return vals, dst[eidx]


@functools.partial(jax.jit, static_argnames=("n", "iterations"))
def _pr_refresh(r, src, dst, inv_deg, dangling, damping, n: int,
                iterations: int):
    def body(_, rr):
        return _pagerank_step(rr, src, dst, None, inv_deg, dangling,
                              damping, n)
    return jax.lax.fori_loop(0, iterations, body, r)


@register_program
class PageRankQueryProgram(BucketProgram):
    """node → top-k out-neighbors by live PageRank over a resident graph."""

    name = "pagerank"
    cost_program = "pagerank_query"
    resource_unit = "one padded edge-mask row: num_edges x 4 bytes"

    def __init__(self, edges, n: int | None = None, damping: float = 0.85):
        super().__init__()
        op = (edges if isinstance(edges, TransitionOperator)
              else build_transition_operator(edges, n))
        if op.mesh is not None or op.weight is not None:
            raise ValueError("serving wants an unsharded operator "
                             "(build without mesh=)")
        self._op = op
        self.n = int(op.n)
        self.num_edges = int(op.nnz)
        self._damping = jnp.asarray(damping, jnp.float32)
        self._ranks = jnp.full((self.n,), 1.0 / self.n, jnp.float32)
        cfg = get_config()
        ks = tuple(sorted({int(k) for k in cfg.serve_program_topk
                           if int(k) <= self.num_edges}))
        if not ks:
            raise ValueError(
                f"no serve_program_topk value fits num_edges="
                f"{self.num_edges} (got {cfg.serve_program_topk!r})")
        self._ks = ks
        self.refresh_count = 0
        self._ledger_register(op.src, op.dst, op.inv_deg, op.dangling,
                              self._ranks)

    def refresh(self, iterations: int = 1) -> np.ndarray:
        """Advance the resident rank vector ``iterations`` power steps and
        install it atomically; returns the new ranks (host copy). One
        compile per distinct ``iterations`` value — callers should pick
        one cadence and stick to it."""
        op = self._op
        with self._lock:
            r = self._ranks
        r = _pr_refresh(r, op.src, op.dst, op.inv_deg, op.dangling,
                        self._damping, self.n, int(iterations))
        with self._lock:
            self._ranks = r
            self.refresh_count += 1
        return np.asarray(jax.device_get(r))

    def ranks(self) -> np.ndarray:
        with self._lock:
            return np.asarray(jax.device_get(self._ranks))

    # ---------------------------------------------------------------- policy
    def buckets(self):
        return [(k,) for k in self._ks]

    def validate(self, request):
        p = request.payload
        if not isinstance(p, dict) or "node" not in p:
            return (f"program {self.name!r} needs payload "
                    f"{{'node': int, 'k': int?}}, got {type(p).__name__}")
        node = p["node"]
        if not 0 <= int(node) < self.n:
            return f"node {node} out of range [0, {self.n})"
        k = int(p.get("k", self._ks[0]))
        if k < 1:
            return f"k must be >= 1, got {k}"
        return None

    def pick_bucket(self, request):
        k = int(request.payload.get("k", self._ks[0]))
        for kb in self._ks:
            if kb >= k:
                return (kb,)
        return None

    def refuse_no_bucket(self, request):
        return (f"no bucket fits program='pagerank' k="
                f"{request.payload.get('k')} (k buckets {list(self._ks)})")

    def admission_cost(self, request, bucket):
        return self.num_edges * 4

    def program_key(self, bucket, width=None):
        return perf.program_key(
            prog=self.name, n=self.n, edges=self.num_edges, k=bucket[0],
            width=width or self.width)

    # ------------------------------------------------------------- mechanism
    def warmup(self) -> int:
        n = 0
        op = self._op
        nodes = {w: jnp.zeros((w,), jnp.int32) for w in self.widths}
        with self._lock:
            ranks = self._ranks
        for (k,) in self.buckets():
            for w in self.widths:
                self._capture_cost(self.program_key((k,), w),
                                   _pr_neighbor_topk, op.src, op.dst, ranks,
                                   nodes[w], k=k)
                _pr_neighbor_topk(op.src, op.dst, ranks, nodes[w], k=k)
                n += 1
        return n

    def step(self, bucket, requests):
        (k,) = bucket
        op = self._op
        w = self.step_width(len(requests))
        nodes = np.full((w,), -1, np.int32)  # -1 matches no src: empty rows
        for i, r in enumerate(requests):
            # analyze: ignore[host-sync] — payload ints are host data
            nodes[i] = int(r.payload["node"])
        with self._lock:
            ranks = self._ranks
        vals, items = _pr_neighbor_topk(op.src, op.dst, ranks,
                                        jnp.asarray(nodes), k=k)
        # analyze: ignore[host-sync] — THE one intentional sync per program
        # step: the one-shot batch retires here with host Result values
        vals = np.asarray(jax.device_get(vals))
        # analyze: ignore[host-sync] — same fetch, second output
        items = np.asarray(jax.device_get(items))
        out = []
        for i, r in enumerate(requests):
            want = int(r.payload.get("k", k))
            good = np.isfinite(vals[i, :want])  # < k out-neighbors pad -inf
            out.append({"items": items[i, :want][good].copy(),
                        "scores": vals[i, :want][good].copy()})
        return out
