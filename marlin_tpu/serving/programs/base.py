"""BucketProgram — the program-shaped seam in the serving engine.

The engine's production spine (AdmissionQueue backpressure, static-bucket
batch forming, the single worker thread, supervisor retry, freeze/adopt
migration, the ``serve`` event stream) was built for paged LM decode but is
not LM-specific: what the spine actually needs from a workload is a handful
of *policy* answers — which static bucket does this request round up to,
what does it cost the admission budget, what's the compiled-program key for
ProgramCosts — plus one *mechanism*: execute a padded batch of rows. A
:class:`BucketProgram` is exactly that contract. The paged-LM path is the
first implementation (:mod:`.lm`, unchanged behavior); ALS scoring,
incremental PageRank queries, and batched classification (:mod:`.als`,
:mod:`.pagerank`, :mod:`.classify`) ride the same spine as additional
request types keyed by ``Request.program``.

Resource-unit contract: ``admission_cost`` is charged against the engine's
one AdmissionQueue HBM budget, so every program prices requests in *bytes
of device residency the request adds while in flight* — KV pages for LM,
one padded score row for ALS/PageRank, one feature row for classification.
Heterogeneous traffic then shares a single honest budget instead of
per-program quotas that fragment it.

Non-LM programs here are **one-shot**: a request is admitted, parked in a
host-side :class:`ProgramRowSet` (the non-KV analog of a paged pool), and
answered by the next batched device call for its bucket. One step retires
the whole batch, which is what makes drain/close, crash recovery, and
freeze/adopt migration compose for free — a live program row is
indistinguishable from a queued one up to its ``queue_s`` clock, so the
engine can always fall back to re-queueing the entry (exactly-once is the
handle's job, not the row's).
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

from ...config import get_config
from ...obs import perf

__all__ = ["BucketProgram", "ProgramRowSet"]


class ProgramRowSet:
    """Host-side row parking for one program bucket — the structural twin of
    a paged pool (``entries`` + ``occupied_slots``/``live_slots``/
    ``free_slots``) with no device state, so the engine's crash handler,
    recovery sweep, and freeze path iterate it with the same code that walks
    KV pools."""

    def __init__(self, bucket, width: int):
        self.bucket = bucket
        self.width = int(width)
        self.entries: list[Any] = [None] * self.width

    def occupied_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is not None]

    # the engine's row-level walkers ask for live_slots(); every occupied
    # program row is live (one-shot programs have no prefill phase)
    live_slots = occupied_slots

    def free_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.entries) if e is None]

    def assign(self, slot: int, entry) -> None:
        assert self.entries[slot] is None, f"slot {slot} occupied"
        self.entries[slot] = entry

    def release(self, slot: int) -> None:
        self.entries[slot] = None


class BucketProgram:
    """One servable workload: policy (buckets, admission cost, program keys)
    plus the batched step that answers requests.

    Lifecycle (the engine drives every arrow)::

        submit ──► validate ──► pick_bucket ──► admission_cost ──► queue
                                                      │ reject/expire
        queue ──► admit (ProgramRowSet slot / KV claim) ──► step ──► Result
                                                      │ crash/freeze
        freeze ──► (state blob | fallback requeue) ──► adopt on the target

    Subclasses implement the policy surface (:meth:`pick_bucket`,
    :meth:`admission_cost`, :meth:`program_key`, :meth:`warmup`,
    :meth:`step`) and may override :meth:`validate`, :meth:`freeze`, and
    :meth:`adopt`. ``name`` keys the registry and ``Request.program``;
    ``cost_program`` names the ProgramCosts family the step timings land
    in; ``resource_unit`` documents what ``admission_cost`` bytes mean.

    Batch widths are the static shape axis shared by all programs: the
    ``serve_program_batches`` config knob lists the padded widths, a step
    pads its live rows up to the smallest fitting width, and compiles are
    bounded by ``len(widths) x len(buckets())`` per program — asserted by
    the ``compile_count`` fixture in tests."""

    name: str = ""
    cost_program: str = ""
    resource_unit: str = "bytes resident per in-flight request"

    def __init__(self):
        cfg = get_config()
        widths = tuple(sorted({int(w) for w in cfg.serve_program_batches}))
        if not widths or widths[0] < 1:
            raise ValueError(
                f"serve_program_batches must be positive ints, got "
                f"{cfg.serve_program_batches!r}")
        self.widths = widths
        #: row capacity of one ProgramRowSet (the largest padded width)
        self.width = widths[-1]
        # guards hot model swaps against the worker thread's step reads
        self._lock = threading.Lock()

    # ---------------------------------------------------------------- policy
    def buckets(self) -> Sequence[tuple]:
        """The static program-bucket tuples this program compiles for."""
        raise NotImplementedError

    def validate(self, request) -> str | None:
        """Synchronous payload check at submit; a string rejects the
        request with that reason, None admits it to bucket selection."""
        return None

    def pick_bucket(self, request) -> tuple | None:
        """Smallest program bucket that fits the request, or None (the
        engine refuses with :meth:`refuse_no_bucket`'s message)."""
        raise NotImplementedError

    def refuse_no_bucket(self, request) -> str:
        return (f"no bucket fits program={self.name!r} request "
                f"(buckets {list(self.buckets())})")

    def admission_cost(self, request, bucket) -> int:
        """Bytes of device residency this request adds while in flight —
        charged against the engine's single AdmissionQueue HBM budget."""
        raise NotImplementedError

    def program_key(self, bucket, width: int | None = None) -> str:
        """ProgramCosts key for one compiled (bucket, width) variant."""
        raise NotImplementedError

    def step_width(self, live: int) -> int:
        """Smallest configured padded width covering ``live`` rows."""
        for w in self.widths:
            if w >= live:
                return w
        return self.width

    # ------------------------------------------------------------- mechanism
    def warmup(self) -> int:
        """Compile every (bucket, width) variant ahead of traffic and land
        its cost record in ProgramCosts; returns the variant count."""
        raise NotImplementedError

    def step(self, bucket, requests) -> list:
        """Answer one padded batch: ``requests`` are the live rows of one
        program bucket (len ≤ ``width``); returns one host-side result
        value per request, in order. Must route through a compiled
        program cached per (bucket, padded width)."""
        raise NotImplementedError

    # ------------------------------------------------------------- migration
    def freeze(self, entry) -> Any:
        """Export device state for one live row at freeze time. None (the
        default) means the row has no exportable state — the engine
        re-queues it through the migration ``fallback`` lane and the
        target simply re-executes it (safe: the handle, not the row,
        guarantees exactly-once)."""
        return None

    def adopt(self, entry, state=None) -> None:
        """Import a row frozen by :meth:`freeze` on the source engine.
        One-shot programs have nothing to import."""
        return None

    # --------------------------------------------------------------- helpers
    def _ledger_register(self, *trees) -> None:
        """Account this program's device-resident model buffers in the
        process :class:`~marlin_tpu.obs.memledger.MemoryLedger` (component
        ``program``) — called at construction and after every hot
        ``swap_model``, where the free-then-register pair debits the old
        weights and credits the new ones exactly (the ledger entry name is
        per-instance, so two programs of one class never collide). Never
        raises — accounting must not fail a swap."""
        try:
            from ...obs import memledger

            try:
                import jax

                leaves = jax.tree_util.tree_leaves(list(trees))
            except Exception:
                leaves = list(trees)
            nbytes = sum(int(getattr(l, "nbytes", 0) or 0) for l in leaves)
            led = memledger.get_ledger()
            entry = f"program:{self.name}#{id(self)}"
            led.free(entry, strict=False)
            led.register(entry, nbytes, "program",
                         owner=f"program:{self.name}")
        except Exception:
            pass

    def _capture_cost(self, key: str, fn, *args, **static) -> None:
        """Land one compile-cost record for ``fn(*args, **static)`` in
        ProgramCosts unless already tried — warmup bookkeeping shared by
        every program."""
        costs = perf.get_program_costs()
        if not costs.tried(self.cost_program, key):
            try:
                costs.capture(self.cost_program, key,
                              lowered=fn.lower(*args, **static))
            except Exception:  # pragma: no cover - cost capture is advisory
                pass
