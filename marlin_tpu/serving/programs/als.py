"""ALS recommendation scoring as a BucketProgram.

The paper's flagship workload (PAPER.md §0) served online: factor matrices
trained by :mod:`marlin_tpu.ml.als` stay device-resident, a request names a
user (payload ``{"user": int, "k": int?}``) and gets that user's top-k items
by inner-product score — one gather, one (W, items) matmul, one
``lax.top_k``, batched over a padded width. Buckets are the configured k
values (``serve_program_topk``); a requested k rounds up to the smallest
bucket and the Result slices back down, exactly like LM steps round up to a
decode bucket.

:meth:`ALSScoreProgram.swap_model` installs freshly trained factors
atomically under the program lock — same shapes hit the same compiled
programs (factors are traced operands), so a hot factor update never
recompiles and never tears a batch (the worker reads both matrices under
the same lock acquisition).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...config import get_config
from ...obs import perf
from . import register_program
from .base import BucketProgram

__all__ = ["ALSScoreProgram"]


@functools.partial(jax.jit, static_argnames=("k",))
def _als_topk(user_factors, item_factors, users, k: int):
    """Top-k items for a padded batch of users: scores = U[users] @ Vᵀ."""
    u = jnp.take(user_factors, users, axis=0)        # (W, rank)
    scores = u @ item_factors.T                      # (W, items)
    vals, idx = jax.lax.top_k(scores, k)
    return vals, idx


def _factors(model):
    """Device arrays from an ALSModel (or any .user_features/
    .product_features pair, or a raw (users, items) array 2-tuple)."""
    uf = getattr(model, "user_features", None)
    pf = getattr(model, "product_features", None)
    if uf is None or pf is None:
        uf, pf = model
    if hasattr(uf, "logical"):
        uf = uf.logical()
    if hasattr(pf, "logical"):
        pf = pf.logical()
    uf = jnp.asarray(uf, jnp.float32)
    pf = jnp.asarray(pf, jnp.float32)
    if uf.ndim != 2 or pf.ndim != 2 or uf.shape[1] != pf.shape[1]:
        raise ValueError(
            f"factor shapes disagree: users {uf.shape}, items {pf.shape}")
    return uf, pf


@register_program
class ALSScoreProgram(BucketProgram):
    """user → top-k item recommendations against resident ALS factors."""

    name = "als"
    cost_program = "als_score"
    resource_unit = "one padded score row: num_items x 4 bytes"

    def __init__(self, model):
        super().__init__()
        self._uf, self._pf = _factors(model)
        self.num_users = int(self._uf.shape[0])
        self.num_items = int(self._pf.shape[0])
        self.rank = int(self._uf.shape[1])
        cfg = get_config()
        ks = tuple(sorted({int(k) for k in cfg.serve_program_topk
                           if int(k) <= self.num_items}))
        if not ks:
            raise ValueError(
                f"no serve_program_topk value fits num_items="
                f"{self.num_items} (got {cfg.serve_program_topk!r})")
        self._ks = ks
        self.swap_count = 0
        self._ledger_register(self._uf, self._pf)

    def swap_model(self, model) -> None:
        """Atomically install freshly trained factors. Shapes must match
        the resident model (same compiled programs keep serving)."""
        uf, pf = _factors(model)
        if (uf.shape, pf.shape) != (self._uf.shape, self._pf.shape):
            raise ValueError(
                f"swap_model shape mismatch: resident "
                f"({self._uf.shape}, {self._pf.shape}), new "
                f"({uf.shape}, {pf.shape})")
        with self._lock:
            self._uf, self._pf = uf, pf
            self.swap_count += 1
        self._ledger_register(self._uf, self._pf)

    # ---------------------------------------------------------------- policy
    def buckets(self):
        return [(k,) for k in self._ks]

    def validate(self, request):
        p = request.payload
        if not isinstance(p, dict) or "user" not in p:
            return (f"program {self.name!r} needs payload "
                    f"{{'user': int, 'k': int?}}, got {type(p).__name__}")
        user = p["user"]
        if not 0 <= int(user) < self.num_users:
            return (f"user {user} out of range [0, {self.num_users})")
        k = int(p.get("k", self._ks[0]))
        if k < 1:
            return f"k must be >= 1, got {k}"
        return None

    def pick_bucket(self, request):
        k = int(request.payload.get("k", self._ks[0]))
        for kb in self._ks:
            if kb >= k:
                return (kb,)
        return None

    def refuse_no_bucket(self, request):
        return (f"no bucket fits program='als' k="
                f"{request.payload.get('k')} (k buckets {list(self._ks)})")

    def admission_cost(self, request, bucket):
        return self.num_items * 4

    def program_key(self, bucket, width=None):
        return perf.program_key(
            prog=self.name, users=self.num_users, items=self.num_items,
            rank=self.rank, k=bucket[0], width=width or self.width)

    # ------------------------------------------------------------- mechanism
    def warmup(self) -> int:
        n = 0
        users = {w: jnp.zeros((w,), jnp.int32) for w in self.widths}
        with self._lock:
            uf, pf = self._uf, self._pf
        for (k,) in self.buckets():
            for w in self.widths:
                self._capture_cost(self.program_key((k,), w), _als_topk,
                                   uf, pf, users[w], k=k)
                _als_topk(uf, pf, users[w], k=k)
                n += 1
        return n

    def step(self, bucket, requests):
        (k,) = bucket
        w = self.step_width(len(requests))
        users = np.zeros((w,), np.int32)
        for i, r in enumerate(requests):
            # analyze: ignore[host-sync] — payload ints are host data
            users[i] = int(r.payload["user"])
        with self._lock:
            uf, pf = self._uf, self._pf
        vals, idx = _als_topk(uf, pf, jnp.asarray(users), k=k)
        # analyze: ignore[host-sync] — THE one intentional sync per program
        # step: a one-shot batch retires here and its Result values are
        # host data by contract (the kernel above launched async)
        vals = np.asarray(jax.device_get(vals))
        # analyze: ignore[host-sync] — same fetch, second output
        idx = np.asarray(jax.device_get(idx))
        out = []
        for i, r in enumerate(requests):
            want = int(r.payload.get("k", k))
            out.append({"items": idx[i, :want].copy(),
                        "scores": vals[i, :want].copy()})
        return out
