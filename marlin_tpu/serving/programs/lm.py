"""The paged/slab LM decode path as the first BucketProgram.

This is the extraction end of the refactor: the *policy* the engine's
``_submit`` used to hardcode for LM traffic — bucket rounding
(:func:`~marlin_tpu.serving.batcher.pick_bucket`), page-unit admission
pricing (:func:`~marlin_tpu.models.planner.request_pages` × page bytes, or
the slab worst case), the pool-capacity refusal, and the ProgramCosts keys
— now answers through the same :class:`~.base.BucketProgram` surface every
other program uses. The *mechanism* (chunked prefill, the decode step, KV
page bookkeeping) stays in the engine's paged/slab loops untouched: LM rows
execute exactly the pre-refactor code path, which is what keeps greedy
output bit-identical to ``lm_generate`` — the acceptance bar for this
seam. :meth:`PagedLMProgram.step` is therefore deliberately unreachable;
the freeze/adopt hooks are likewise the engine's KV-blob export, not ours.
"""

from __future__ import annotations

import threading

from ..batcher import bucket_kv_bytes, pick_bucket
from . import register_program
from .base import BucketProgram

__all__ = ["PagedLMProgram"]


@register_program
class PagedLMProgram(BucketProgram):
    """token prompt → generated tokens via the engine's paged/slab loops."""

    name = "lm"
    cost_program = "lm_decode_paged"
    resource_unit = ("actual KV pages x page bytes (paged) / "
                     "bucket slab bytes (slab)")

    def __init__(self, engine):
        # no super().__init__: LM's batch axis is the engine's max_batch,
        # not the serve_program_batches widths shared by one-shot programs
        self._eng = engine
        self._lock = threading.Lock()
        self.widths = (engine.max_batch,)
        self.width = engine.max_batch

    # ---------------------------------------------------------------- policy
    def buckets(self):
        return list(self._eng.buckets)

    def validate(self, request):
        if request.prompt is None:
            return "program 'lm' needs a token prompt"
        return None

    def pick_bucket(self, request):
        return pick_bucket(request.prompt.shape[0], request.steps,
                           self._eng.buckets)

    def refuse_no_bucket(self, request):
        return (f"no bucket fits prompt_len={request.prompt.shape[0]} "
                f"steps={request.steps} (buckets {list(self._eng.buckets)})")

    def admission_cost(self, request, bucket):
        eng = self._eng
        if eng.paged:
            # admission charges the request's ACTUAL pages (the memory its
            # cache rows can ever write — planner.request_pages), not the
            # bucket worst case: short requests in long buckets stop
            # reserving capacity they never use
            from ...models.planner import request_pages

            pages = request_pages(request.prompt.shape[0], request.steps,
                                  eng._page_len)
            if pages > eng._num_pages - 1:
                raise ValueError(
                    f"request needs {pages} KV pages but the pool holds "
                    f"{eng._num_pages - 1} (serve_num_pages)")
            return pages * eng._page_bytes
        return bucket_kv_bytes(eng.params, eng.heads, bucket,
                               eng.compute_dtype)

    def program_key(self, bucket, width=None):
        return self._eng._prog_key(bucket)

    # ------------------------------------------------------------- mechanism
    def warmup(self) -> int:
        # ServeEngine.warmup drives the LM compiles directly (paged program
        # identity includes the live pool's slab shape)
        return 0

    def step(self, bucket, requests):  # pragma: no cover - engine-executed
        raise RuntimeError(
            "LM rows execute in the engine's paged/slab loops, not via "
            "BucketProgram.step")
