"""The BucketProgram registry: servable workloads keyed by
``Request.program``.

``PROGRAM_REGISTRY`` maps a program name to its :class:`~.base
.BucketProgram` subclass; :func:`register_program` is the class decorator
that populates it at import time. The engine resolves ``Request.program``
against the *instances* it was constructed with (``ServeEngine(...,
programs=[...])``) — the registry is the catalog (error messages, docs
tables, tooling), the engine's instance map is the routing table, and the
two agree by construction because every instance's class registered here.

See docs/serving.md ("BucketProgram interface") for the lifecycle diagram
and the how-to-add-a-program walkthrough.
"""

from __future__ import annotations

__all__ = ["PROGRAM_REGISTRY", "register_program", "available_programs",
           "BucketProgram", "ProgramRowSet", "PagedLMProgram",
           "ALSScoreProgram", "PageRankQueryProgram", "ClassifyProgram"]

#: program name -> BucketProgram subclass
PROGRAM_REGISTRY: dict[str, type] = {}


def register_program(cls):
    """Class decorator: catalog one BucketProgram subclass by its name."""
    name = getattr(cls, "name", "")
    if not name:
        raise ValueError(f"{cls.__name__} must set a non-empty .name")
    if name in PROGRAM_REGISTRY:
        raise ValueError(
            f"program {name!r} already registered by "
            f"{PROGRAM_REGISTRY[name].__name__}")
    PROGRAM_REGISTRY[name] = cls
    return cls


def available_programs() -> list[str]:
    return sorted(PROGRAM_REGISTRY)


from .base import BucketProgram, ProgramRowSet  # noqa: E402
from .lm import PagedLMProgram  # noqa: E402
from .als import ALSScoreProgram  # noqa: E402
from .pagerank import PageRankQueryProgram  # noqa: E402
from .classify import ClassifyProgram  # noqa: E402
