"""Batched classification / embedding scoring as a BucketProgram.

Serves either of the paper's supervised models online: a
:class:`~marlin_tpu.ml.logistic_regression.LogisticRegressionModel`
(intercept-first weight vector) or an MLP parameter dict from
:func:`~marlin_tpu.ml.neural_network.mlp_init` — a request carries one
feature vector (payload ``{"x": (d,) floats}``) and gets back the model's
probabilities plus an argmax/threshold label. One program bucket (the model
is the shape), padded batch widths shared with every other program, and the
same atomic :meth:`ClassifyProgram.swap_model` hot-update contract as ALS.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...ml.neural_network import mlp_forward
from ...obs import perf
from . import register_program
from .base import BucketProgram

__all__ = ["ClassifyProgram"]


@jax.jit
def _logreg_proba(weights, x):
    return jax.nn.sigmoid(weights[0] + x @ weights[1:])


@functools.partial(jax.jit, static_argnames=("activation",))
def _mlp_proba(params, x, activation: str):
    return mlp_forward(params, x, activation)


def _model_arrays(model, activation):
    """(kind, params, feature_dim, num_outputs) for either model family."""
    w = getattr(model, "weights", model)
    if isinstance(w, dict):
        params = {k: jnp.asarray(v, jnp.float32) for k, v in w.items()}
        # mlp_forward indexes w0..wN by position; validate the contract here
        # so a typo'd dict fails at construction, not inside a traced call
        for i in range(len(params)):
            if f"w{i}" not in params:
                raise ValueError(
                    f"MLP params must be w0..w{len(params) - 1}, got "
                    f"{sorted(params)}")
        dim = int(params["w0"].shape[0])
        n_out = int(params[f"w{len(params) - 1}"].shape[1])
        # abstract trace: rejects an unknown activation at construction
        jax.eval_shape(lambda p, xx: mlp_forward(p, xx, activation),
                       params, jnp.zeros((1, dim), jnp.float32))
        return "mlp", params, dim, n_out
    w = jnp.asarray(w, jnp.float32).reshape(-1)
    if w.shape[0] < 2:
        raise ValueError(f"logreg weights need [intercept, w...], got "
                         f"shape {w.shape}")
    return "logreg", w, int(w.shape[0]) - 1, 1


@register_program
class ClassifyProgram(BucketProgram):
    """feature vector → class probabilities over a resident model."""

    name = "classify"
    cost_program = "classify_fwd"
    resource_unit = "one padded feature row: feature_dim x 4 bytes"

    def __init__(self, model, activation: str = "sigmoid"):
        super().__init__()
        self._activation = activation
        self._kind, self._params, self.feature_dim, self.num_outputs = \
            _model_arrays(model, activation)
        self.swap_count = 0
        self._ledger_register(self._params)

    def swap_model(self, model) -> None:
        """Atomically install new weights of the same shape (same compiled
        programs keep serving; a shape change is a new program)."""
        kind, params, dim, n_out = _model_arrays(model, self._activation)
        if (kind, dim, n_out) != (self._kind, self.feature_dim,
                                  self.num_outputs):
            raise ValueError(
                f"swap_model shape mismatch: resident {self._kind} "
                f"d={self.feature_dim} out={self.num_outputs}, new {kind} "
                f"d={dim} out={n_out}")
        with self._lock:
            self._params = params
            self.swap_count += 1
        self._ledger_register(self._params)

    # ---------------------------------------------------------------- policy
    def buckets(self):
        return [()]  # the model is the shape; width is the only batch axis

    def validate(self, request):
        p = request.payload
        x = p.get("x") if isinstance(p, dict) else p
        if x is None:
            return (f"program {self.name!r} needs payload "
                    f"{{'x': ({self.feature_dim},) floats}}")
        x = np.asarray(x, np.float32).reshape(-1)
        if x.shape[0] != self.feature_dim:
            return (f"feature vector has {x.shape[0]} dims, model wants "
                    f"{self.feature_dim}")
        return None

    def pick_bucket(self, request):
        return ()

    def admission_cost(self, request, bucket):
        return self.feature_dim * 4

    def program_key(self, bucket, width=None):
        return perf.program_key(
            prog=self.name, kind=self._kind, dim=self.feature_dim,
            out=self.num_outputs, width=width or self.width)

    # ------------------------------------------------------------- mechanism
    def _fwd(self, params, x):
        if self._kind == "logreg":
            return _logreg_proba(params, x)
        return _mlp_proba(params, x, self._activation)

    def warmup(self) -> int:
        n = 0
        with self._lock:
            params = self._params
        for w in self.widths:
            x = jnp.zeros((w, self.feature_dim), jnp.float32)
            fn = _logreg_proba if self._kind == "logreg" else _mlp_proba
            if self._kind == "logreg":
                self._capture_cost(self.program_key((), w), fn, params, x)
            else:
                self._capture_cost(self.program_key((), w), fn, params, x,
                                   activation=self._activation)
            self._fwd(params, x)
            n += 1
        return n

    def step(self, bucket, requests):
        w = self.step_width(len(requests))
        x = np.zeros((w, self.feature_dim), np.float32)
        for i, r in enumerate(requests):
            p = r.payload
            # analyze: ignore[host-sync] — payload features are host data
            x[i] = np.asarray(p.get("x") if isinstance(p, dict) else p,
                              np.float32).reshape(-1)
        with self._lock:
            params = self._params
        # analyze: ignore[host-sync] — THE one intentional sync per program
        # step: the one-shot batch retires here with host Result values
        proba = np.asarray(jax.device_get(self._fwd(params, jnp.asarray(x))))
        out = []
        for i, _ in enumerate(requests):
            row = proba[i]
            if row.ndim == 0 or (row.ndim == 1 and row.shape[0] == 1):
                p1 = float(np.reshape(row, ()) if row.ndim == 0 else row[0])
                out.append({"proba": p1, "label": int(p1 >= 0.5)})
            else:
                # analyze: ignore[host-sync] — row is already host numpy
                out.append({"proba": row.copy(),
                            "label": int(np.argmax(row))})  # analyze: ignore[host-sync] — host numpy
        return out
