"""Retrying IO: exponential backoff + jitter + deadline, built for seam tests.

The reference leans on Spark/Hadoop client retries for every HDFS hiccup
(SURVEY.md §5.3); the rebuild's remote-filesystem hook (io/fs.py) talks to
object stores and network filesystems directly, so transient failures are this
library's problem. :class:`RetryPolicy` is the one shared answer: remote
``open_path``/``list_names`` and checkpoint IO route through it.

Design points:

- **Seam-tested determinism** — the clock, the sleep, and the jitter RNG are
  all injectable (``clock=``, ``sleep=``, ``seed=``), so tests assert the
  exact backoff sequence without real waiting.
- **Observability** — every retry emits a ``retry`` event to the policy's
  :class:`~marlin_tpu.utils.tracing.EventLog` (or the process-default log,
  :func:`~marlin_tpu.utils.tracing.set_default_event_log`); silent retries
  hide degraded storage until it becomes an outage.
- **Deadline** — a wall-clock budget caps total time across attempts; a
  policy with generous attempt counts still fails fast when the budget is
  spent (the last error is re-raised, never swallowed).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterator

from .faults import FaultInjected
from .tracing import get_default_event_log

__all__ = ["RetryPolicy", "get_retry_policy", "set_retry_policy"]

#: Exceptions worth retrying by default: transient IO. TimeoutError and
#: ConnectionError are OSError subclasses; FaultInjected is included so chaos
#: tests exercise the same code path production errors take.
DEFAULT_RETRY_ON: tuple[type[BaseException], ...] = (OSError, FaultInjected)


class RetryPolicy:
    """Exponential backoff with jitter and an overall deadline.

    ``delay(i)`` for attempt i (0-based) is
    ``min(max_delay, base_delay * multiplier**i)`` scaled by a random factor
    in ``[1, 1 + jitter]`` drawn from ``random.Random(seed)`` — seeded
    policies produce identical delay sequences run-to-run.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.25,
        deadline: float | None = None,
        retry_on: tuple[type[BaseException], ...] = DEFAULT_RETRY_ON,
        seed: int | None = None,
        clock: Callable[[], float] | None = None,
        sleep: Callable[[float], None] | None = None,
        event_log=None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline = deadline
        self.retry_on = retry_on
        self.clock = clock or time.monotonic
        self.sleep = sleep or time.sleep
        self.event_log = event_log
        self._rng = random.Random(seed)
        #: total retries performed through this policy (across calls)
        self.retries = 0

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt + 1`` (0-based)."""
        d = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.random()
        return d

    def delays(self) -> Iterator[float]:
        """The at-most ``max_attempts - 1`` backoff delays, in order."""
        for i in range(self.max_attempts - 1):
            yield self.delay(i)

    def call(self, fn: Callable[[], Any], describe: str = "",
             retry_on: tuple[type[BaseException], ...] | None = None) -> Any:
        """Run ``fn()`` with retries; re-raises the last error when the
        attempt budget or deadline is exhausted."""
        retry_on = retry_on or self.retry_on
        log = self.event_log or get_default_event_log()
        start = self.clock()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                attempt += 1
                if attempt >= self.max_attempts:
                    if log is not None:
                        log.event("retry_exhausted", op=describe,
                                  attempts=attempt, error=repr(e))
                    raise
                d = self.delay(attempt - 1)
                if (self.deadline is not None
                        and self.clock() - start + d > self.deadline):
                    if log is not None:
                        log.event("retry_deadline", op=describe,
                                  attempts=attempt, error=repr(e))
                    raise
                self.retries += 1
                if log is not None:
                    log.event("retry", op=describe, attempt=attempt,
                              delay_s=d, error=repr(e))
                self.sleep(d)


_policy = RetryPolicy()


def get_retry_policy() -> RetryPolicy:
    """The process-wide policy remote IO (io/fs.py) retries through."""
    return _policy


def set_retry_policy(policy: RetryPolicy | None) -> RetryPolicy:
    """Swap the process-wide policy (None restores the default); returns the
    previous one so tests can put it back."""
    global _policy
    prev = _policy
    _policy = policy if policy is not None else RetryPolicy()
    return prev
