from .profiling import timer, evaluate, StepTimer, trace  # noqa: F401
from .tracing import (  # noqa: F401
    annotate,
    EventLog,
    matmul_flops,
    effective_gflops,
    get_default_event_log,
    set_default_event_log,
)
from .failure import ResilientLoop, heartbeat, NonFiniteLossError  # noqa: F401
from .retry import RetryPolicy, get_retry_policy, set_retry_policy  # noqa: F401
from . import faults  # noqa: F401
from .mtutils import (  # noqa: F401
    random_den_vec_matrix,
    random_block_matrix,
    random_dis_vector,
    random_spa_vec_matrix,
    zeros_den_vec_matrix,
    ones_den_vec_matrix,
    ones_dis_vector,
    array_to_matrix,
    matrix_to_array,
    repeat_by_row,
    repeat_by_column,
)
