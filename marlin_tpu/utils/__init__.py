from .profiling import timer, evaluate, StepTimer, trace  # noqa: F401
from .tracing import annotate, EventLog, matmul_flops, effective_gflops  # noqa: F401
from .failure import ResilientLoop, heartbeat, NonFiniteLossError  # noqa: F401
from .mtutils import (  # noqa: F401
    random_den_vec_matrix,
    random_block_matrix,
    random_dis_vector,
    random_spa_vec_matrix,
    zeros_den_vec_matrix,
    ones_den_vec_matrix,
    ones_dis_vector,
    array_to_matrix,
    matrix_to_array,
    repeat_by_row,
    repeat_by_column,
)
