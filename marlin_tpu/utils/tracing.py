"""Structured tracing/observability beyond wall-clock prints.

The reference's observability is `System.currentTimeMillis` deltas and raw
printlns (SURVEY.md §5.1/§5.5 — flagged as a gap worth exceeding). This module
adds:

- :func:`annotate` — names a region so it shows up in `jax.profiler` traces
  (XProf/TensorBoard) as a labeled span.
- :class:`EventLog` — append-only JSON-lines event log (step timings, bytes
  moved, custom counters) for post-hoc analysis without a profiler UI. Every
  record automatically carries the active span context
  (:mod:`marlin_tpu.obs.trace` — ``trace_id``/``span_id``/``parent_id``), so
  records across threads and subsystems join into traces; the analyzer
  (``python -m marlin_tpu.obs.report``) reconstructs them.
- :func:`matmul_flops` / :func:`effective_gflops` — the FLOP bookkeeping the
  examples print, centralized.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from typing import Any

import jax

from ..config import get_config as _get_config
from ..obs.trace import context_fields as _span_fields

__all__ = ["annotate", "EventLog", "matmul_flops", "effective_gflops",
           "set_default_event_log", "get_default_event_log"]


@contextlib.contextmanager
def annotate(name: str):
    """Label a region in profiler traces; no-ops cheaply outside tracing."""
    with jax.profiler.TraceAnnotation(name):
        yield


def matmul_flops(m: int, k: int, n: int) -> float:
    return 2.0 * m * k * n


def effective_gflops(flops: float, seconds: float) -> float:
    return flops / max(seconds, 1e-12) / 1e9


class EventLog:
    """JSON-lines event log: ``log.event("step", step=i, loss=x)``. Each line
    carries a monotonic timestamp plus the active span context; flushes per
    event so crashes keep history (this doubles as the post-mortem record
    for the failure subsystem).

    ``max_bytes`` bounds the file via rotation: a write that would cross the
    bound first shifts ``path`` → ``path.1`` → ``path.2`` (``backups``
    generations kept, oldest dropped) — per-event flush with unbounded
    growth is not serve-loop safe for long-running engines. ``None`` defers
    to ``config.obs_log_max_bytes`` *at write time* (so ``config_context``
    scoping works); 0 disables rotation."""

    def __init__(self, path: str, max_bytes: int | None = None,
                 backups: int = 2):
        self.path = path
        self.max_bytes = max_bytes
        self.backups = int(backups)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")
        # append mode: tell() is not the size on every platform until the
        # first write — ask the filesystem
        self._size = os.path.getsize(path)
        self.last_read_skipped = 0
        # writers are concurrent (serving workers, prefetch producers, the
        # submitting thread): a shared handle without a lock interleaves
        # partial lines, corrupting the JSONL stream
        self._lock = threading.Lock()

    def _limit(self) -> int:
        if self.max_bytes is not None:
            return self.max_bytes
        return _get_config().obs_log_max_bytes

    def _maybe_rotate(self, nbytes: int) -> None:
        """Rotate (under the write lock) when the next line would cross the
        bound. A single line larger than the whole bound still writes — an
        event is never dropped, the NEXT write rotates."""
        limit = self._limit()
        if not limit or self._size == 0 or self._size + nbytes <= limit:
            return
        self._f.close()
        for i in range(self.backups - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if self.backups >= 1:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._f = open(self.path, "a")
        self._size = 0

    def event(self, kind: str, **fields: Any) -> None:
        # span context first so an explicit field of the same name (a
        # caller restamping trace_id) wins
        rec = {"t": time.time(), "kind": kind, **_span_fields(), **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._f.closed:
                return  # a worker racing close() drops its record rather
                # than killing its thread — observability must stay passive
            self._maybe_rotate(len(line))
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    @contextlib.contextmanager
    def timed(self, kind: str, **fields: Any):
        """Times the body; the record lands even when the body raises
        (tagged ``ok=False``) — a crash is exactly when the post-mortem
        needs the timing, not when it should vanish."""
        t0 = time.perf_counter()
        ok = True
        try:
            yield
        except BaseException:
            ok = False
            raise
        finally:
            self.event(kind, seconds=time.perf_counter() - t0, ok=ok,
                       **fields)

    def close(self) -> None:
        # under the write lock: closing mid-event from another thread would
        # raise "I/O operation on closed file" inside the writer
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def read(self, include_rotated: bool = False) -> list[dict]:
        """Parsed records, oldest first. A torn line — a process killed
        mid-``write`` leaves a partial JSON tail, exactly the crash this
        log is the post-mortem for — is skipped and flagged (a
        ``RuntimeWarning`` plus ``self.last_read_skipped``) instead of
        raising ``JSONDecodeError`` and taking the whole record down with
        it. ``include_rotated`` prepends the ``.2``/``.1`` backups that
        exist, so a rotated stream reads as one."""
        from ..obs.report import load_events  # one torn-line-tolerant parse

        paths = [self.path]
        if include_rotated:
            paths = [p for i in range(self.backups, 0, -1)
                     for p in [f"{self.path}.{i}"] if os.path.exists(p)
                     ] + paths
        records = []
        skipped = 0
        for p in paths:
            recs, sk = load_events(p)
            records.extend(recs)
            skipped += sk
        self.last_read_skipped = skipped
        if skipped:
            warnings.warn(
                f"{self.path}: skipped {skipped} torn/partial JSONL "
                f"line(s) (process killed mid-write?)", RuntimeWarning,
                stacklevel=2)
        return records


# Process-default event log: subsystems without a log handle of their own
# (remote-IO retries in utils/retry.py, recovery events in utils/failure.py)
# report here when one is installed, so a run's post-mortem record is one
# stream rather than per-module fragments.
_default_log: EventLog | None = None


def set_default_event_log(log: EventLog | None) -> EventLog | None:
    """Install (or, with None, remove) the process-default event log;
    returns the previous one so callers can restore it."""
    global _default_log
    prev = _default_log
    _default_log = log
    return prev


def get_default_event_log() -> EventLog | None:
    return _default_log
