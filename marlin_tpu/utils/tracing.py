"""Structured tracing/observability beyond wall-clock prints.

The reference's observability is `System.currentTimeMillis` deltas and raw
printlns (SURVEY.md §5.1/§5.5 — flagged as a gap worth exceeding). This module
adds:

- :func:`annotate` — names a region so it shows up in `jax.profiler` traces
  (XProf/TensorBoard) as a labeled span.
- :class:`EventLog` — append-only JSON-lines event log (step timings, bytes
  moved, custom counters) for post-hoc analysis without a profiler UI.
- :func:`matmul_flops` / :func:`effective_gflops` — the FLOP bookkeeping the
  examples print, centralized.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any

import jax

__all__ = ["annotate", "EventLog", "matmul_flops", "effective_gflops",
           "set_default_event_log", "get_default_event_log"]


@contextlib.contextmanager
def annotate(name: str):
    """Label a region in profiler traces; no-ops cheaply outside tracing."""
    with jax.profiler.TraceAnnotation(name):
        yield


def matmul_flops(m: int, k: int, n: int) -> float:
    return 2.0 * m * k * n


def effective_gflops(flops: float, seconds: float) -> float:
    return flops / max(seconds, 1e-12) / 1e9


class EventLog:
    """JSON-lines event log: ``log.event("step", step=i, loss=x)``. Each line
    carries a monotonic timestamp; flushes per event so crashes keep history
    (this doubles as the post-mortem record for the failure subsystem)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")
        # writers are concurrent (serving workers, prefetch producers, the
        # submitting thread): a shared handle without a lock interleaves
        # partial lines, corrupting the JSONL stream
        self._lock = threading.Lock()

    def event(self, kind: str, **fields: Any) -> None:
        rec = {"t": time.time(), "kind": kind, **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._f.closed:
                return  # a worker racing close() drops its record rather
                # than killing its thread — observability must stay passive
            self._f.write(line)
            self._f.flush()

    @contextlib.contextmanager
    def timed(self, kind: str, **fields: Any):
        """Times the body; the record lands even when the body raises
        (tagged ``ok=False``) — a crash is exactly when the post-mortem
        needs the timing, not when it should vanish."""
        t0 = time.perf_counter()
        ok = True
        try:
            yield
        except BaseException:
            ok = False
            raise
        finally:
            self.event(kind, seconds=time.perf_counter() - t0, ok=ok,
                       **fields)

    def close(self) -> None:
        # under the write lock: closing mid-event from another thread would
        # raise "I/O operation on closed file" inside the writer
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def read(self) -> list[dict]:
        with open(self.path) as f:
            return [json.loads(line) for line in f if line.strip()]


# Process-default event log: subsystems without a log handle of their own
# (remote-IO retries in utils/retry.py, recovery events in utils/failure.py)
# report here when one is installed, so a run's post-mortem record is one
# stream rather than per-module fragments.
_default_log: EventLog | None = None


def set_default_event_log(log: EventLog | None) -> EventLog | None:
    """Install (or, with None, remove) the process-default event log;
    returns the previous one so callers can restore it."""
    global _default_log
    prev = _default_log
    _default_log = log
    return prev


def get_default_event_log() -> EventLog | None:
    return _default_log
