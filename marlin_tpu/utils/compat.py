"""JAX version compatibility shims.

The multi-device modules are written against the modern ``jax.shard_map``
API (top-level export, ``check_vma=``, varying-manual-axes types and
``jax.lax.pcast``). Older jax releases (e.g. 0.4.x, where the CPU CI
container sits) carry the same functionality as
``jax.experimental.shard_map.shard_map`` with ``check_rep=`` and no
varying types at all. Importing — and pytest-collecting — a module must
never depend on which era of jax is installed, so every shard_map user
routes through this module instead of touching ``jax.shard_map`` at
attribute-lookup time:

    from ..utils.compat import shard_map, pcast
    f = shard_map(local, mesh=mesh, in_specs=..., out_specs=...,
                  check_vma=False)

On a jax with neither API the wrapper raises ``ShardMapUnavailable``
(a ``NotImplementedError``) at *call* time with an actionable message —
analysis and collection of the importing file degrade to a skip, not an
import error.
"""

from __future__ import annotations

import functools

import jax

__all__ = ["HAS_SHARD_MAP", "ShardMapUnavailable", "shard_map", "pcast",
           "vma_of", "shape_dtype_struct"]


class ShardMapUnavailable(NotImplementedError):
    """Raised when no shard_map implementation exists in this jax."""


def _resolve():
    """(callable, style): the best shard_map and which kwarg dialect it
    speaks — "vma" (modern top-level) or "rep" (experimental)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm, "vma"
    try:
        from jax.experimental.shard_map import shard_map as esm
        return esm, "rep"
    except ImportError:
        return None, ""


HAS_SHARD_MAP = _resolve()[0] is not None


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None, **kw):
    """``jax.shard_map`` on modern jax, ``jax.experimental.shard_map`` on
    0.4.x (translating ``check_vma`` to ``check_rep`` and the
    partial-manual ``axis_names=`` selection to its 0.4.x complement
    ``auto=``). With ``f=None`` returns a partial, so
    ``functools.partial(shard_map, mesh=...)`` call sites keep working
    unchanged."""
    impl, style = _resolve()
    if impl is None:
        raise ShardMapUnavailable(
            "this jax has neither jax.shard_map nor "
            "jax.experimental.shard_map; the multi-device paths need one "
            "of them (install jax >= 0.4.3)")
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    if check_vma is not None:
        kwargs["check_vma" if style == "vma" else "check_rep"] = check_vma
    if axis_names is not None:
        if style == "vma":
            kwargs["axis_names"] = axis_names
        else:
            kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    if f is None:
        return functools.partial(impl, **kwargs)
    return impl(f, **kwargs)


def vma_of(x):
    """The varying-manual-axes set of ``x``'s abstract type — empty on jax
    without ``jax.typeof`` / VMA types (0.4.x), where every manual-mode
    value is implicitly varying and there is nothing to propagate."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(x), "vma", frozenset()))


def shape_dtype_struct(shape, dtype, vma=frozenset()):
    """``jax.ShapeDtypeStruct`` forwarding ``vma=`` only when non-empty —
    0.4.x has no such kwarg, and :func:`vma_of` returns the empty set
    there, so the two degrade together."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def pcast(t, axes, to="varying"):
    """``jax.lax.pcast`` where it exists; identity on pre-VMA jax, whose
    type system has no varying/invariant distinction to cast between."""
    impl = getattr(jax.lax, "pcast", None)
    if impl is None:
        return t
    return impl(t, axes, to=to)
