"""MTUtils-parity factory facade.

The reference funnels all user-facing construction through the ``MTUtils``
object (utils/MTUtils.scala:34-134 factories, 402-438 array converters,
446-491 repeat). These are thin wrappers over the matrix classmethods so code
ported from the reference reads one-to-one.
"""

from __future__ import annotations

import numpy as np

from ..matrix.dense import BlockMatrix, DenseVecMatrix
from ..matrix.sparse import SparseVecMatrix
from ..matrix.vector import DistributedVector


def random_den_vec_matrix(rows: int, cols: int, seed: int = 0, dist: str = "uniform",
                          mesh=None, **kw):
    """MTUtils.randomDenVecMatrix (utils/MTUtils.scala:63-73)."""
    return DenseVecMatrix.random(seed, rows, cols, dist=dist, mesh=mesh, **kw)


def random_block_matrix(rows: int, cols: int, seed: int = 0, dist: str = "uniform",
                        mesh=None, **kw):
    """MTUtils.randomBlockMatrix (utils/MTUtils.scala:96-116)."""
    return BlockMatrix.random(seed, rows, cols, dist=dist, mesh=mesh, **kw)


def random_dis_vector(length: int, seed: int = 0, dist: str = "uniform", mesh=None, **kw):
    """MTUtils.randomDisVector (utils/MTUtils.scala:34-47)."""
    return DistributedVector.random(seed, length, dist=dist, mesh=mesh, **kw)


def random_spa_vec_matrix(rows: int, cols: int, density: float = 0.01, seed: int = 0,
                          mesh=None, **kw):
    """MTUtils.randomSpaVecMatrix (utils/MTUtils.scala:75-94)."""
    return SparseVecMatrix.random(seed, rows, cols, density=density, mesh=mesh, **kw)


def zeros_den_vec_matrix(rows: int, cols: int, mesh=None):
    return DenseVecMatrix.zeros(rows, cols, mesh=mesh)


def ones_den_vec_matrix(rows: int, cols: int, mesh=None):
    return DenseVecMatrix.ones(rows, cols, mesh=mesh)


def ones_dis_vector(length: int, mesh=None):
    return DistributedVector.ones(length, mesh=mesh)


def array_to_matrix(arr, kind: str = "dense_vec", mesh=None):
    """MTUtils array→matrix converters (utils/MTUtils.scala:402-438)."""
    arr = np.asarray(arr)
    if kind in ("dense_vec", "row"):
        return DenseVecMatrix.from_array(arr, mesh)
    if kind in ("block",):
        return BlockMatrix.from_array(arr, mesh)
    raise ValueError(f"unknown matrix kind: {kind}")


def matrix_to_array(mat) -> np.ndarray:
    return mat.to_numpy()


def repeat_by_row(mat, times: int):
    """MTUtils.repeatByRow (utils/MTUtils.scala:446-469)."""
    return mat.repeat_by_row(times)


def repeat_by_column(mat, times: int):
    """MTUtils.repeatByColumn (utils/MTUtils.scala:471-491)."""
    return mat.repeat_by_column(times)
