"""Deterministic fault injection for chaos-testing the recovery subsystem.

The reference never tests its fault tolerance — Spark's lineage recomputation
is assumed to work (SURVEY.md §5.3). The rebuild's explicit checkpoint-restart
machinery (utils/failure.py, io/checkpoint.py) is only trustworthy if it is
*exercised* against the failures it exists for, so this module provides a
registry of named fault points wired into the IO and training paths:

==================  =========================================================
point               fires from
==================  =========================================================
``ckpt.write``      :func:`~marlin_tpu.io.checkpoint.save_checkpoint` entry
                    and each payload-file write (ctx carries ``path``)
``ckpt.manifest``   just before an integrity/shard manifest write
``fs.open``         :func:`~marlin_tpu.io.fs.open_path` (every open; write
                    handles additionally pass through :func:`wrap_file`)
``fs.list``         :func:`~marlin_tpu.io.fs.list_names`
``step.run``        :class:`~marlin_tpu.utils.failure.ResilientLoop` before
                    each step (raise/delay) and on each metric (mutation)
``device.probe``    each per-device probe in
                    :func:`~marlin_tpu.utils.failure.heartbeat`
``prefetch.produce``
                    :class:`~marlin_tpu.parallel.prefetch.ChunkPrefetcher`
                    before each source-chunk read (ctx carries
                    ``path="chunk-<i>"`` so ``match`` can target one chunk)
``dataplane.read``  :meth:`~marlin_tpu.io.chunkstore.ChunkStore.read_rows`
                    before each native window read (ctx carries
                    ``path="<store name>@<row>"`` and ``index=<row>`` so
                    ``match`` can target one window) — torn chunk / bad
                    checksum / short mmap chaos for the data plane
``serve.enqueue``   :meth:`~marlin_tpu.serving.engine.ServeEngine.submit`
                    entry (ctx carries ``path=<rid>``) — a raise here
                    surfaces to the submitting caller
``serve.step``      the serving worker loop, just before each gang batch
                    launch / each row-level slot prefill (ctx carries
                    ``path="bucket-<P>x<steps>"``) — a raise fails that
                    batch's / that admission's requests with ``error``
                    Results; the engine keeps serving
``serve.prefill``   the paged scheduler, just before each bounded prefill
                    CHUNK (ctx carries ``path="bucket-<P>x<steps>"``) — a
                    raise fails only the rows prefilling in that chunk;
                    already-decoded rows and queued requests keep serving
``serve.decode_step``
                    the row-level scheduler, just before each single-token
                    decode step over a bucket's KV slab (ctx carries
                    ``path="bucket-<P>x<steps>"``) — a raise fails only
                    that step's live rows with ``error`` Results and leaves
                    the slot pool consistent; queued requests keep serving
``serve.worker_crash``
                    the serving worker loop, once per iteration OUTSIDE the
                    per-batch/per-step failure envelopes (ctx carries
                    ``path=<worker thread name>``) — a raise kills the whole
                    worker thread, the failure class
                    :class:`~marlin_tpu.serving.supervisor.Supervisor`
                    exists to recover from (unsupervised engines fail all
                    held requests with ``error`` Results, as before)
``serve.router_route``
                    :meth:`~marlin_tpu.serving.router.Router.submit`, once
                    per replica attempt (ctx carries ``path="replica-<i>"``)
                    — a raise marks that replica failed for this request
                    and the router fails over to the next candidate
``serve.migrate``   cross-replica KV migration, once per leg (ctx carries
                    ``path="export:<rid>@<src>"`` per exported row,
                    ``path="import@<target>"`` per adopted blob,
                    ``path="adopt:<rid>@<target>"`` per row bind, and
                    ``path="warm@<target>"`` per cache-warm import) — a
                    raise degrades that leg to the PR 7 retry fallback:
                    the affected rows become fresh-attempt twins, imported
                    pages are released, exactly-once delivery holds
==================  =========================================================

Behaviors are :class:`Fault` subclasses — :class:`RaiseFault` (raise once /
N times / forever), :class:`DelayFault` (latency), :class:`TornWriteFault`
(a write handle that stops persisting after N bytes, simulating a crash
mid-write), :class:`MutateFault` (e.g. NaN into a step's metric) — optionally
gated by a seeded :class:`Schedule` so probabilistic chaos runs are exactly
reproducible.

Faults auto-deregister once their budget is consumed; tests should still use
:func:`injected` (a context manager) or :func:`clear` so nothing leaks across
tests — the suite's conftest asserts the registry is empty after every test.

Everything here is stdlib-only and safe to import from the IO layer.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, Iterator

__all__ = [
    "KNOWN_POINTS", "FaultInjected", "Schedule", "Fault", "RaiseFault",
    "DelayFault", "TornWriteFault", "MutateFault", "inject", "clear",
    "active", "injected", "fire", "wrap_file", "mutate",
]

KNOWN_POINTS = frozenset({
    "ckpt.write", "ckpt.manifest", "fs.open", "fs.list", "step.run",
    "device.probe", "prefetch.produce", "dataplane.read", "serve.enqueue",
    "serve.step", "serve.prefill", "serve.decode_step", "serve.worker_crash",
    "serve.router_route", "serve.migrate", "serve.fleet",
    "serve.program_step",
})


class FaultInjected(RuntimeError):
    """Default exception raised by injected faults."""


class Schedule:
    """Seeded, reproducible firing schedule.

    Decides, per *arrival* at the fault point, whether the fault triggers:

    - ``Schedule(fire_on=[0, 2])`` — fire on the 1st and 3rd arrivals only.
    - ``Schedule(seed=7, rate=0.3)`` — fire each arrival with probability 0.3,
      drawn from ``random.Random(7)`` so two schedules with the same seed
      produce the identical firing pattern.
    """

    def __init__(self, fire_on=None, seed: int | None = None,
                 rate: float | None = None):
        if fire_on is None and rate is None:
            raise ValueError("Schedule needs fire_on=... or seed=/rate=...")
        self.fire_on = None if fire_on is None else frozenset(fire_on)
        self.rate = rate
        self._rng = random.Random(seed)
        self.arrivals = 0

    def should_fire(self) -> bool:
        i = self.arrivals
        self.arrivals += 1
        if self.fire_on is not None:
            return i in self.fire_on
        return self._rng.random() < self.rate


class Fault:
    """One injected behavior at one point.

    ``times`` bounds how often it triggers (-1 = unbounded); ``match`` gates
    on a substring of the context's ``path`` (file path, device string, …);
    ``schedule`` gates on a :class:`Schedule`. A fault whose budget is spent
    auto-deregisters, so a consumed fault never leaks into the next test.
    """

    #: which dispatch consumes this fault: "fire" (raise/delay at the point),
    #: "wrap" (wrap a writable file handle), "mutate" (transform a value).
    kind = "fire"

    def __init__(self, times: int = 1, match: str | None = None,
                 schedule: Schedule | None = None):
        self.times = times
        self.match = match
        self.schedule = schedule
        self.fired = 0

    def exhausted(self) -> bool:
        return self.times >= 0 and self.fired >= self.times

    def applies(self, ctx: dict) -> bool:
        if self.exhausted():
            return False
        if self.match is not None and self.match not in str(ctx.get("path", "")):
            return False
        if self.schedule is not None and not self.schedule.should_fire():
            return False
        return True

    # behavior hooks — exactly one is meaningful per `kind`
    def on_fire(self, point: str, ctx: dict) -> None:
        pass

    def wrap(self, f, ctx: dict):
        return f

    def mutate_value(self, value):
        return value

    def __repr__(self):
        return (f"{type(self).__name__}(times={self.times}, fired={self.fired}"
                + (f", match={self.match!r}" if self.match else "") + ")")


class RaiseFault(Fault):
    """Raise an exception at the point. ``exc`` may be an exception class, a
    zero-arg factory, or an instance (re-raised each time)."""

    def __init__(self, exc: Any = FaultInjected, **kw):
        super().__init__(**kw)
        self.exc = exc

    def on_fire(self, point, ctx):
        e = self.exc
        if isinstance(e, type) and issubclass(e, BaseException):
            e = e(f"injected fault at {point} (ctx={ctx})")
        elif callable(e) and not isinstance(e, BaseException):
            e = e()
        raise e


class DelayFault(Fault):
    """Sleep ``seconds`` at the point — a slow device / laggy filesystem."""

    def __init__(self, seconds: float, sleep: Callable[[float], None] = time.sleep,
                 **kw):
        super().__init__(**kw)
        self.seconds = seconds
        self._sleep = sleep

    def on_fire(self, point, ctx):
        self._sleep(self.seconds)


class _TornFile:
    """A write handle that stops persisting after ``keep`` bytes. The bytes
    that did land are flushed (a real crash leaves its durable prefix behind);
    with ``then_raise`` the crossing write raises, simulating the process
    dying mid-write rather than silently truncating."""

    def __init__(self, f, keep: int, then_raise: bool):
        self._f = f
        self._left = keep
        self._then_raise = then_raise

    def write(self, data):
        n = len(data)
        if n <= self._left:
            self._left -= n
            return self._f.write(data)
        kept = data[: self._left]
        self._left = 0
        if kept:
            self._f.write(kept)
        try:
            self._f.flush()
        except Exception:
            pass
        if self._then_raise:
            raise FaultInjected(
                f"torn write: stream truncated {n - len(kept)} bytes short")
        return n

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return self._f.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._f, name)


class TornWriteFault(Fault):
    """Truncate a written file to ``keep_bytes`` — the canonical torn-write /
    kill-mid-save failure. Applied to write handles via :func:`wrap_file`."""

    kind = "wrap"

    def __init__(self, keep_bytes: int, then_raise: bool = True, **kw):
        super().__init__(**kw)
        self.keep_bytes = keep_bytes
        self.then_raise = then_raise

    def wrap(self, f, ctx):
        return _TornFile(f, self.keep_bytes, self.then_raise)


class MutateFault(Fault):
    """Replace a value flowing past the point — e.g. NaN into a step metric.
    ``value`` may be a constant or a one-arg callable of the original."""

    kind = "mutate"

    def __init__(self, value: Any = float("nan"), **kw):
        super().__init__(**kw)
        self.value = value

    def mutate_value(self, old):
        return self.value(old) if callable(self.value) else self.value


_LOCK = threading.Lock()
_REGISTRY: dict[str, list[Fault]] = {}


def inject(point: str, fault: Fault) -> Fault:
    """Register ``fault`` at ``point``; returns the fault (for assertions on
    ``.fired``). Unknown point names are rejected — a typo'd point would
    silently never fire."""
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown fault point {point!r} (known: "
                         f"{sorted(KNOWN_POINTS)})")
    with _LOCK:
        _REGISTRY.setdefault(point, []).append(fault)
    return fault


def clear(point: str | None = None) -> None:
    """Drop every registered fault (or just ``point``'s)."""
    with _LOCK:
        if point is None:
            _REGISTRY.clear()
        else:
            _REGISTRY.pop(point, None)


def active() -> dict[str, list[Fault]]:
    """Registered, not-yet-exhausted faults by point (exhausted faults
    auto-deregister at consumption, so anything here is still pending)."""
    with _LOCK:
        out = {p: [f for f in fl if not f.exhausted()]
               for p, fl in _REGISTRY.items()}
    return {p: fl for p, fl in out.items() if fl}


@contextlib.contextmanager
def injected(point: str, fault: Fault) -> Iterator[Fault]:
    """Scoped injection: registers on entry, removes on exit regardless of
    how many times it fired — the leak-proof way to inject in tests."""
    inject(point, fault)
    try:
        yield fault
    finally:
        with _LOCK:
            fl = _REGISTRY.get(point)
            if fl is not None and fault in fl:
                fl.remove(fault)
            if not fl:
                _REGISTRY.pop(point, None)


def _consume(point: str, kind: str, ctx: dict) -> list[Fault]:
    """The faults at ``point`` of ``kind`` that trigger for this arrival;
    bookkeeping (fired counts, auto-deregistration) happens here under the
    lock, the behavior itself runs outside it (it may sleep or raise)."""
    with _LOCK:
        fl = _REGISTRY.get(point)
        if not fl:
            return []
        hits = []
        for f in list(fl):
            if f.kind != kind or not f.applies(ctx):
                continue
            f.fired += 1
            hits.append(f)
            if f.exhausted():
                fl.remove(f)
        if not fl:
            _REGISTRY.pop(point, None)
    return hits


def fire(point: str, **ctx) -> None:
    """Trigger raise/delay faults at ``point``. No-ops in nanoseconds when
    nothing is registered — safe on hot IO paths."""
    if not _REGISTRY:
        return
    for f in _consume(point, "fire", ctx):
        f.on_fire(point, ctx)


def wrap_file(point: str, fobj, **ctx):
    """Pass a writable handle through any torn-write faults at ``point``."""
    if not _REGISTRY:
        return fobj
    for f in _consume(point, "wrap", ctx):
        fobj = f.wrap(fobj, ctx)
    return fobj


def mutate(point: str, value, **ctx):
    """Pass a value through any mutation faults at ``point``."""
    if not _REGISTRY:
        return value
    for f in _consume(point, "mutate", ctx):
        value = f.mutate_value(value)
    return value
