"""Failure detection and checkpoint-based elastic recovery.

The reference has *no* failure handling of its own — Spark's lineage
recomputation and task retry cover it invisibly (SURVEY.md §5.3). SPMD JAX has
no lineage: a device failure kills the step and the state with it. The rebuild
therefore makes recovery an explicit subsystem:

- :class:`ResilientLoop` — wraps an iterative workload's step function with
  periodic checkpointing, failure detection (exceptions from the runtime,
  non-finite losses), and resume-from-last-checkpoint retry with a bounded
  retry budget. This is the checkpoint-restart answer to Spark's
  recompute-from-lineage, stated as such.
- :func:`heartbeat` — a lightweight liveness probe: runs a trivial jitted op
  on every device and reports per-device latency; a hung/failed device shows
  up as a timeout instead of a silent stall.

Both are chaos-tested through :mod:`marlin_tpu.utils.faults`: the ``step.run``
point fires before every step (and can mutate its metric — NaN injection),
``device.probe`` fires inside every heartbeat probe, and the checkpoint IO
underneath carries its own points. Recovery walks *backward* through committed
checkpoint generations — a torn or corrupt latest generation
(:class:`~marlin_tpu.io.checkpoint.CheckpointCorruptError`) falls back to the
newest one that still verifies instead of killing the run.
"""

from __future__ import annotations

import time
import zipfile
from typing import Any, Callable

import jax
import jax.numpy as jnp

from . import faults as _faults
from .tracing import get_default_event_log

__all__ = ["ResilientLoop", "heartbeat", "NonFiniteLossError"]


class NonFiniteLossError(RuntimeError):
    """Raised when a step's loss/metric goes NaN/Inf — numeric failure is a
    failure mode too, and restarting from the last good checkpoint is the
    same remedy as a device loss."""


#: What a generation may raise while being loaded that means "this generation
#: is unusable, try an older one" rather than "abort": integrity failures
#: (CheckpointCorruptError), vanished files (FileNotFoundError/OSError),
#: truncated npy/npz payloads (ValueError/EOFError/BadZipFile), corrupt JSON
#: manifests (JSONDecodeError is a ValueError), and mangled structures
#: (KeyError). Broader than the old (FileNotFoundError, OSError) pair, which
#: let a corrupt manifest or truncated array escape the recovery path
#: entirely.
def _resume_errors():
    from ..io.checkpoint import CheckpointCorruptError

    return (CheckpointCorruptError, FileNotFoundError, OSError, EOFError,
            ValueError, KeyError, zipfile.BadZipFile)


def heartbeat(timeout_s: float = 30.0, raise_on_failure: bool = True) -> dict:
    """Probe every visible device with a tiny computation; returns
    {device_str: latency_s}, with ``float('inf')`` marking devices that
    missed the deadline or raised (a dead device usually *errors* from the
    runtime rather than hangs — those exceptions ride on the returned
    mapping as ``.errors``). All probes launch concurrently and every device
    is waited on against one shared deadline, so a single wedged device
    neither serializes the sweep nor hides the status of the devices behind
    it. With ``raise_on_failure`` a TimeoutError naming *all* failed devices
    (and their errors) is raised after the full sweep; the per-device map
    rides on the exception as ``.results``. A truly hung
    ``block_until_ready`` thread cannot be killed from Python; it is left as
    a daemon and never re-joined, so a stuck probe cannot wedge later
    heartbeats.

    The ``device.probe`` fault point fires inside each probe (ctx ``path`` is
    the device string, so a fault can target one device): an injected raise
    lands in ``.errors``, an injected delay past the deadline shows up as a
    timeout — exactly how a real dead vs. wedged device presents."""
    import threading

    results: dict[str, float] = {}
    errors: dict[str, Exception] = {}
    lock = threading.Lock()
    t0 = time.perf_counter()

    def probe(d):
        try:
            _faults.fire("device.probe", path=str(d), device=str(d))
            x = jax.device_put(jnp.ones(()), d)
            jax.block_until_ready(x + 1.0)
            with lock:
                results[str(d)] = time.perf_counter() - t0
        except Exception as e:  # a dead device ERRORS rather than hangs —
            with lock:          # record it instead of mislabeling as timeout
                errors[str(d)] = e

    threads = [threading.Thread(target=probe, args=(d,), daemon=True)
               for d in jax.devices()]
    for th in threads:
        th.start()
    deadline = t0 + timeout_s
    for th in threads:
        th.join(max(0.0, deadline - time.perf_counter()))

    class _Results(dict):
        pass

    out = _Results({str(d): results.get(str(d), float("inf"))
                    for d in jax.devices()})
    out.errors = dict(errors)
    failed = sorted(k for k, v in out.items() if v == float("inf"))
    if failed and raise_on_failure:
        detail = "; ".join(f"{k}: {errors[k]!r}" for k in failed if k in errors)
        err = TimeoutError(
            f"{len(failed)}/{len(out)} device heartbeats failed after "
            f"{timeout_s:.1f}s: {', '.join(failed)}"
            + (f" (device errors: {detail})" if detail else ""))
        err.results = out
        raise err
    return out


class ResilientLoop:
    """Run ``state, metric = step_fn(state, i)`` for ``iterations`` steps with
    checkpoint/resume fault tolerance.

    On any runtime exception or non-finite metric — from the step itself *or*
    from the checkpoint save (a transient IO failure must not kill a run) —
    the loop restores the newest checkpoint generation that verifies and
    continues from there, up to ``max_retries`` times. A fresh run resumes
    automatically if ``checkpoint_dir`` already holds a committed checkpoint
    (crash-restart of the whole process); a torn or corrupt latest generation
    falls back to the one before it.

    ``keep`` bounds on-disk retention to that many committed generations
    (the fall-back depth); ``event_log`` (or the process default,
    :func:`~marlin_tpu.utils.tracing.set_default_event_log`) receives
    ``resume``/``resume_skip``/``step_failure`` events for post-mortems.
    """

    def __init__(
        self,
        step_fn: Callable[[Any, int], tuple[Any, float]],
        checkpoint_dir: str,
        checkpoint_every: int = 50,
        max_retries: int = 3,
        check_finite: bool = True,
        keep: int = 3,
        event_log=None,
    ):
        self.step_fn = step_fn
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_retries = max_retries
        self.check_finite = check_finite
        self.keep = keep
        self.event_log = event_log
        self.retries = 0

    def _log(self, kind: str, **fields) -> None:
        log = self.event_log or get_default_event_log()
        if log is not None:
            log.event(kind, **fields)

    def _try_resume(self, state_template):
        """Restore the newest checkpoint generation that loads and verifies,
        walking backward past torn/corrupt ones; with none restorable,
        restart from the pristine initial state (never from a
        possibly-corrupt current one)."""
        from ..io.checkpoint import list_generations, load_checkpoint

        committed = list_generations(self.checkpoint_dir)
        if not committed:
            uncommitted = list_generations(self.checkpoint_dir,
                                           committed_only=False)
            if uncommitted:
                # generation directories exist but none carries a COMMITTED
                # marker: either torn writes, or checkpoints written before
                # the atomic-commit protocol (docs/robustness.md explains
                # the one-time migration) — restarting fresh must not be
                # silent about either
                import warnings

                warnings.warn(
                    f"ResilientLoop: {self.checkpoint_dir} holds generation "
                    f"directories {uncommitted} but none is committed "
                    "(torn writes, or pre-protocol checkpoints needing a "
                    "one-time COMMITTED marker — see docs/robustness.md); "
                    "restarting from the initial state",
                    RuntimeWarning, stacklevel=3)
        skipped = []
        for step in reversed(committed):
            try:
                state, s = load_checkpoint(state_template,
                                           self.checkpoint_dir, step=step)
            except _resume_errors() as e:
                self._log("resume_skip", step=step, error=repr(e))
                skipped.append((step, e))
                continue
            self._log("resume", step=s)
            return state, s
        if skipped:
            # checkpoints existed but NONE restored — restarting from scratch
            # is the contract, but silently doing so would mask e.g. a
            # template/configuration mismatch, so say it loudly
            import warnings

            warnings.warn(
                f"ResilientLoop: no generation under {self.checkpoint_dir} "
                f"was restorable — restarting from the initial state. "
                "Skipped: "
                + "; ".join(f"step {s}: {e!r}" for s, e in skipped),
                RuntimeWarning, stacklevel=3)
        return self._initial, 0

    def run(self, state, iterations: int):
        from ..io.checkpoint import save_checkpoint

        self._initial = state
        state, start = self._try_resume(state)
        i = start
        metrics = []
        while i < iterations:
            try:
                _faults.fire("step.run", step=i)
                new_state, metric = self.step_fn(state, i)
                m = float(metric)
                m = _faults.mutate("step.run", m, step=i)
                if self.check_finite and not (m == m and abs(m) != float("inf")):
                    raise NonFiniteLossError(f"non-finite metric {m} at step {i}")
                state = new_state
                metrics.append(m)
                i += 1
                if i % self.checkpoint_every == 0 or i == iterations:
                    save_checkpoint(state, self.checkpoint_dir, i,
                                    keep=self.keep)
            except Exception as e:
                self.retries += 1
                self._log("step_failure", step=i, retry=self.retries,
                          error=repr(e))
                if self.retries > self.max_retries:
                    raise
                state, i = self._try_resume(state)
                # drop metrics for the steps being replayed so the returned
                # history has exactly one entry per step
                del metrics[max(0, i - start):]
                continue
        return state, metrics
