"""Failure detection and checkpoint-based elastic recovery.

The reference has *no* failure handling of its own — Spark's lineage
recomputation and task retry cover it invisibly (SURVEY.md §5.3). SPMD JAX has
no lineage: a device failure kills the step and the state with it. The rebuild
therefore makes recovery an explicit subsystem:

- :class:`ResilientLoop` — wraps an iterative workload's step function with
  periodic checkpointing, failure detection (exceptions from the runtime,
  non-finite losses), and resume-from-last-checkpoint retry with a bounded
  retry budget. This is the checkpoint-restart answer to Spark's
  recompute-from-lineage, stated as such.
- :func:`heartbeat` — a lightweight liveness probe: runs a trivial jitted op
  on every device and reports per-device latency; a hung/failed device shows
  up as a timeout instead of a silent stall.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..io.checkpoint import load_checkpoint, save_checkpoint

__all__ = ["ResilientLoop", "heartbeat", "NonFiniteLossError"]


class NonFiniteLossError(RuntimeError):
    """Raised when a step's loss/metric goes NaN/Inf — numeric failure is a
    failure mode too, and restarting from the last good checkpoint is the
    same remedy as a device loss."""


def heartbeat(timeout_s: float = 30.0, raise_on_failure: bool = True) -> dict:
    """Probe every visible device with a tiny computation; returns
    {device_str: latency_s}, with ``float('inf')`` marking devices that
    missed the deadline or raised (a dead device usually *errors* from the
    runtime rather than hanging — those exceptions ride on the returned
    mapping as ``.errors``). All probes launch concurrently and every device
    is waited on against one shared deadline, so a single wedged device
    neither serializes the sweep nor hides the status of the devices behind
    it. With ``raise_on_failure`` a TimeoutError naming *all* failed devices
    (and their errors) is raised after the full sweep; the per-device map
    rides on the exception as ``.results``. A truly hung
    ``block_until_ready`` thread cannot be killed from Python; it is left as
    a daemon and never re-joined, so a stuck probe cannot wedge later
    heartbeats."""
    import threading

    results: dict[str, float] = {}
    errors: dict[str, Exception] = {}
    lock = threading.Lock()
    t0 = time.perf_counter()

    def probe(d):
        try:
            x = jax.device_put(jnp.ones(()), d)
            jax.block_until_ready(x + 1.0)
            with lock:
                results[str(d)] = time.perf_counter() - t0
        except Exception as e:  # a dead device ERRORS rather than hangs —
            with lock:          # record it instead of mislabeling as timeout
                errors[str(d)] = e

    threads = [threading.Thread(target=probe, args=(d,), daemon=True)
               for d in jax.devices()]
    for th in threads:
        th.start()
    deadline = t0 + timeout_s
    for th in threads:
        th.join(max(0.0, deadline - time.perf_counter()))

    class _Results(dict):
        pass

    out = _Results({str(d): results.get(str(d), float("inf"))
                    for d in jax.devices()})
    out.errors = dict(errors)
    failed = sorted(k for k, v in out.items() if v == float("inf"))
    if failed and raise_on_failure:
        detail = "; ".join(f"{k}: {errors[k]!r}" for k in failed if k in errors)
        err = TimeoutError(
            f"{len(failed)}/{len(out)} device heartbeats failed after "
            f"{timeout_s:.1f}s: {', '.join(failed)}"
            + (f" (device errors: {detail})" if detail else ""))
        err.results = out
        raise err
    return out


class ResilientLoop:
    """Run ``state, metric = step_fn(state, i)`` for ``iterations`` steps with
    checkpoint/resume fault tolerance.

    On any runtime exception or non-finite metric, the loop restores the most
    recent checkpoint and continues from there, up to ``max_retries`` times.
    A fresh run resumes automatically if ``checkpoint_dir`` already holds a
    checkpoint (crash-restart of the whole process).
    """

    def __init__(
        self,
        step_fn: Callable[[Any, int], tuple[Any, float]],
        checkpoint_dir: str,
        checkpoint_every: int = 50,
        max_retries: int = 3,
        check_finite: bool = True,
    ):
        self.step_fn = step_fn
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_retries = max_retries
        self.check_finite = check_finite
        self.retries = 0

    def _try_resume(self, state_template):
        """Restore the latest checkpoint; with none on disk, restart from the
        pristine initial state (never from a possibly-corrupt current one)."""
        try:
            return load_checkpoint(state_template, self.checkpoint_dir)
        except (FileNotFoundError, OSError):
            return self._initial, 0

    def run(self, state, iterations: int):
        self._initial = state
        state, start = self._try_resume(state)
        i = start
        metrics = []
        while i < iterations:
            try:
                new_state, metric = self.step_fn(state, i)
                m = float(metric)
                if self.check_finite and not (m == m and abs(m) != float("inf")):
                    raise NonFiniteLossError(f"non-finite metric {m} at step {i}")
            except Exception:
                self.retries += 1
                if self.retries > self.max_retries:
                    raise
                state, i = self._try_resume(state)
                # drop metrics for the steps being replayed so the returned
                # history has exactly one entry per step
                del metrics[max(0, i - start):]
                continue
            state = new_state
            metrics.append(m)
            i += 1
            if i % self.checkpoint_every == 0 or i == iterations:
                save_checkpoint(state, self.checkpoint_dir, i)
        return state, metrics
