"""Failure detection and checkpoint-based elastic recovery.

The reference has *no* failure handling of its own — Spark's lineage
recomputation and task retry cover it invisibly (SURVEY.md §5.3). SPMD JAX has
no lineage: a device failure kills the step and the state with it. The rebuild
therefore makes recovery an explicit subsystem:

- :class:`ResilientLoop` — wraps an iterative workload's step function with
  periodic checkpointing, failure detection (exceptions from the runtime,
  non-finite losses), and resume-from-last-checkpoint retry with a bounded
  retry budget. This is the checkpoint-restart answer to Spark's
  recompute-from-lineage, stated as such.
- :func:`heartbeat` — a lightweight liveness probe: runs a trivial jitted op
  on every device and reports per-device latency; a hung/failed device shows
  up as a timeout instead of a silent stall.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..io.checkpoint import load_checkpoint, save_checkpoint

__all__ = ["ResilientLoop", "heartbeat", "NonFiniteLossError"]


class NonFiniteLossError(RuntimeError):
    """Raised when a step's loss/metric goes NaN/Inf — numeric failure is a
    failure mode too, and restarting from the last good checkpoint is the
    same remedy as a device loss."""


def heartbeat(timeout_s: float = 30.0) -> dict:
    """Probe every visible device with a tiny computation; returns
    {device_str: latency_s}. The probe runs in a watchdog thread so a truly
    hung device surfaces as a TimeoutError instead of hanging the caller —
    ``block_until_ready`` alone would block forever on a wedged device."""
    import threading

    out = {}
    for dev in jax.devices():
        result: dict = {}

        def probe(d=dev, r=result):
            x = jax.device_put(jnp.ones(()), d)
            jax.block_until_ready(x + 1.0)
            r["ok"] = True

        t0 = time.perf_counter()
        th = threading.Thread(target=probe, daemon=True)
        th.start()
        th.join(timeout_s)
        dt = time.perf_counter() - t0
        if th.is_alive() or "ok" not in result:
            raise TimeoutError(f"device {dev} heartbeat timed out after {dt:.1f}s")
        out[str(dev)] = dt
    return out


class ResilientLoop:
    """Run ``state, metric = step_fn(state, i)`` for ``iterations`` steps with
    checkpoint/resume fault tolerance.

    On any runtime exception or non-finite metric, the loop restores the most
    recent checkpoint and continues from there, up to ``max_retries`` times.
    A fresh run resumes automatically if ``checkpoint_dir`` already holds a
    checkpoint (crash-restart of the whole process).
    """

    def __init__(
        self,
        step_fn: Callable[[Any, int], tuple[Any, float]],
        checkpoint_dir: str,
        checkpoint_every: int = 50,
        max_retries: int = 3,
        check_finite: bool = True,
    ):
        self.step_fn = step_fn
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_retries = max_retries
        self.check_finite = check_finite
        self.retries = 0

    def _try_resume(self, state_template):
        """Restore the latest checkpoint; with none on disk, restart from the
        pristine initial state (never from a possibly-corrupt current one)."""
        try:
            return load_checkpoint(state_template, self.checkpoint_dir)
        except (FileNotFoundError, OSError):
            return self._initial, 0

    def run(self, state, iterations: int):
        self._initial = state
        state, start = self._try_resume(state)
        i = start
        metrics = []
        while i < iterations:
            try:
                new_state, metric = self.step_fn(state, i)
                m = float(metric)
                if self.check_finite and not (m == m and abs(m) != float("inf")):
                    raise NonFiniteLossError(f"non-finite metric {m} at step {i}")
            except Exception:
                self.retries += 1
                if self.retries > self.max_retries:
                    raise
                state, i = self._try_resume(state)
                # drop metrics for the steps being replayed so the returned
                # history has exactly one entry per step
                del metrics[max(0, i - start):]
                continue
            state = new_state
            metrics.append(m)
            i += 1
            if i % self.checkpoint_every == 0 or i == iterations:
                save_checkpoint(state, self.checkpoint_dir, i)
        return state, metrics
