"""Timing and profiling hooks.

The reference's only observability is ad-hoc ``System.currentTimeMillis`` deltas
in examples and factorization loops plus ``MTUtils.evaluate`` to force lazy RDDs
(SURVEY.md §5.1 calls this a gap worth exceeding). Here:

- :func:`evaluate` — force-materialize (block_until_ready) without transferring,
  the analog of ``MTUtils.evaluate`` (utils/MTUtils.scala:218-220). Essential
  for honest timing under JAX's async dispatch.
- :func:`timer` — wall-clock context manager that prints millis like the
  examples do (e.g. examples/BLAS3.scala:34-56).
- :class:`StepTimer` — per-iteration timing hook for training loops.
- :class:`StageTimes` — per-stage wall-clock aggregation for pipelined
  operations (the streaming prefetch path's produce/transfer/compute/drain
  split), thread-safe because producer threads and the consumer record into
  the same instance.
- :func:`trace` — context manager around ``jax.profiler`` emitting a TensorBoard
  trace (XLA-level, per-op on TPU); no reference equivalent.
"""

from __future__ import annotations

import contextlib
import threading
import time

import jax


def evaluate(*xs):
    """Block until the given arrays (or matrices) are materialized on device;
    returns them. Accepts marlin matrices, jax arrays, or pytrees.

    Beyond ``block_until_ready`` this fetches one scalar element per array:
    some remote-attached runtimes (e.g. relayed TPU tunnels) acknowledge
    ``block_until_ready`` before execution finishes, and the data-dependent
    4-byte fetch forces a true completion barrier — without it, timing loops
    measure dispatch latency instead of compute."""
    for x in xs:
        data = getattr(x, "data", x)
        for leaf in jax.tree.leaves(data):
            jax.block_until_ready(leaf)
            if hasattr(leaf, "ndim") and getattr(leaf, "size", 0) > 0:
                # 4-byte data-dependent fetch of one element (no relayout)
                jax.device_get(leaf[(0,) * leaf.ndim])
    return xs[0] if len(xs) == 1 else xs


@contextlib.contextmanager
def timer(label: str = "", results: list | None = None, quiet: bool = False):
    """Wall-clock the body, print millis like the reference's examples do —
    and, when a default :class:`~marlin_tpu.utils.tracing.EventLog` is
    installed, land the same timing there as a ``kind="timer"`` record
    (with the active trace context), so example/bench timings are part of
    the post-mortem stream instead of scrollback-only."""
    t0 = time.perf_counter()
    yield
    dt_ms = (time.perf_counter() - t0) * 1000.0
    if results is not None:
        results.append(dt_ms)
    from .tracing import get_default_event_log

    log = get_default_event_log()
    if log is not None:
        log.event("timer", label=label or "elapsed",
                  seconds=round(dt_ms / 1e3, 6))
    if not quiet:
        print(f"{label or 'elapsed'}: {dt_ms:.1f} ms")


class StepTimer:
    """Records per-step wall-clock; use around the body of an iterative loop."""

    def __init__(self):
        self.times_ms: list[float] = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, sync=None):
        if sync is not None:
            evaluate(sync)
        self.times_ms.append((time.perf_counter() - self._t0) * 1000.0)

    @property
    def mean_ms(self) -> float:
        return sum(self.times_ms) / max(1, len(self.times_ms))

    def summary(self) -> str:
        if not self.times_ms:
            return "no steps recorded"
        return (
            f"{len(self.times_ms)} steps, mean {self.mean_ms:.1f} ms, "
            f"min {min(self.times_ms):.1f} ms, max {max(self.times_ms):.1f} ms"
        )


_stage_families = None  # lazy (registry import stays off the module path)


def _stage_metrics():
    global _stage_families
    if _stage_families is None:
        from ..obs.metrics import get_registry

        reg = get_registry()
        _stage_families = (
            reg.counter("marlin_stage_seconds_total",
                        "Wall-clock accumulated per pipeline stage "
                        "(StageTimes: produce/transfer/stall/compute/drain)",
                        labelnames=("stage",)),
            reg.counter("marlin_stage_events_total",
                        "StageTimes samples per pipeline stage",
                        labelnames=("stage",)),
        )
    return _stage_families


class StageTimes:
    """Aggregate wall-clock by named stage across threads.

    The streaming prefetch pipeline records ``produce`` (host read + dtype
    conversion), ``transfer`` (``jax.device_put`` dispatch), ``stall`` (time
    the consumer waited on the queue — the *un-overlapped* producer latency,
    ~0 when prefetch is keeping up), ``compute`` (device dispatch) and
    ``drain`` (blocking D2H fetches). Producer threads and the consumer write
    concurrently, hence the lock. Every sample also lands in the process
    metrics registry (``marlin_stage_seconds_total{stage=...}``), so stage
    budgets are scrapeable, not just printable."""

    def __init__(self):
        self.seconds: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self.seconds[stage] = self.seconds.get(stage, 0.0) + seconds
            self.counts[stage] = self.counts.get(stage, 0) + 1
        secs, events = _stage_metrics()
        secs.labels(stage=stage).inc(seconds)
        events.labels(stage=stage).inc()

    @contextlib.contextmanager
    def timed(self, stage: str):
        t0 = time.perf_counter()
        yield
        self.add(stage, time.perf_counter() - t0)

    def summary(self) -> str:
        with self._lock:
            if not self.seconds:
                return "no stages recorded"
            return ", ".join(
                f"{k} {self.seconds[k]:.3f}s/{self.counts[k]}"
                for k in sorted(self.seconds))

    def emit(self, kind: str = "stage_times", log=None, **fields) -> None:
        """Write one summary event to ``log`` (or the process-default
        EventLog); silently no-ops when neither exists."""
        from .tracing import get_default_event_log

        log = log or get_default_event_log()
        if log is None:
            return
        with self._lock:
            secs = {f"{k}_s": round(v, 6) for k, v in self.seconds.items()}
            counts = dict(self.counts)
        log.event(kind, **secs, counts=counts, **fields)


@contextlib.contextmanager
def trace(logdir: str = "/tmp/marlin_tpu_trace"):
    """Emit a jax.profiler trace viewable in TensorBoard/XProf.

    This is the inline, wrap-your-own-code spelling. For a *running*
    process, the same capture is a triggerable service:
    :func:`marlin_tpu.obs.perf.capture_profile` (single-flight, rotating
    size-capped capture dir, ``kind="profile"`` EventLog record), exposed
    as ``POST /debug/profile?seconds=N`` on the obs HTTP server and as a
    SIGUSR2 hook — no code change, no restart."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
