"""AOT compile-only evidence channel: compile for TPU without a chip.

The build/test environment has the TPU *compiler* (libtpu) even when no chip
is reachable, so every TPU claim that is really a claim about what Mosaic/XLA
accepts and schedules can be proven ahead of time:

- Pallas kernels (flash attention fwd/bwd, the BSR manual-DMA kernel) are
  lowered by the real Mosaic compiler — interpret-mode correctness on the CPU
  mesh says nothing about whether Mosaic accepts scalar-prefetch grids,
  ``pl.ANY`` HBM refs or manual ``make_async_copy`` double-buffering; this
  does.
- ``Compiled.memory_analysis()`` of a TPU lowering gives the compiler's HBM
  accounting (argument/output/temp/generated-code bytes) for long-context
  configurations that cannot run on the CPU mesh at all — the predicted-HBM
  column of docs/parallelism.md's budget table.

No reference analog: the reference compiles JVM bytecode and finds out about
memory at runtime (SURVEY.md §5.7 is the rebuild's long-context story).

Usage is deliberately plain ``jax.jit(...).trace(...).lower().compile()`` —
this module only supplies the topology plumbing, so the artifact proven is
the same jitted program the runtime path executes.
"""

from __future__ import annotations

import functools
import os

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["tpu_topology", "topology_mesh", "supports_aot_tpu",
           "trace_lm_train_step", "parse_hbm_oom"]


@functools.lru_cache(maxsize=None)
def tpu_topology(topology_name: str = "v5e:2x2"):
    """A compile-only TPU topology (never touches hardware or the relay).

    Requires libtpu (the compiler) to be importable; raises RuntimeError with
    the underlying cause otherwise — callers that want to skip instead gate on
    :func:`supports_aot_tpu`.

    The probe runs with ``TPU_SKIP_MDS_QUERY=1`` (restored afterwards unless
    the caller already set it): a compile-only topology needs no instance
    metadata, and on hosts without a TPU runtime libtpu's PJRT plugin init
    otherwise blocks the process — GIL held — retrying GCP metadata fetches
    (30 tries per variable), which hangs any caller, including the test
    suite's collection-time skipif gate."""
    from jax.experimental import topologies

    had = "TPU_SKIP_MDS_QUERY" in os.environ
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    try:
        return topologies.get_topology_desc(
            platform="tpu", topology_name=topology_name)
    except Exception as e:  # pragma: no cover - env without libtpu
        raise RuntimeError(
            f"compile-only TPU topology {topology_name!r} unavailable: {e}"
        ) from e
    finally:
        if not had:
            os.environ.pop("TPU_SKIP_MDS_QUERY", None)


def supports_aot_tpu() -> bool:
    try:
        tpu_topology()
        return True
    except RuntimeError:
        return False


def topology_mesh(axis_names: tuple[str, ...], shape: tuple[int, ...],
                  topology_name: str = "v5e:2x2") -> Mesh:
    """A Mesh over compile-only topology devices, for AOT-compiling the same
    sharded programs the runtime builds over real chips."""
    topo = tpu_topology(topology_name)
    n = int(np.prod(shape))
    devs = np.asarray(topo.devices)
    if n > devs.size:
        raise ValueError(
            f"mesh shape {shape} needs {n} devices; topology "
            f"{topology_name!r} has {devs.size}")
    return Mesh(devs[:n].reshape(shape), axis_names)


def trace_lm_train_step(model, seq: int, mesh):
    """Trace the REAL ``lm_train_step`` for AOT compilation: replicated
    ``ShapeDtypeStruct`` args over ``mesh`` for a ``TransformerLM`` at
    ``seq`` tokens — the one arg-plumbing shared by the context planner,
    ``tools/aot_report.py`` and the compile-only tests (callers ``.lower()
    .compile()`` the result, usually under
    ``config_context(pallas_interpret=False)``)."""
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..mesh import ROWS
    from ..models.transformer import lm_train_step

    rep = NamedSharding(mesh, PartitionSpec())
    # expert tensors carry the RUNTIME placement (shard_moe_params shards
    # their leading expert axis over rows) — replicating them here would
    # overstate per-chip expert + Adam memory by the axis size, making the
    # planner's multi-chip MoE evidence diverge from the program that runs
    exp = NamedSharding(mesh, PartitionSpec(ROWS, None, None))
    rows = mesh.shape.get(ROWS, 1)

    def leaf_sharding(path, x):
        in_moe = any(getattr(k, "key", None) == "moe" for k in path)
        if (in_moe and jnp.ndim(x) == 3
                and jnp.shape(x)[0] % max(rows, 1) == 0):
            return exp
        return rep

    def sds(tree):
        return jax.tree_util.tree_map_with_path(
            lambda p, x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                              sharding=leaf_sharding(p, x)),
            tree)

    params = jax.eval_shape(model.init_params)
    opt_state = jax.eval_shape(optax.adam(model.learning_rate).init, params)
    tokens = jax.ShapeDtypeStruct((seq,), jnp.int32, sharding=rep)
    return lm_train_step.trace(
        sds(params), sds(opt_state), tokens, mesh, model.heads, model.attn,
        model.remat, model.precision, model.learning_rate, model.loss_chunk,
        model.compute_dtype, model.mlp_chunk, model.offload_residuals,
        model._moe(), model.moe_aux_weight)


def parse_hbm_oom(exc) -> int | None:
    """Bytes the TPU compiler says it needed, parsed from an over-HBM
    rejection ("Ran out of memory in hbm ... Used X of Y hbm") — None when
    the exception is not that rejection. An OOM'd compile is a *result* (the
    compiler locating the cliff), which is why both the planner and
    aot_report record it instead of crashing."""
    import re

    m = re.search(r"Used ([0-9.]+)([GMK]) of [0-9.]+[GMK] hbm", str(exc))
    if not m:
        return None
    mult = {"K": 1024, "M": 1024 ** 2, "G": 1024 ** 3}[m.group(2)]
    return int(float(m.group(1)) * mult)
