"""Deterministic sharded random matrix generation.

The reference generates data *inside* RDD partitions with per-partition seeds
derived deterministically from a base seed (rdd/RandomRDD.scala:28-45, seeds
hashed via MurmurHash3 in MTUtils.hashSeed, utils/MTUtils.scala:18-21;
generators in utils/RandomDataGenerator.scala: Zeros/Ones/Uniform/Normal/Poisson
plus XORShiftRandom). The TPU-native equivalent is JAX's counter-based
(threefry) RNG, which is *splittable and partitionable*: generating a sharded
array under jit with an output sharding produces each shard on its own device
with no cross-device data movement, and the result is independent of the mesh —
the moral upgrade of "deterministic per-partition seeding".

All factories return a raw ``jax.Array`` with the requested sharding; the
matrix-type factories in ``marlin_tpu.matrix`` wrap these.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from .config import get_config


def ensure_key(seed_or_key) -> jax.Array:
    if isinstance(seed_or_key, (int,)):
        return jax.random.key(seed_or_key)
    return seed_or_key


@functools.partial(jax.jit, static_argnames=("dist", "shape", "dtype", "sharding"))
def _generate(key, dist: str, shape: tuple[int, ...], dtype, sharding, minval, maxval, lam):
    if dist == "uniform":
        x = jax.random.uniform(key, shape, dtype=dtype, minval=minval, maxval=maxval)
    elif dist == "normal":
        x = jax.random.normal(key, shape, dtype=dtype)
    elif dist == "poisson":
        x = jax.random.poisson(key, lam, shape).astype(dtype)
    elif dist == "zeros":
        x = jnp.zeros(shape, dtype)
    elif dist == "ones":
        x = jnp.ones(shape, dtype)
    else:
        raise ValueError(f"unknown distribution: {dist}")
    if sharding is not None:
        x = jax.lax.with_sharding_constraint(x, sharding)
    return x


def random_array(
    seed_or_key,
    shape: tuple[int, ...],
    dist: str = "uniform",
    dtype: Any = None,
    sharding: NamedSharding | None = None,
    minval: float = 0.0,
    maxval: float = 1.0,
    lam: float = 1.0,
) -> jax.Array:
    """Generate an i.i.d. random array, sharded at generation time.

    ``dist`` mirrors the reference's generator set
    (utils/RandomDataGenerator.scala:12-100): ``uniform`` (default, like
    UniformGenerator), ``normal`` (StandardNormalGenerator), ``poisson``
    (PoissonGenerator — the reference pulls in colt just for this), plus
    ``zeros``/``ones`` (ZerosGenerator/OnesGenerator).
    """
    dtype = dtype or get_config().default_dtype
    key = ensure_key(seed_or_key)
    return _generate(key, dist, tuple(shape), dtype, sharding, minval, maxval, lam)
