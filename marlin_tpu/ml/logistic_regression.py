"""Full-batch logistic regression via distributed mat-vec products.

The reference example (examples/LogisticRegression.scala) runs full-batch LR
where each iteration is a distributed matrix-vector product against the
broadcast weight vector, with a custom co-partitioner keeping data and labels
aligned (:21-28). ``DenseVecMatrix.lr`` (DenseVecMatrix.scala:1005-1035) is the
in-library SGD variant (first column = label, replaced by intercept).

Here the whole optimization — sigmoid margin, gradient mat-vec, 1/√i step decay
— is a jitted ``lax.fori_loop``: zero host round-trips for the entire run, with
the gradient all-reduce scheduled by XLA over the row-sharded data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["logistic_regression", "LogisticRegressionModel"]


@functools.partial(jax.jit, static_argnames=("iters",))
def _lr_fori(feats, labels, step_size, iters: int):
    m = feats.shape[0]

    def body(i, w):
        margin = -(feats @ w)
        mul = 1.0 / (1.0 + jnp.exp(margin)) - labels
        grad = feats.T @ mul
        scale = step_size / m / jnp.sqrt(i.astype(feats.dtype) + 1.0)
        return w - grad * scale

    w0 = jnp.zeros((feats.shape[1],), feats.dtype)
    return jax.lax.fori_loop(0, iters, body, w0)


class LogisticRegressionModel:
    def __init__(self, weights: np.ndarray):
        self.weights = weights  # [intercept, w1, ..., wd]

    def predict_proba(self, x) -> np.ndarray:
        x = np.asarray(x)
        z = self.weights[0] + x @ self.weights[1:]
        return 1.0 / (1.0 + np.exp(-z))

    def predict(self, x) -> np.ndarray:
        return (self.predict_proba(x) > 0.5).astype(np.int32)


def logistic_regression(data, step_size: float = 1.0, iterations: int = 100
                        ) -> LogisticRegressionModel:
    """Train on a DenseVecMatrix whose rows are ``(label, features...)``
    (the DenseVecMatrix.lr contract). Returns the fitted model."""
    arr = data.logical() if hasattr(data, "logical") else jnp.asarray(data)
    m = arr.shape[0]
    labels = arr[:, 0]
    feats = jnp.concatenate([jnp.ones((m, 1), arr.dtype), arr[:, 1:]], axis=1)
    w = _lr_fori(feats, labels, jnp.asarray(step_size, arr.dtype), int(iterations))
    return LogisticRegressionModel(np.asarray(jax.device_get(w)))
