from .als import als_run, ALSModel  # noqa: F401
from .neural_network import NeuralNetwork, mlp_init, mlp_forward, train_step  # noqa: F401
from .logistic_regression import logistic_regression, LogisticRegressionModel  # noqa: F401
from .pagerank import (pagerank, build_transition_matrix,  # noqa: F401
                       build_transition_operator, TransitionOperator)
