from .als import als_run, ALSModel  # noqa: F401
