"""Alternating least squares on explicit ratings.

The reference ports the old MLlib blocked ALS (ml/ALSHelp.scala): user/product
factor blocks with in/out link tables, a message-passing shuffle per half-
iteration (outlinks → messages → join inlinks, ALSHelp.scala:263-286), per-user
normal equations accumulated with BLAS dspr (:236-254), solved via an explicit
``inv(AᵀA)`` (:388-392 — a numerical weakness SURVEY.md §7 flags to fix).

TPU-first there are no link tables and no shuffles: factors are dense sharded
(num_users × rank) / (num_items × rank) arrays; for each half-step the rated
items' factors are *gathered* by index (XLA turns cross-shard gathers into
collectives), per-rating outer products ``v vᵀ`` are accumulated per user with
``segment_sum`` (the dspr loop, vectorized), and the per-user rank×rank normal
equations are solved batched with ``jnp.linalg.solve`` — not an explicit
inverse. One whole ALS sweep is a single jitted program.

Supports the regularization modes of the reference: plain λ and
weighted-λ (``alpha``-free explicit ALS-WR scaling by each user's rating count,
ALSHelp.scala:57-60 implicitPrefs=false path).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.compat import pcast, shard_map, vma_of

__all__ = ["als_run", "ALSModel"]


@dataclasses.dataclass
class ALSModel:
    user_features: object  # DenseVecMatrix (num_users × rank)
    product_features: object  # DenseVecMatrix (num_items × rank)

    def predict(self, users, items) -> jax.Array:
        u = self.user_features.logical()
        v = self.product_features.logical()
        return jnp.sum(u[jnp.asarray(users)] * v[jnp.asarray(items)], axis=1)

    def rmse(self, coo) -> float:
        pred = self.predict(coo.row_indices, coo.col_indices)
        err = pred - coo.values
        return float(jnp.sqrt(jnp.mean(err * err)))


def _chunked_segment_stats(factors_other, seg_ids, other_ids, ratings,
                           num_segments, weight=None, chunk: int | None = None):
    """Accumulate per-segment XᵀX / Xᵀy / counts over nnz in bounded chunks:
    the (chunk, rank, rank) outer-product tensor never materializes beyond a
    fixed element budget, so huge rating sets (the MEMORY_AND_DISK link tables
    of the reference, ALSHelp.scala:32) stay in HBM."""
    nnz = ratings.shape[0]
    rank = factors_other.shape[1]
    if chunk is None:
        # ~64 MB f32 of outer-product tensor per chunk regardless of rank
        chunk = max(1, (1 << 24) // (rank * rank))
    chunk = max(1, min(chunk, nnz))
    n_chunks = max(1, -(-nnz // chunk))
    pad = n_chunks * chunk - nnz
    if pad:
        seg_ids = jnp.pad(seg_ids, (0, pad), constant_values=num_segments)
        other_ids = jnp.pad(other_ids, (0, pad))
        ratings = jnp.pad(ratings, (0, pad))
        if weight is not None:
            weight = jnp.pad(weight, (0, pad))
    dt = factors_other.dtype

    def body(carry, idx):
        xtx, xty, counts = carry
        s = seg_ids[idx]
        vt = factors_other[other_ids[idx]]
        r = ratings[idx]
        w = weight[idx] if weight is not None else jnp.ones_like(r)
        outer = vt[:, :, None] * vt[:, None, :] * w[:, None, None]
        # the extra segment (num_segments) swallows the padding entries
        xtx = xtx + jax.ops.segment_sum(outer, s, num_segments + 1)
        xty = xty + jax.ops.segment_sum(vt * r[:, None], s, num_segments + 1)
        counts = counts + jax.ops.segment_sum(jnp.ones_like(r), s, num_segments + 1)
        return (xtx, xty, counts), None

    init = (
        jnp.zeros((num_segments + 1, rank, rank), dt),
        jnp.zeros((num_segments + 1, rank), dt),
        jnp.zeros((num_segments + 1,), dt),
    )
    # inside shard_map the data is varying over the mesh axes; the scan carry
    # init must carry the same varying-manual-axes type
    vma = tuple(vma_of(ratings))
    if vma:
        init = tuple(pcast(x, vma, to="varying") for x in init)
    idxs = jnp.arange(n_chunks * chunk).reshape(n_chunks, chunk)
    (xtx, xty, counts), _ = jax.lax.scan(body, init, idxs)
    return xtx[:num_segments], xty[:num_segments], counts[:num_segments]


def _solve_explicit_stats(xtx, xty, counts, lam, weighted):
    """Batched regularized normal-equation solve from accumulated stats —
    ``jnp.linalg.solve``, not the reference's explicit ``inv(AᵀA)``
    (ALSHelp.scala:388-392)."""
    reg = lam * (counts[:, None] if weighted else jnp.ones_like(counts)[:, None])
    eye = jnp.eye(xtx.shape[-1], dtype=xtx.dtype)
    a = xtx + reg[:, :, None] * eye
    # rows with no ratings keep a well-posed system (identity) and get 0
    sol = jnp.linalg.solve(a, xty[..., None])[..., 0]
    return jnp.where(counts[:, None] > 0, sol, jnp.zeros_like(sol))


def _solve_implicit_stats(yty, corr, rhs, counts, lam):
    eye = jnp.eye(yty.shape[0], dtype=yty.dtype)
    a = yty[None] + corr + lam * eye[None]
    sol = jnp.linalg.solve(a, rhs[..., None])[..., 0]
    return jnp.where(counts[:, None] > 0, sol, jnp.zeros_like(sol))


@functools.partial(jax.jit, static_argnames=("num_segments", "weighted"))
def _solve_side(factors_other, seg_ids, other_ids, ratings, rank, lam,
                num_segments, weighted):
    """One explicit half-step: recompute `num_segments` factor rows from the
    fixed other side. seg_ids: which row each rating belongs to; other_ids:
    which fixed factor it references. Normal-equation stats accumulate in
    nnz chunks (the vectorized dspr loop, ALSHelp.scala:292-382)."""
    xtx, xty, counts = _chunked_segment_stats(
        factors_other, seg_ids, other_ids, ratings, num_segments
    )
    return _solve_explicit_stats(xtx, xty, counts, lam, weighted)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _solve_side_implicit(factors_other, seg_ids, other_ids, ratings, lam, alpha,
                         num_segments):
    """One implicit-feedback half-step (Hu/Koren/Volinsky; the role of the
    reference's implicitPrefs path with its computeYtY precompute,
    ALSHelp.scala:188-200, 292-382): solve
    (YᵀY + Yᵀ(C−I)Y + λI) x = Yᵀ C p  per row, with the dense YᵀY computed
    once globally and only the (c−1)-weighted corrections segment-summed."""
    yty = jnp.dot(factors_other.T, factors_other, precision="highest")
    conf_minus_1 = alpha * ratings  # c = 1 + alpha*r
    # chunked accumulation: corr = Σ (c−1)·v vᵀ, rhs = Σ c·p·v (p = 1 observed)
    corr, rhs, counts = _chunked_segment_stats(
        factors_other, seg_ids, other_ids, 1.0 + conf_minus_1,
        num_segments, weight=conf_minus_1,
    )
    return _solve_implicit_stats(yty, corr, rhs, counts, lam)


def _block_ratings_by_segment(seg_ids, other_ids, vals, num_segments,
                              n_dev: int, block: int):
    """Host-side prep for the sharded path: sort ratings by owning segment and
    pack them into a dense ``(total_blocks, max_nnz)`` layout where block ``b``
    holds exactly the ratings of segments ``[b·block, (b+1)·block)``. Device
    ``d`` then owns a contiguous run of blocks — this replaces the reference's
    in/out link tables + HashPartitioner shuffle (ALSHelp.scala:101-165) with a
    static layout XLA can scan without any data-dependent control flow.

    Padding entries carry segment id ``block`` (the swallow segment of
    ``_chunked_segment_stats``) and rating 0. The packed size is
    ``total_blocks · max_nnz`` where ``max_nnz`` is the fullest block — fine
    for near-uniform rating distributions; a pathologically hot segment block
    inflates padding, in which case lower ``segment_block``."""
    seg = np.asarray(seg_ids)
    oth = np.asarray(other_ids)
    val = np.asarray(vals)
    segs_per_dev = -(-num_segments // (n_dev * block)) * block
    padded_segments = segs_per_dev * n_dev
    total_blocks = padded_segments // block
    order = np.argsort(seg, kind="stable")
    seg, oth, val = seg[order], oth[order], val[order]
    blk = seg // block
    counts = np.bincount(blk, minlength=total_blocks).astype(np.int64)
    max_nnz = -(-max(int(counts.max()), 8) // 8) * 8
    starts = np.cumsum(counts) - counts
    pos = np.arange(seg.shape[0]) - starts[blk]
    sid = np.full((total_blocks, max_nnz), block, np.int32)
    oid = np.zeros((total_blocks, max_nnz), np.int32)
    v = np.zeros((total_blocks, max_nnz), np.float32)
    sid[blk, pos] = (seg % block).astype(np.int32)
    oid[blk, pos] = oth.astype(np.int32)
    v[blk, pos] = val.astype(np.float32)
    return sid, oid, v, padded_segments


@functools.partial(jax.jit,
                   static_argnames=("mesh", "block", "weighted", "implicit"))
def _solve_side_sharded(factors_other, blk_sid, blk_oid, blk_val, lam, alpha,
                        *, mesh, block, weighted, implicit):
    """One sharded half-step. The updated side's segment axis is sharded over
    *all* mesh devices (each device owns a contiguous run of segment blocks and
    solves only those), so the ``(segments, rank, rank)`` stat tensor never
    materializes beyond one ``segment_block`` per device. The fixed other side
    arrives replicated — the shard_map in_spec ``P()`` makes GSPMD insert the
    all-gather, which is this design's entire communication (the analog of the
    reference's outlinks→messages shuffle, ALSHelp.scala:263-286)."""
    axes = tuple(mesh.axis_names)
    spec_b = P(axes, None)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), spec_b, spec_b, spec_b, P(), P()),
        out_specs=spec_b,
    )
    def run(other, sid, oid, val, lam_, alpha_):
        yty = (jnp.dot(other.T, other, precision="highest")
               if implicit else None)

        def body(_, xs):
            s, o, r = xs
            if implicit:
                cm1 = alpha_ * r
                corr, rhs, counts = _chunked_segment_stats(
                    other, s, o, 1.0 + cm1, block, weight=cm1)
                sol = _solve_implicit_stats(yty, corr, rhs, counts, lam_)
            else:
                xtx, xty, counts = _chunked_segment_stats(other, s, o, r, block)
                sol = _solve_explicit_stats(xtx, xty, counts, lam_, weighted)
            return None, sol

        _, out = jax.lax.scan(body, None, (sid, oid, val))
        return out.reshape(-1, out.shape[-1])

    return run(factors_other, blk_sid, blk_oid, blk_val, lam, alpha)


def als_run(ratings, rank: int, iterations: int = 10, lam: float = 0.01,
            seed: int = 0, weighted_lambda: bool = True, mesh=None,
            implicit_prefs: bool = False, alpha: float = 1.0,
            num_user_blocks: int = -1, num_product_blocks: int = -1,
            shard: bool | None = None, segment_block: int = 4096) -> ALSModel:
    """Run blocked ALS (ALSHelp.ALSRun, ml/ALSHelp.scala:34-96).

    ``ratings`` is a CoordinateMatrix of (user, product, rating). Factors are
    initialized on the unit sphere like ``randomFactor`` (ALSHelp.scala:170-179).
    ``implicit_prefs``/``alpha`` select the implicit-feedback formulation, the
    same switch ALSRun takes (ALSHelp.scala:33-34). ``num_user_blocks``/
    ``num_product_blocks`` are accepted for signature parity but ignored:
    blocking was the reference's shuffle-partitioning knob, and factor layout
    here is governed by the mesh sharding instead.

    ``shard`` selects the blocked solver (segment axes of the factor matrices
    and stat accumulators sharded over all devices, the fixed side
    all-gathered per half-step) — the scale path matching the reference's
    MEMORY_AND_DISK blocked design (ALSHelp.scala:32, 263-286). On a single
    device it is the bounded-memory mode: stats materialize one
    ``segment_block`` at a time instead of ``(num_segments, rank, rank)`` at
    once, which is what lets reference-scale rating sets (10⁶+ users) fit one
    chip's HBM. ``None`` auto-enables it when the full stat tensor of either
    side would exceed 256 MB. ``segment_block`` is the per-device solve
    granularity.
    """
    del num_user_blocks, num_product_blocks
    from ..matrix.dense import DenseVecMatrix

    mesh = mesh or ratings.mesh
    num_users, num_items = ratings.shape
    # jit-produced ratings may carry BCOO padding (indices == shape); padded
    # entries would be clip-gathered into wrong segments. Detect with two
    # device-side scalar reduces so the clean (reference-scale) case never
    # pays a host round-trip of the full entry arrays
    if ratings.nnz and (int(jnp.max(ratings.row_indices)) >= num_users
                        or int(jnp.max(ratings.col_indices)) >= num_items):
        ratings = ratings.compact()
    users = jnp.asarray(ratings.row_indices, jnp.int32)
    items = jnp.asarray(ratings.col_indices, jnp.int32)
    vals = jnp.asarray(ratings.values, jnp.float32)

    key_u, key_v = jax.random.split(jax.random.key(seed))
    u = jax.random.normal(key_u, (num_users, rank), jnp.float32)
    u = jnp.abs(u) / jnp.linalg.norm(u, axis=1, keepdims=True)
    v = jax.random.normal(key_v, (num_items, rank), jnp.float32)
    v = jnp.abs(v) / jnp.linalg.norm(v, axis=1, keepdims=True)

    n_dev = int(np.prod(list(mesh.shape.values())))
    if shard is None:
        # blocked mode whenever the full stat tensor is HBM-hostile — on ANY
        # device count (the single-chip ALS bench config needs 31 GB of stats
        # through the unsharded path; blocked, it needs one segment block)
        stat_bytes = 4 * rank * rank * max(num_users, num_items)
        shard = stat_bytes > (1 << 28)

    if shard:
        u, v = _als_sharded(mesh, u, v, users, items, vals, num_users,
                            num_items, iterations, lam, alpha, weighted_lambda,
                            implicit_prefs, segment_block, n_dev)
    else:
        for _ in range(iterations):
            # products fixed -> update users, then users fixed -> update products
            if implicit_prefs:
                u = _solve_side_implicit(v, users, items, vals, lam, alpha, num_users)
                v = _solve_side_implicit(u, items, users, vals, lam, alpha, num_items)
            else:
                u = _solve_side(v, users, items, vals, rank, lam, num_users, weighted_lambda)
                v = _solve_side(u, items, users, vals, rank, lam, num_items, weighted_lambda)

    return ALSModel(
        DenseVecMatrix.from_array(u, mesh),
        DenseVecMatrix.from_array(v, mesh),
    )


def _als_sharded(mesh, u, v, users, items, vals, num_users, num_items,
                 iterations, lam, alpha, weighted_lambda, implicit_prefs,
                 segment_block, n_dev):
    """Drive the sharded half-steps: pack both rating orientations once
    (user-sorted for the user update, item-sorted for the item update), place
    the packed blocks and the factor matrices sharded over the whole mesh, and
    alternate jitted half-steps. Factors stay padded/sharded across the loop;
    the slice back to logical size happens once at the end."""
    axes = tuple(mesh.axis_names)
    spec_b = NamedSharding(mesh, P(axes, None))
    block = max(8, min(segment_block, -(-max(num_users, num_items) // n_dev)))

    users_np, items_np, vals_np = (np.asarray(users), np.asarray(items),
                                   np.asarray(vals))
    u_sid, u_oid, u_val, pad_users = _block_ratings_by_segment(
        users_np, items_np, vals_np, num_users, n_dev, block)
    v_sid, v_oid, v_val, pad_items = _block_ratings_by_segment(
        items_np, users_np, vals_np, num_items, n_dev, block)
    u_sid, u_oid, u_val, v_sid, v_oid, v_val = (
        jax.device_put(x, spec_b)
        for x in (u_sid, u_oid, u_val, v_sid, v_oid, v_val))

    u = jax.device_put(jnp.pad(u, ((0, pad_users - num_users), (0, 0))), spec_b)
    v = jax.device_put(jnp.pad(v, ((0, pad_items - num_items), (0, 0))), spec_b)
    lam = jnp.float32(lam)
    alpha = jnp.float32(alpha)
    for _ in range(iterations):
        u = _solve_side_sharded(v, u_sid, u_oid, u_val, lam, alpha, mesh=mesh,
                                block=block, weighted=weighted_lambda,
                                implicit=implicit_prefs)
        v = _solve_side_sharded(u, v_sid, v_oid, v_val, lam, alpha, mesh=mesh,
                                block=block, weighted=weighted_lambda,
                                implicit=implicit_prefs)
    return u[:num_users], v[:num_items]
