"""Alternating least squares on explicit ratings.

The reference ports the old MLlib blocked ALS (ml/ALSHelp.scala): user/product
factor blocks with in/out link tables, a message-passing shuffle per half-
iteration (outlinks → messages → join inlinks, ALSHelp.scala:263-286), per-user
normal equations accumulated with BLAS dspr (:236-254), solved via an explicit
``inv(AᵀA)`` (:388-392 — a numerical weakness SURVEY.md §7 flags to fix).

TPU-first there are no link tables and no shuffles: factors are dense sharded
(num_users × rank) / (num_items × rank) arrays; for each half-step the rated
items' factors are *gathered* by index (XLA turns cross-shard gathers into
collectives), per-rating outer products ``v vᵀ`` are accumulated per user with
``segment_sum`` (the dspr loop, vectorized), and the per-user rank×rank normal
equations are solved batched with ``jnp.linalg.solve`` — not an explicit
inverse. One whole ALS sweep is a single jitted program.

Supports the regularization modes of the reference: plain λ and
weighted-λ (``alpha``-free explicit ALS-WR scaling by each user's rating count,
ALSHelp.scala:57-60 implicitPrefs=false path).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

__all__ = ["als_run", "ALSModel"]


@dataclasses.dataclass
class ALSModel:
    user_features: object  # DenseVecMatrix (num_users × rank)
    product_features: object  # DenseVecMatrix (num_items × rank)

    def predict(self, users, items) -> jax.Array:
        u = self.user_features.logical()
        v = self.product_features.logical()
        return jnp.sum(u[jnp.asarray(users)] * v[jnp.asarray(items)], axis=1)

    def rmse(self, coo) -> float:
        pred = self.predict(coo.row_indices, coo.col_indices)
        err = pred - coo.values
        return float(jnp.sqrt(jnp.mean(err * err)))


def _chunked_segment_stats(factors_other, seg_ids, other_ids, ratings,
                           num_segments, weight=None, chunk: int | None = None):
    """Accumulate per-segment XᵀX / Xᵀy / counts over nnz in bounded chunks:
    the (chunk, rank, rank) outer-product tensor never materializes beyond a
    fixed element budget, so huge rating sets (the MEMORY_AND_DISK link tables
    of the reference, ALSHelp.scala:32) stay in HBM."""
    nnz = ratings.shape[0]
    rank = factors_other.shape[1]
    if chunk is None:
        # ~64 MB f32 of outer-product tensor per chunk regardless of rank
        chunk = max(1, (1 << 24) // (rank * rank))
    chunk = max(1, min(chunk, nnz))
    n_chunks = max(1, -(-nnz // chunk))
    pad = n_chunks * chunk - nnz
    if pad:
        seg_ids = jnp.pad(seg_ids, (0, pad), constant_values=num_segments)
        other_ids = jnp.pad(other_ids, (0, pad))
        ratings = jnp.pad(ratings, (0, pad))
        if weight is not None:
            weight = jnp.pad(weight, (0, pad))
    dt = factors_other.dtype

    def body(carry, idx):
        xtx, xty, counts = carry
        s = seg_ids[idx]
        vt = factors_other[other_ids[idx]]
        r = ratings[idx]
        w = weight[idx] if weight is not None else jnp.ones_like(r)
        outer = vt[:, :, None] * vt[:, None, :] * w[:, None, None]
        # the extra segment (num_segments) swallows the padding entries
        xtx = xtx + jax.ops.segment_sum(outer, s, num_segments + 1)
        xty = xty + jax.ops.segment_sum(vt * r[:, None], s, num_segments + 1)
        counts = counts + jax.ops.segment_sum(jnp.ones_like(r), s, num_segments + 1)
        return (xtx, xty, counts), None

    init = (
        jnp.zeros((num_segments + 1, rank, rank), dt),
        jnp.zeros((num_segments + 1, rank), dt),
        jnp.zeros((num_segments + 1,), dt),
    )
    idxs = jnp.arange(n_chunks * chunk).reshape(n_chunks, chunk)
    (xtx, xty, counts), _ = jax.lax.scan(body, init, idxs)
    return xtx[:num_segments], xty[:num_segments], counts[:num_segments]


@functools.partial(jax.jit, static_argnames=("num_segments", "weighted"))
def _solve_side(factors_other, seg_ids, other_ids, ratings, rank, lam,
                num_segments, weighted):
    """One explicit half-step: recompute `num_segments` factor rows from the
    fixed other side. seg_ids: which row each rating belongs to; other_ids:
    which fixed factor it references. Normal-equation stats accumulate in
    nnz chunks (the vectorized dspr loop, ALSHelp.scala:292-382)."""
    xtx, xty, counts = _chunked_segment_stats(
        factors_other, seg_ids, other_ids, ratings, num_segments
    )
    reg = lam * (counts[:, None] if weighted else jnp.ones_like(counts)[:, None])
    eye = jnp.eye(xtx.shape[-1], dtype=xtx.dtype)
    a = xtx + reg[:, :, None] * eye
    # rows with no ratings keep a well-posed system (identity) and get 0
    b = xty
    sol = jnp.linalg.solve(a, b[..., None])[..., 0]
    return jnp.where(counts[:, None] > 0, sol, jnp.zeros_like(sol))


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _solve_side_implicit(factors_other, seg_ids, other_ids, ratings, lam, alpha,
                         num_segments):
    """One implicit-feedback half-step (Hu/Koren/Volinsky; the role of the
    reference's implicitPrefs path with its computeYtY precompute,
    ALSHelp.scala:188-200, 292-382): solve
    (YᵀY + Yᵀ(C−I)Y + λI) x = Yᵀ C p  per row, with the dense YᵀY computed
    once globally and only the (c−1)-weighted corrections segment-summed."""
    yty = jnp.dot(factors_other.T, factors_other, precision="highest")
    conf_minus_1 = alpha * ratings  # c = 1 + alpha*r
    # chunked accumulation: corr = Σ (c−1)·v vᵀ, rhs = Σ c·p·v (p = 1 observed)
    corr, rhs, counts = _chunked_segment_stats(
        factors_other, seg_ids, other_ids, 1.0 + conf_minus_1,
        num_segments, weight=conf_minus_1,
    )
    eye = jnp.eye(yty.shape[0], dtype=yty.dtype)
    a = yty[None] + corr + lam * eye[None]
    sol = jnp.linalg.solve(a, rhs[..., None])[..., 0]
    return jnp.where(counts[:, None] > 0, sol, jnp.zeros_like(sol))


def als_run(ratings, rank: int, iterations: int = 10, lam: float = 0.01,
            seed: int = 0, weighted_lambda: bool = True, mesh=None,
            implicit_prefs: bool = False, alpha: float = 1.0,
            num_user_blocks: int = -1, num_product_blocks: int = -1) -> ALSModel:
    """Run blocked ALS (ALSHelp.ALSRun, ml/ALSHelp.scala:34-96).

    ``ratings`` is a CoordinateMatrix of (user, product, rating). Factors are
    initialized on the unit sphere like ``randomFactor`` (ALSHelp.scala:170-179).
    ``implicit_prefs``/``alpha`` select the implicit-feedback formulation, the
    same switch ALSRun takes (ALSHelp.scala:33-34). ``num_user_blocks``/
    ``num_product_blocks`` are accepted for signature parity but ignored:
    blocking was the reference's shuffle-partitioning knob, and factor layout
    here is governed by the mesh sharding instead.
    """
    del num_user_blocks, num_product_blocks
    from ..matrix.dense import DenseVecMatrix

    mesh = mesh or ratings.mesh
    num_users, num_items = ratings.shape
    users = jnp.asarray(ratings.row_indices, jnp.int32)
    items = jnp.asarray(ratings.col_indices, jnp.int32)
    vals = jnp.asarray(ratings.values, jnp.float32)

    key_u, key_v = jax.random.split(jax.random.key(seed))
    u = jax.random.normal(key_u, (num_users, rank), jnp.float32)
    u = jnp.abs(u) / jnp.linalg.norm(u, axis=1, keepdims=True)
    v = jax.random.normal(key_v, (num_items, rank), jnp.float32)
    v = jnp.abs(v) / jnp.linalg.norm(v, axis=1, keepdims=True)

    for _ in range(iterations):
        # products fixed -> update users, then users fixed -> update products
        if implicit_prefs:
            u = _solve_side_implicit(v, users, items, vals, lam, alpha, num_users)
            v = _solve_side_implicit(u, items, users, vals, lam, alpha, num_items)
        else:
            u = _solve_side(v, users, items, vals, rank, lam, num_users, weighted_lambda)
            v = _solve_side(u, items, users, vals, rank, lam, num_items, weighted_lambda)

    return ALSModel(
        DenseVecMatrix.from_array(u, mesh),
        DenseVecMatrix.from_array(v, mesh),
    )
