"""Two-layer MLP on sharded data — the reference's NeuralNetwork workload.

The reference trains a 2-layer sigmoid MLP on MNIST with *block-sampled*
mini-batch SGD (examples/NeuralNetwork.scala): the driver picks a random subset
of resident blocks per iteration (:93-105), forward is per-block ``(block·W)·σ``
with driver-held weights captured in closures (:221-231, an implicit broadcast
per iteration), backprop is hand-rolled (output error :119-128, layer error
:137-144, delta :152-162), and the weight update is a ``treeReduce`` of
per-block gradients back to the driver (:171-183).

TPU-first inversions:
- the whole step (sample → forward → backward → update) is ONE jitted SPMD
  program; weights live *on device*, replicated over the mesh — there is no
  driver round-trip per iteration at all;
- backprop is ``jax.grad`` of the loss, not hand-derived formulas;
- ``treeReduce`` to the driver becomes the all-reduce XLA inserts when the
  sharded batch's gradients contract into replicated weight updates;
- block sampling becomes strided row sampling: a random offset plus a stride
  walks the row-sharded data so every device contributes equally to each batch
  (the co-location that NeuralNetworkPartitioner provides in the reference,
  examples/NeuralNetwork.scala:266-289, holds by construction since data and
  labels share one sharding).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..mesh import ROWS, default_mesh

__all__ = ["NeuralNetwork", "mlp_init", "mlp_forward", "mlp_loss", "train_step",
           "train_step_optax"]


def mlp_init(key, layer_sizes: tuple[int, ...], dtype=jnp.float32) -> dict:
    """Glorot-uniform weight init. The reference uses a fixed ±0.05 uniform
    (examples/NeuralNetwork.scala:205-206) — nearly the same scale for its
    2-layer 784→100→10 shape, but fan-scaled init keeps gradients alive when
    ``layer_sizes`` goes deeper than the reference ever does."""
    params = {}
    keys = jax.random.split(key, len(layer_sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(layer_sizes[:-1], layer_sizes[1:])):
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        params[f"w{i}"] = jax.random.uniform(
            keys[i], (fan_in, fan_out), dtype, minval=-limit, maxval=limit
        )
    return params


def mlp_forward(params: dict, x: jax.Array, activation: str = "sigmoid") -> jax.Array:
    """σ(…σ(x·W0)·W1…) — the per-block forward (:221-231), whole-batch.
    ``activation`` applies to hidden layers ("sigmoid" is the reference's
    choice and the default; "relu" keeps gradients alive in deep stacks);
    the output layer is always sigmoid, matching the reference's output-error
    convention."""
    activations = {"sigmoid": jax.nn.sigmoid, "relu": jax.nn.relu,
                   "tanh": jnp.tanh}
    if activation not in activations:
        raise ValueError(
            f"unknown activation {activation!r}; choose from {sorted(activations)}"
        )
    act = activations[activation]
    h = x
    n_layers = len(params)
    for i in range(n_layers):
        z = h @ params[f"w{i}"]
        h = jax.nn.sigmoid(z) if i == n_layers - 1 else act(z)
    return h


def mlp_loss(params: dict, x: jax.Array, y: jax.Array,
             activation: str = "sigmoid") -> jax.Array:
    """Squared-error loss matching the reference's output-error convention
    (computeOutputError, examples/NeuralNetwork.scala:119-128)."""
    out = mlp_forward(params, x, activation)
    return 0.5 * jnp.mean(jnp.sum((out - y) ** 2, axis=-1))


def _sampled_loss_and_grads(params, x, y, key, batch_size, remat, activation):
    """Shared core of both step variants: strided batch sample + grad."""
    m = x.shape[0]
    stride = max(1, m // batch_size)
    offset = jax.random.randint(key, (), 0, m)
    idx = (offset + jnp.arange(batch_size) * stride) % m
    xb, yb = x[idx], y[idx]

    def loss_with_act(p, xx, yy):
        return mlp_loss(p, xx, yy, activation)

    loss_fn = jax.checkpoint(loss_with_act) if remat else loss_with_act
    return jax.value_and_grad(loss_fn)(params, xb, yb)


@functools.partial(jax.jit, static_argnames=("batch_size", "lr", "remat", "activation"))
def train_step(params, x, y, key, batch_size: int, lr: float, remat: bool = False,
               activation: str = "sigmoid"):
    """One SPMD step: strided batch sample + grad + SGD update (the
    reference's plain update, examples/NeuralNetwork.scala:244-248).
    ``remat=True`` rematerializes the forward in the backward pass
    (``jax.checkpoint``) — trading FLOPs for activation memory, the knob for
    models/batches near the HBM limit."""
    loss, grads = _sampled_loss_and_grads(params, x, y, key, batch_size,
                                          remat, activation)
    new_params = jax.tree.map(lambda w, g: w - lr * g, params, grads)
    return new_params, loss


def _build_tx(optimizer: str, lr: float, momentum: float):
    """optax transform from plain config values. Called both outside jit (for
    ``tx.init``) and inside the jitted step — keying the step's static args on
    ``(optimizer, lr, momentum)`` primitives means identical configs share one
    compiled program, where a GradientTransformation object per instance would
    retrace every time."""
    import optax

    if optimizer == "sgd":
        return optax.sgd(lr)
    if optimizer == "momentum":
        return optax.sgd(lr, momentum=momentum)
    if optimizer == "adam":
        return optax.adam(lr)
    raise ValueError(
        f"unknown optimizer {optimizer!r} (one of 'sgd', 'momentum', 'adam')"
    )


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "optimizer", "lr", "momentum", "remat", "activation"))
def train_step_optax(params, opt_state, x, y, key, batch_size: int,
                     optimizer: str, lr: float, momentum: float = 0.9,
                     remat: bool = False, activation: str = "sigmoid"):
    """The optimizer-parameterized step: optax momentum/adam instead of the
    reference's plain SGD. Same sampling and grad core; the update rule is
    the only difference."""
    import optax

    loss, grads = _sampled_loss_and_grads(params, x, y, key, batch_size,
                                          remat, activation)
    tx = _build_tx(optimizer, lr, momentum)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


@dataclasses.dataclass
class NeuralNetwork:
    """User-facing trainer mirroring the reference CLI's knobs
    (examples/NeuralNetwork.scala:186-208: layer sizes, iterations, step size,
    batch fraction). The reference is fixed at two layers; ``hidden_dim`` may
    be an int (that case) or a tuple for arbitrary depth."""

    input_dim: int = 784
    hidden_dim: int | tuple[int, ...] = 100
    output_dim: int = 10
    learning_rate: float = 0.5
    seed: int = 0
    remat: bool = False  # jax.checkpoint the forward (memory for FLOPs)
    activation: str = "sigmoid"  # hidden activation; "relu" for deep stacks
    optimizer: str = "sgd"  # "sgd" (reference parity) | "momentum" | "adam"
    momentum: float = 0.9  # used by optimizer="momentum"

    @property
    def layer_sizes(self) -> tuple[int, ...]:
        hidden = (
            (self.hidden_dim,) if isinstance(self.hidden_dim, int) else tuple(self.hidden_dim)
        )
        return (self.input_dim, *hidden, self.output_dim)

    def init_params(self, mesh=None, dtype=jnp.float32) -> dict:
        mesh = mesh or default_mesh()
        params = mlp_init(jax.random.key(self.seed), self.layer_sizes, dtype)
        repl = NamedSharding(mesh, P())
        return jax.tree.map(lambda w: jax.device_put(w, repl), params)


    def train(
        self,
        data,
        labels,
        iterations: int = 100,
        batch_size: int = 256,
        params: dict | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 0,
        log_every: int = 0,
        opt_state=None,
    ):
        """Train; ``data`` is a DenseVecMatrix/BlockMatrix (rows = examples),
        ``labels`` an (m,) int vector (DistributedIntVector/array) one-hot
        encoded internally, like the reference's label chunks
        (examples/NeuralNetwork.scala:64-84). Returns (params, losses).

        With a non-SGD ``optimizer``, mid-training checkpoints hold
        ``{"params": ..., "opt_state": ...}`` (optimizer moments must survive
        a restart — a resume that resets Adam's moments spikes the loss), the
        final optimizer state is left on ``self.last_opt_state``, and
        ``opt_state`` lets a resumed run pass it back in."""
        from ..io.checkpoint import save_checkpoint
        from ..matrix.vector import DistributedVector

        if self.optimizer != "sgd":
            _build_tx(self.optimizer, self.learning_rate, self.momentum)  # validate
        mesh = getattr(data, "mesh", None) or default_mesh()
        x = data.logical() if hasattr(data, "logical") else jnp.asarray(data)
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(ROWS, None)))
        if isinstance(labels, DistributedVector):
            labels = labels.logical()
        labels = jnp.asarray(labels)
        y = (
            jax.nn.one_hot(labels, self.output_dim, dtype=x.dtype)
            if labels.ndim == 1
            else labels
        )
        params = params if params is not None else self.init_params(mesh, x.dtype)
        batch_size = min(batch_size, x.shape[0])
        losses = []
        key = jax.random.key(self.seed + 1)
        use_optax = self.optimizer != "sgd"
        if use_optax and opt_state is None:
            opt_state = _build_tx(self.optimizer, self.learning_rate,
                                  self.momentum).init(params)
        for it in range(iterations):
            key, sub = jax.random.split(key)
            if not use_optax:
                params, loss = train_step(
                    params, x, y, sub, batch_size, self.learning_rate,
                    self.remat, self.activation,
                )
            else:
                params, opt_state, loss = train_step_optax(
                    params, opt_state, x, y, sub, batch_size, self.optimizer,
                    self.learning_rate, self.momentum, self.remat,
                    self.activation,
                )
            if log_every and (it + 1) % log_every == 0:
                print(f"iter {it + 1}: loss {float(loss):.6f}")
            losses.append(loss)
            if checkpoint_dir and checkpoint_every and (it + 1) % checkpoint_every == 0:
                state = (params if not use_optax
                         else {"params": params, "opt_state": opt_state})
                save_checkpoint(state, checkpoint_dir, it + 1)
        self.last_opt_state = opt_state
        return params, [float(l) for l in losses]

    def predict(self, params: dict, data) -> np.ndarray:
        x = data.logical() if hasattr(data, "logical") else jnp.asarray(data)
        return np.asarray(jax.device_get(
            jnp.argmax(mlp_forward(params, x, self.activation), axis=-1)))

    def accuracy(self, params: dict, data, labels) -> float:
        pred = self.predict(params, data)
        labels = np.asarray(
            labels.to_numpy() if hasattr(labels, "to_numpy") else labels
        )
        return float((pred == labels).mean())

    def save_weights(self, params: dict, path: str):
        """CSV weight dump like the reference's final save
        (examples/NeuralNetwork.scala:259-260)."""
        for name, w in params.items():
            np.savetxt(f"{path}.{name}.csv", np.asarray(jax.device_get(w)), delimiter=",")
