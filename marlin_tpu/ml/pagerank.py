"""PageRank as iterated distributed mat-vec.

The reference example (examples/PageRank.scala) builds a link matrix and
multiplies it against the rank vector per iteration (:46-58), one Spark job per
step. Here the link matrix is a (sparse or dense) sharded operand, the rank
vector is replicated, and the full power iteration runs as one jitted
``lax.fori_loop`` with XLA collectives inside — plus an optional convergence
threshold via ``lax.while_loop``.

Graph-scale input never densifies: :func:`build_transition_operator` keeps the
graph as (src, dst) edge arrays plus an out-degree table (the reference builds
its link matrix distributed from the edge file, examples/PageRank.scala:46-58),
and the iteration is gather + ``segment_sum`` over edges — the TPU-shaped SpMV
for unstructured graphs, optionally sharded over the edge axis of the mesh.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map

__all__ = ["pagerank", "build_transition_matrix", "build_transition_operator",
           "TransitionOperator"]


def build_transition_matrix(edges, n: int | None = None) -> np.ndarray:
    """Column-stochastic transition matrix from (src, dst) edge pairs.
    Dangling nodes get uniform columns."""
    edges = np.asarray(list(edges), dtype=np.int64)
    if edges.size == 0:
        raise ValueError("empty edge list")
    if n is None:
        n = int(edges.max()) + 1
    m = np.zeros((n, n), np.float32)
    np.add.at(m, (edges[:, 1], edges[:, 0]), 1.0)
    colsum = m.sum(axis=0)
    dangling = colsum == 0
    m[:, ~dangling] /= colsum[~dangling]
    m[:, dangling] = 1.0 / n
    return m


@dataclasses.dataclass
class TransitionOperator:
    """Column-stochastic link operator held in edge-list form: applying it to a
    rank vector is ``segment_sum(r[src]/outdeg[src], dst)`` plus the dangling
    mass spread uniformly — identical math to the dense
    :func:`build_transition_matrix` without the n×n materialization."""

    src: jax.Array  # (E,) int32
    dst: jax.Array  # (E,) int32
    inv_deg: jax.Array  # (n,) f32, 1/outdegree, 0 at dangling nodes
    dangling: jax.Array  # (n,) f32, 1.0 at dangling nodes
    n: int
    mesh: object | None = None
    weight: jax.Array | None = None  # (E,) f32 edge validity (sharded padding)

    @property
    def shape(self):
        return (self.n, self.n)

    @property
    def nnz(self):
        return int(self.src.shape[0])


def build_transition_operator(edges, n: int | None = None,
                              mesh=None) -> TransitionOperator:
    """Edge-list transition operator from (src, dst) pairs — the graph-scale
    input path (reference: examples/PageRank.scala:46-58 builds the link
    matrix distributed from the edge file). O(E + n) memory; duplicate edges
    weight like the dense builder (each contributes one out-link).

    ``edges`` is an (E, 2) array-like or iterable of pairs. With ``mesh`` the
    edge arrays are sharded over all mesh devices and the per-iteration
    scatter-reduce runs edge-parallel with a psum."""
    edges = np.asarray(edges if hasattr(edges, "ndim") else list(edges),
                       dtype=np.int64)
    if edges.size == 0:
        raise ValueError("empty edge list")
    edges = edges.reshape(-1, 2)
    if n is None:
        n = int(edges.max()) + 1
    deg = np.bincount(edges[:, 0], minlength=n).astype(np.float32)
    dangling = (deg == 0).astype(np.float32)
    inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0).astype(np.float32)
    # sort by destination once at build time: the per-iteration scatter-reduce
    # then runs with indices_are_sorted=True — on TPU an unsorted 10^8-update
    # scatter-add is pathologically slow, a sorted one is a linear pass
    order = np.argsort(edges[:, 1], kind="stable")
    src = edges[order, 0].astype(np.int32)
    dst = edges[order, 1].astype(np.int32)
    if mesh is not None:
        axes = tuple(mesh.axis_names)
        n_dev = int(np.prod([mesh.shape[a] for a in axes]))
        # pad the edge axis to the device count; padding edges carry weight 0
        # so they contribute nothing, and dst = n-1 keeps the axis dst-sorted
        pad = (-len(src)) % n_dev
        weight = np.ones(len(src) + pad, np.float32)
        if pad:
            src = np.concatenate([src, np.zeros(pad, np.int32)])
            dst = np.concatenate([dst, np.full(pad, n - 1, np.int32)])
            weight[-pad:] = 0.0
        espec = NamedSharding(mesh, P(axes))
        return TransitionOperator(
            jax.device_put(src, espec), jax.device_put(dst, espec),
            jnp.asarray(inv_deg), jnp.asarray(dangling), n, mesh,
            jax.device_put(weight, espec))
    return TransitionOperator(jnp.asarray(src), jnp.asarray(dst),
                              jnp.asarray(inv_deg), jnp.asarray(dangling), n)


def _pagerank_step(r, src, dst, weight, inv_deg, dangling, damping, n,
                   psum_axes=None):
    """One power-iteration step in edge form: gather per-edge contributions,
    scatter-reduce into destinations (segment_sum — the reduceByKey of
    examples/PageRank.scala:52), spread dangling mass uniformly."""
    contrib = (r * inv_deg)[src]
    if weight is not None:
        contrib = contrib * weight
    acc = jax.ops.segment_sum(contrib, dst, n, indices_are_sorted=True)
    if psum_axes:
        acc = jax.lax.psum(acc, psum_axes)
    d_mass = jnp.sum(r * dangling)
    r = damping * (acc + d_mass / n) + (1.0 - damping) / n
    return r / jnp.sum(r)


@functools.partial(jax.jit, static_argnames=("n", "iterations", "mesh"))
def _pagerank_edges(src, dst, weight, inv_deg, dangling, damping, n: int,
                    iterations: int, mesh=None):
    r0 = jnp.full((n,), 1.0 / n, jnp.float32)

    if mesh is None:
        def body(_, r):
            return _pagerank_step(r, src, dst, weight, inv_deg, dangling,
                                  damping, n)
        return jax.lax.fori_loop(0, iterations, body, r0)

    axes = tuple(mesh.axis_names)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P(), P(), P()),
        out_specs=P(),
    )
    def run(src_, dst_, w_, inv_deg_, dangling_, damping_):
        def body(_, r):
            return _pagerank_step(r, src_, dst_, w_, inv_deg_, dangling_,
                                  damping_, n, psum_axes=axes)
        # psum returns a mesh-invariant value, so the whole carry stays
        # invariant and the replicated out_spec holds by construction
        return jax.lax.fori_loop(0, iterations, body, r0)

    return run(src, dst, weight, inv_deg, dangling, damping)


@functools.partial(jax.jit, static_argnames=("iterations",))
def _pagerank_fori(m, damping, iterations: int):
    n = m.shape[0]
    r0 = jnp.full((n,), 1.0 / n, jnp.result_type(m.dtype, jnp.float32))

    def body(_, r):
        r = damping * (m @ r) + (1.0 - damping) / n
        return r / jnp.sum(r)

    return jax.lax.fori_loop(0, iterations, body, r0)


def pagerank(link_matrix, damping: float = 0.85, iterations: int = 20) -> np.ndarray:
    """Run power iteration. ``link_matrix`` is a DenseMatrix/SparseVecMatrix/
    array holding a column-stochastic transition matrix (use
    :func:`build_transition_matrix` to build one from an edge list), or a
    :class:`TransitionOperator` from :func:`build_transition_operator` for
    graph-scale edge lists that must never densify. Sparse operands stay
    sparse: the mat-vec inside the loop is a BCOO contraction / edge-parallel
    scatter-reduce."""
    from ..matrix.sparse import SparseVecMatrix

    if isinstance(link_matrix, TransitionOperator):
        op = link_matrix
        r = _pagerank_edges(op.src, op.dst, op.weight, op.inv_deg, op.dangling,
                            jnp.asarray(damping, jnp.float32), op.n,
                            int(iterations), op.mesh)
        return np.asarray(jax.device_get(r))
    if isinstance(link_matrix, SparseVecMatrix):
        arr = link_matrix.bcoo
    else:
        arr = link_matrix.logical() if hasattr(link_matrix, "logical") else jnp.asarray(link_matrix)
    r = _pagerank_fori(arr, jnp.asarray(damping, jnp.float32), int(iterations))
    return np.asarray(jax.device_get(r))
